"""Native (C++) index backend and hash-chain fast path.

ctypes bindings for ``csrc/kvindex``: a two-level-LRU index and the
FNV-64a/canonical-CBOR block-hash chain, both GIL-free. The NativeIndex
implements the same Index contract as the Python backends (shared contract
tests run over it); the hash fast path is used by ``ChunkedTokenDatabase``
for text-only blocks (multimodal-tainted blocks take the Python path).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

from ..utils.lockdep import new_lock
from ..core.keys import BlockHash, KeyType, PodEntry
from ..utils.logging import get_logger
from .base import Index

logger = get_logger("index.native")

_CSRC_DIR = Path(__file__).resolve().parent.parent.parent / "csrc" / "kvindex"
_LIB_PATH = _CSRC_DIR / "libkvindex.so"
_build_lock = new_lock()
_lib: Optional[ctypes.CDLL] = None

_FLAG_SPECULATIVE = 1
_FLAG_HAS_GROUP = 2


def load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        src = _CSRC_DIR / "kvindex.cpp"
        if not _LIB_PATH.exists() or (
            src.exists() and src.stat().st_mtime > _LIB_PATH.stat().st_mtime
        ):
            if os.environ.get("KVTPU_NATIVE_NO_BUILD") == "1":
                raise RuntimeError(
                    f"{_LIB_PATH} is missing or stale and "
                    "KVTPU_NATIVE_NO_BUILD=1 forbids compiling at import "
                    "time; run `make native` first (or drop the env knob)")
            # Loud on purpose: an import-time compile means the prebuilt
            # path was skipped, which in production adds seconds of
            # latency (and a toolchain dependency) to first use.
            logger.warning(
                "libkvindex.so missing/stale at %s — compiling at import "
                "time; prebuild with `make native` to avoid this",
                _LIB_PATH)
            subprocess.run(["make", "-s"], cwd=str(_CSRC_DIR), check=True,
                           capture_output=True)
        lib = ctypes.CDLL(str(_LIB_PATH))

        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)

        lib.kvhash_init.restype = ctypes.c_uint64
        lib.kvhash_init.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kvhash_chain.restype = ctypes.c_int
        lib.kvhash_chain.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
            ctypes.c_int, u64p,
        ]
        lib.kvidx_create.restype = ctypes.c_void_p
        lib.kvidx_create.argtypes = [ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64]
        lib.kvidx_destroy.argtypes = [ctypes.c_void_p]
        lib.kvidx_intern.restype = ctypes.c_int32
        lib.kvidx_intern.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kvidx_get_string.restype = ctypes.c_int
        lib.kvidx_get_string.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int
        ]
        lib.kvidx_add.argtypes = [
            ctypes.c_void_p, u64p, ctypes.c_int, u64p, ctypes.c_int,
            i32p, i32p, u8p, i32p, ctypes.c_int,
        ]
        lib.kvidx_lookup.restype = ctypes.c_int
        lib.kvidx_lookup.argtypes = [
            ctypes.c_void_p, u64p, ctypes.c_int, i32p, ctypes.c_int,
            i32p, i32p, ctypes.c_int,
        ]
        lib.kvidx_evict.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
            i32p, i32p, u8p, i32p, ctypes.c_int,
        ]
        lib.kvidx_get_request_key.restype = ctypes.c_uint64
        lib.kvidx_get_request_key.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kvidx_clear.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.kvidx_len.restype = ctypes.c_uint64
        lib.kvidx_len.argtypes = [ctypes.c_void_p]
        lib.kvidx_score.restype = ctypes.c_int
        lib.kvidx_score.argtypes = [
            ctypes.c_void_p, u64p, ctypes.c_int, i32p, ctypes.c_int,
            i32p, ctypes.POINTER(ctypes.c_double), ctypes.c_int,
            i32p, ctypes.POINTER(ctypes.c_double), ctypes.c_int, i32p,
        ]
        lib.kvidx_score_ex.restype = ctypes.c_int
        lib.kvidx_score_ex.argtypes = lib.kvidx_score.argtypes + [ctypes.c_int]
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.kvidx_score_chunked.restype = ctypes.c_int
        lib.kvidx_score_chunked.argtypes = [
            ctypes.c_void_p, u64p, ctypes.c_int,  # keys
            i32p, ctypes.c_int,                   # filter pods
            i32p, f64p, ctypes.c_int,             # tier weights
            ctypes.c_int,                         # chunk_size
            i32p, i32p, u8p, ctypes.c_int,        # residency claims
            ctypes.c_double, ctypes.c_double, ctypes.c_double,  # weights
            i32p, f64p, ctypes.c_int, i32p,       # out pods/scores/cap/hits
            i32p, i32p,                           # out chunks / early_exit
            i32p, f64p, ctypes.c_int, i32p,       # out residency
        ]
        lib.kvidx_map_len.restype = ctypes.c_uint64
        lib.kvidx_map_len.argtypes = [ctypes.c_void_p]
        lib.kvidx_dump.restype = ctypes.c_int
        lib.kvidx_dump.argtypes = [
            ctypes.c_void_p, u64p, i32p, ctypes.c_int, i32p, ctypes.c_int,
        ]
        lib.kvidx_dump_mappings.restype = ctypes.c_int
        lib.kvidx_dump_mappings.argtypes = [
            ctypes.c_void_p, u64p, i32p, ctypes.c_int, u64p, ctypes.c_int,
        ]
        lib.kvidx_set_mapping.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, u64p, ctypes.c_int,
        ]

        _lib = lib
        return _lib


_load_failed = False


def native_available() -> bool:
    """True when the native library loads; a failed build is cached so
    callers (e.g. IndexConfig.default on every create_index) don't re-spawn
    the compiler per call."""
    global _load_failed
    if _load_failed:
        return False
    try:
        load_library()
        return True
    except Exception:
        _load_failed = True
        return False


# -- hash-chain fast path ---------------------------------------------------


def hash_init(seed: str, model: str) -> int:
    return load_library().kvhash_init(seed.encode(), model.encode())


def hash_chain(parent: int, tokens: Sequence[int], block_size: int) -> list[int]:
    """Chain-hash full text-only blocks natively."""
    return hash_chain_with_array(parent, tokens, block_size)[0]


def hash_chain_with_array(
    parent: int, tokens: Sequence[int], block_size: int
) -> tuple[list[int], np.ndarray]:
    """Chain-hash natively, returning the keys both as a list and as the
    ``uint64`` array the C++ call produced — callers that feed the keys
    straight back into ``NativeIndex.score`` (the fused score path) keep
    the array and skip a per-call ``asarray`` over thousands of keys."""
    lib = load_library()
    arr = np.asarray(tokens, np.uint32)
    n_blocks = len(arr) // block_size
    if n_blocks == 0:
        return [], np.empty(0, np.uint64)
    out = np.empty(n_blocks, np.uint64)
    n = lib.kvhash_chain(
        ctypes.c_uint64(parent & 0xFFFFFFFFFFFFFFFF),
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(arr), block_size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    out = out[:n]
    return [int(h) for h in out], out


# -- native index -----------------------------------------------------------


@dataclass
class NativeIndexConfig:
    size: int = 10**8
    pod_cache_size: int = 10
    mapping_size: int = 10**8

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "NativeIndexConfig":
        if not d:
            return cls()
        return cls(
            size=d.get("size", 10**8) or 10**8,
            pod_cache_size=d.get("podCacheSize", d.get("pod_cache_size", 10)) or 10,
            mapping_size=d.get("mappingSize", d.get("mapping_size", 10**8)) or 10**8,
        )


class NativeIndex(Index):
    """C++-backed Index implementation."""

    def __init__(self, cfg: Optional[NativeIndexConfig] = None):
        cfg = cfg or NativeIndexConfig()
        self._lib = load_library()
        self._handle = self._lib.kvidx_create(cfg.size, cfg.pod_cache_size,
                                              cfg.mapping_size)
        if not self._handle:
            raise RuntimeError("failed to create native index")
        # Mirror of the native intern table (id → string), filled lazily.
        self._interned: dict[str, int] = {}
        self._strings: dict[int, str] = {}
        self._intern_lock = new_lock()
        self._lookup_cap = 4096  # entries; grown on demand
        # PodEntry is frozen/immutable: memoize by packed tuple so lookups
        # reuse objects instead of re-materializing identical entries.
        self._entry_cache: dict[tuple[int, int, int, int], PodEntry] = {}

    def _intern(self, s: str) -> int:
        with self._intern_lock:
            sid = self._interned.get(s)
            if sid is None:
                sid = self._lib.kvidx_intern(self._handle, s.encode())
                self._interned[s] = sid
                self._strings[sid] = s
            return sid

    def _resolve(self, sid: int) -> str:
        s = self._strings.get(sid)
        if s is not None:
            return s
        buf = ctypes.create_string_buffer(512)
        n = self._lib.kvidx_get_string(self._handle, sid, buf, 512)
        s = buf.value.decode() if n >= 0 else ""
        with self._intern_lock:
            self._strings[sid] = s
        return s

    def _pack_entries(self, entries: Sequence[PodEntry]):
        n = len(entries)
        pods = np.empty(n, np.int32)
        tiers = np.empty(n, np.int32)
        flags = np.empty(n, np.uint8)
        groups = np.empty(n, np.int32)
        for i, e in enumerate(entries):
            pods[i] = self._intern(e.pod_identifier)
            tiers[i] = self._intern(e.device_tier)
            flags[i] = (_FLAG_SPECULATIVE if e.speculative else 0) | (
                _FLAG_HAS_GROUP if e.has_group else 0
            )
            groups[i] = e.group_idx
        return pods, tiers, flags, groups

    @staticmethod
    def _keys_array(keys: Sequence[BlockHash]) -> np.ndarray:
        try:
            return np.asarray(keys, np.uint64)
        except (OverflowError, TypeError, ValueError):
            return np.asarray([k & 0xFFFFFFFFFFFFFFFF for k in keys], np.uint64)

    # Zero-copy ingest marker (events.pool packed path): keys may arrive
    # as numpy uint64 views and flow to the C side without materializing
    # per-element Python ints.
    accepts_key_arrays = True

    def add(self, engine_keys, request_keys, entries) -> None:
        # len()-based emptiness: request_keys may be a numpy view, whose
        # truth value is ambiguous for more than one element.
        if request_keys is None or len(request_keys) == 0 or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        rk = self._keys_array(request_keys)
        ek = (self._keys_array(engine_keys)
              if engine_keys is not None and len(engine_keys)
              else np.empty(0, np.uint64))
        pods, tiers, flags, groups = self._pack_entries(entries)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._lib.kvidx_add(
            self._handle,
            ek.ctypes.data_as(u64p), len(ek),
            rk.ctypes.data_as(u64p), len(rk),
            pods.ctypes.data_as(i32p), tiers.ctypes.data_as(i32p),
            flags.ctypes.data_as(u8p), groups.ctypes.data_as(i32p),
            len(entries),
        )

    def lookup(self, request_keys, pod_identifier_set=None):
        if not request_keys:
            raise ValueError("no request_keys provided for lookup")
        keys = self._keys_array(request_keys)
        if pod_identifier_set:
            filt = np.asarray(
                [self._intern(p) for p in pod_identifier_set], np.int32
            )
        else:
            filt = np.empty(0, np.int32)
        counts = np.zeros(len(keys), np.int32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        while True:
            out = np.empty(self._lookup_cap * 4, np.int32)
            total = self._lib.kvidx_lookup(
                self._handle,
                keys.ctypes.data_as(u64p), len(keys),
                filt.ctypes.data_as(i32p), len(filt),
                counts.ctypes.data_as(i32p),
                out.ctypes.data_as(i32p), len(out),
            )
            if total >= 0:
                break
            self._lookup_cap *= 2

        result: dict[BlockHash, list[PodEntry]] = {}
        flat = out[: total * 4].tolist()
        entry_cache = self._entry_cache
        pos = 0
        for i, key in enumerate(request_keys):
            c = int(counts[i])
            if c == 0:
                continue
            entries = []
            for j in range(pos, pos + c):
                packed = tuple(flat[j * 4:j * 4 + 4])
                entry = entry_cache.get(packed)
                if entry is None:
                    pod, tier, fl, group = packed
                    entry = PodEntry(
                        pod_identifier=self._resolve(pod),
                        device_tier=self._resolve(tier),
                        speculative=bool(fl & _FLAG_SPECULATIVE),
                        has_group=bool(fl & _FLAG_HAS_GROUP),
                        group_idx=group,
                    )
                    entry_cache[packed] = entry
                entries.append(entry)
            result[key] = entries
            pos += c
        return result

    def evict(self, key, key_type, entries) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        self.evict_batch([key], key_type, entries)

    def evict_batch(self, keys, key_type, entries) -> None:
        """Evict many keys with one entry-packing/interning pass."""
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        pods, tiers, flags, groups = self._pack_entries(entries)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        is_engine = 1 if key_type is KeyType.ENGINE else 0
        for key in keys:
            self._lib.kvidx_evict(
                self._handle,
                ctypes.c_uint64(key & 0xFFFFFFFFFFFFFFFF),
                is_engine,
                pods.ctypes.data_as(i32p), tiers.ctypes.data_as(i32p),
                flags.ctypes.data_as(u8p), groups.ctypes.data_as(i32p),
                len(entries),
            )

    def score(
        self,
        request_keys: Sequence[BlockHash],
        medium_weights: dict[str, float],
        pod_identifier_set=None,
        early_exit: bool = False,
    ) -> tuple[dict[str, float], int]:
        """Fused lookup + longest-prefix tier-weighted scoring in C++.

        Exactly equivalent to ``LongestPrefixScorer.score`` over
        ``lookup`` (shared equivalence tests), without materializing any
        PodEntry objects. Returns ``(scores, hit_count)`` where hit_count
        is the Lookup-equivalent number of resident keys (telemetry).
        The scan also refreshes LRU recency like a lookup would.

        ``early_exit=True`` stops the C++ scan once the prefix chain broke:
        identical scores, but hit_count only covers the scanned prefix and
        post-gap blocks are not LRU-refreshed.
        """
        if len(request_keys) == 0:  # len() so ndarray keys are accepted
            return {}, 0
        keys = self._keys_array(request_keys)
        if pod_identifier_set:
            filt = np.asarray([self._intern(p) for p in pod_identifier_set], np.int32)
        else:
            filt = np.empty(0, np.int32)
        wt = np.asarray([self._intern(t) for t in medium_weights], np.int32)
        wv = np.asarray(list(medium_weights.values()), np.float64)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        f64p = ctypes.POINTER(ctypes.c_double)
        hits = np.zeros(1, np.int32)
        cap = 1024
        while True:
            out_pods = np.empty(cap, np.int32)
            out_scores = np.empty(cap, np.float64)
            n = self._lib.kvidx_score_ex(
                self._handle,
                keys.ctypes.data_as(u64p), len(keys),
                filt.ctypes.data_as(i32p), len(filt),
                wt.ctypes.data_as(i32p), wv.ctypes.data_as(f64p), len(wt),
                out_pods.ctypes.data_as(i32p), out_scores.ctypes.data_as(f64p),
                cap, hits.ctypes.data_as(i32p),
                1 if early_exit else 0,
            )
            if n >= 0:
                break
            cap = -n  # buffer too small: exact needed size reported
        return (
            {
                self._resolve(int(out_pods[i])): float(out_scores[i])
                for i in range(n)
            },
            int(hits[0]),
        )

    def score_chunked(
        self,
        request_keys: Sequence[BlockHash],
        medium_weights: dict[str, float],
        pod_identifier_set=None,
        chunk_size: int = 0,
        claims: Optional[Sequence[tuple[str, int, bool]]] = None,
        landed_weight: float = 1.0,
        in_flight_discount: float = 0.5,
        tier_discount: float = 1.0,
    ) -> tuple[dict[str, float], int, dict[str, float], dict[str, int]]:
        """Chunked fused scoring with residency fold-in: the whole score
        data plane — early-exit chunked lookup, tier-weighted prefix
        scoring, and the per-pod consecutive-from-0 residency walk — in
        ONE ctypes crossing and one native lock hold.

        ``chunk_size`` mirrors the Python ``lookup_chunked`` granularity:
        the scan stops at the first chunk boundary after the prefix chain
        broke (0 scans everything). ``claims`` are sparse
        ``(pod, key_index, landed)`` rows from
        :meth:`~..scoring.residency.ResidencyTracker.claim_rows`.

        Returns ``(scores, hit_count, residency_bonus, stats)`` where
        ``scores`` are the BASE prefix scores (bonus not folded in — the
        caller applies liveness weighting to the base first, exactly like
        the unfused path), ``residency_bonus`` is pod → bonus, and
        ``stats`` carries ``chunks`` scanned and ``early_exited``.
        """
        empty_stats = {"chunks": 0, "early_exited": 0}
        if len(request_keys) == 0:  # len() so ndarray keys are accepted
            return {}, 0, {}, empty_stats
        keys = self._keys_array(request_keys)
        if pod_identifier_set:
            filt = np.asarray(
                [self._intern(p) for p in pod_identifier_set], np.int32
            )
        else:
            filt = np.empty(0, np.int32)
        wt = np.asarray([self._intern(t) for t in medium_weights], np.int32)
        wv = np.asarray(list(medium_weights.values()), np.float64)

        n_claims = len(claims) if claims else 0
        claim_pods = np.empty(n_claims, np.int32)
        claim_idx = np.empty(n_claims, np.int32)
        claim_landed = np.empty(n_claims, np.uint8)
        res_cap = 0
        if n_claims:
            distinct: set[str] = set()
            for i, (pod, idx, landed) in enumerate(claims):
                claim_pods[i] = self._intern(pod)
                claim_idx[i] = idx
                claim_landed[i] = 1 if landed else 0
                distinct.add(pod)
            res_cap = len(distinct)
        res_pods = np.empty(max(res_cap, 1), np.int32)
        res_bonus = np.empty(max(res_cap, 1), np.float64)

        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f64p = ctypes.POINTER(ctypes.c_double)
        hits = np.zeros(1, np.int32)
        chunks = np.zeros(1, np.int32)
        early = np.zeros(1, np.int32)
        res_n = np.zeros(1, np.int32)
        cap = 1024
        while True:
            out_pods = np.empty(cap, np.int32)
            out_scores = np.empty(cap, np.float64)
            n = self._lib.kvidx_score_chunked(
                self._handle,
                keys.ctypes.data_as(u64p), len(keys),
                filt.ctypes.data_as(i32p), len(filt),
                wt.ctypes.data_as(i32p), wv.ctypes.data_as(f64p), len(wt),
                int(chunk_size),
                claim_pods.ctypes.data_as(i32p),
                claim_idx.ctypes.data_as(i32p),
                claim_landed.ctypes.data_as(u8p), n_claims,
                float(landed_weight), float(in_flight_discount),
                float(tier_discount),
                out_pods.ctypes.data_as(i32p),
                out_scores.ctypes.data_as(f64p), cap,
                hits.ctypes.data_as(i32p),
                chunks.ctypes.data_as(i32p),
                early.ctypes.data_as(i32p),
                res_pods.ctypes.data_as(i32p),
                res_bonus.ctypes.data_as(f64p), res_cap,
                res_n.ctypes.data_as(i32p),
            )
            if n >= 0:
                break
            cap = -n  # buffer too small: exact needed size reported
        return (
            {
                self._resolve(int(out_pods[i])): float(out_scores[i])
                for i in range(n)
            },
            int(hits[0]),
            {
                self._resolve(int(res_pods[i])): float(res_bonus[i])
                for i in range(int(res_n[0]))
            },
            {"chunks": int(chunks[0]), "early_exited": int(early[0])},
        )

    def get_request_key(self, engine_key):
        rk = self._lib.kvidx_get_request_key(
            self._handle, ctypes.c_uint64(engine_key & 0xFFFFFFFFFFFFFFFF)
        )
        return int(rk) if rk != 0 else None

    def clear(self, pod_identifier: str) -> None:
        self._lib.kvidx_clear(self._handle, self._intern(pod_identifier))

    # -- snapshot capability (recovery/) --

    def dump_state(self) -> dict:
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        key_cap = max(int(self._lib.kvidx_len(self._handle)), 1) + 64
        entry_cap = key_cap * 16
        while True:
            keys = np.empty(key_cap, np.uint64)
            counts = np.empty(key_cap, np.int32)
            packed = np.empty(entry_cap * 4, np.int32)
            nk = self._lib.kvidx_dump(
                self._handle,
                keys.ctypes.data_as(u64p), counts.ctypes.data_as(i32p), key_cap,
                packed.ctypes.data_as(i32p), entry_cap,
            )
            if nk >= 0:
                break
            # Concurrent growth between the len() sizing and the dump.
            key_cap *= 2
            entry_cap *= 2
        entries: list = []
        pos = 0
        flat = packed.tolist()
        for i in range(nk):
            c = int(counts[i])
            rows = [
                [
                    self._resolve(flat[j * 4]),
                    self._resolve(flat[j * 4 + 1]),
                    flat[j * 4 + 2],
                    flat[j * 4 + 3],
                ]
                for j in range(pos, pos + c)
            ]
            entries.append([int(keys[i]), rows])
            pos += c

        map_cap = max(int(self._lib.kvidx_map_len(self._handle)), 1) + 64
        rk_cap = map_cap * 8
        while True:
            eks = np.empty(map_cap, np.uint64)
            mcounts = np.empty(map_cap, np.int32)
            rks = np.empty(rk_cap, np.uint64)
            nm = self._lib.kvidx_dump_mappings(
                self._handle,
                eks.ctypes.data_as(u64p), mcounts.ctypes.data_as(i32p), map_cap,
                rks.ctypes.data_as(u64p), rk_cap,
            )
            if nm >= 0:
                break
            map_cap *= 2
            rk_cap *= 2
        mappings: list = []
        pos = 0
        for i in range(nm):
            c = int(mcounts[i])
            mappings.append(
                [int(eks[i]), [int(rk) for rk in rks[pos:pos + c]]]
            )
            pos += c
        return {"entries": entries, "mappings": mappings}

    def restore_state(self, state: dict) -> int:
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        # Group request keys sharing an identical entry set so each group
        # restores with one native call (the common case: thousands of
        # keys all held by the same pod+tier).
        groups: dict[tuple, list[int]] = {}
        for request_key, rows in state.get("entries", []):
            if rows:
                groups.setdefault(
                    tuple(tuple(r) for r in rows), []
                ).append(request_key)
        restored = 0
        empty_ek = np.empty(0, np.uint64)
        for rows, request_keys in groups.items():
            n = len(rows)
            pods = np.empty(n, np.int32)
            tiers = np.empty(n, np.int32)
            flags = np.empty(n, np.uint8)
            group_idx = np.empty(n, np.int32)
            for i, (pod, tier, fl, g) in enumerate(rows):
                pods[i] = self._intern(pod)
                tiers[i] = self._intern(tier)
                flags[i] = fl
                group_idx[i] = g
            rka = self._keys_array(request_keys)
            self._lib.kvidx_add(
                self._handle,
                empty_ek.ctypes.data_as(u64p), 0,
                rka.ctypes.data_as(u64p), len(rka),
                pods.ctypes.data_as(i32p), tiers.ctypes.data_as(i32p),
                flags.ctypes.data_as(u8p), group_idx.ctypes.data_as(i32p),
                n,
            )
            restored += n * len(request_keys)
        # Mappings restore through the dedicated call: kvidx_add with no
        # entries would create empty PodSlots, which Lookup treats as
        # broken prefix chains.
        for engine_key, rks in state.get("mappings", []):
            rka = self._keys_array(rks)
            self._lib.kvidx_set_mapping(
                self._handle,
                ctypes.c_uint64(engine_key & 0xFFFFFFFFFFFFFFFF),
                rka.ctypes.data_as(u64p), len(rka),
            )
        return restored

    def __len__(self) -> int:
        return int(self._lib.kvidx_len(self._handle))

    def close(self) -> None:
        if self._handle:
            self._lib.kvidx_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:  # lint: allow-swallow (best-effort __del__ cleanup)
            pass
