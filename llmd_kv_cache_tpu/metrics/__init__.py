"""Prometheus metrics (counterpart of ``pkg/kvcache/metrics/``)."""

from .collector import (
    INDEX_ADMISSIONS,
    INDEX_EVICTIONS,
    INDEX_LOOKUP_HITS,
    INDEX_LOOKUP_LATENCY,
    INDEX_LOOKUP_REQUESTS,
    INDEX_MAX_POD_HIT_COUNT,
    record_event_lag,
    record_ingest_batch,
    record_prefix_cache_delta,
    start_metrics_logging,
)

__all__ = [
    "INDEX_ADMISSIONS",
    "INDEX_EVICTIONS",
    "INDEX_LOOKUP_HITS",
    "INDEX_LOOKUP_LATENCY",
    "INDEX_LOOKUP_REQUESTS",
    "INDEX_MAX_POD_HIT_COUNT",
    "record_event_lag",
    "record_ingest_batch",
    "record_prefix_cache_delta",
    "start_metrics_logging",
]
