"""Prometheus collectors for the KV-block index.

Counterpart of reference ``pkg/kvcache/metrics/collector.go:29-93``: the same
metric families (``kvcache_index_admissions_total`` etc.) on the default
prometheus_client registry, plus an optional periodic "metrics beat" log line
(``collector.go:97-165``).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Dict, Iterable, Optional, Sequence, Tuple

from prometheus_client import REGISTRY, Counter, Gauge, Histogram

from ..utils.lockdep import new_lock
from ..utils.logging import get_logger

logger = get_logger("metrics")

_NS = "kvcache_index"

INDEX_ADMISSIONS = Counter(f"{_NS}_admissions_total", "Block keys admitted to the index")
INDEX_EVICTIONS = Counter(f"{_NS}_evictions_total", "Block keys evicted from the index")
INDEX_LOOKUP_REQUESTS = Counter(f"{_NS}_lookup_requests_total", "Index lookups served")
INDEX_LOOKUP_HITS = Counter(f"{_NS}_lookup_hits_total", "Block keys found during lookups")
# Accumulates the best per-pod hit count of each lookup, matching the
# reference's counter semantics (collector.go:43-44). Hits are counted at
# any position, not only the consecutive prefix.
INDEX_MAX_POD_HIT_COUNT = Counter(
    f"{_NS}_max_pod_hit_count",
    "Sum over lookups of the highest per-pod block hit count (any position)",
)
INDEX_LOOKUP_LATENCY = Histogram(
    f"{_NS}_lookup_latency_seconds",
    "Index lookup latency",
    buckets=(1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0),
)

# Score-path hot-loop families (docs/architecture.md "Score-path
# performance"): the prefix-key cache and batched event ingestion are
# invisible in the index families above, so they get their own counters.
PREFIX_CACHE_HIT_BLOCKS = Counter(
    f"{_NS}_prefix_cache_hit_blocks_total",
    "Block keys served from the token-processor prefix cache",
)
PREFIX_CACHE_MISS_BLOCKS = Counter(
    f"{_NS}_prefix_cache_miss_blocks_total",
    "Block keys hashed because the prefix cache had no covering prefix",
)
EVENT_INGEST_BATCHES = Counter(
    "kvcache_event_ingest_batches_total",
    "Worker drain batches processed by the event pool",
)
EVENT_INGEST_MESSAGES = Counter(
    "kvcache_event_ingest_messages_total",
    "Raw event messages ingested by the event pool",
)
EVENT_INGEST_COALESCED_OPS = Counter(
    "kvcache_event_ingest_coalesced_ops_total",
    "Index write calls saved by coalescing consecutive same-pod digests",
)


def record_prefix_cache_delta(hit_blocks: int, miss_blocks: int) -> None:
    if hit_blocks > 0:
        PREFIX_CACHE_HIT_BLOCKS.inc(hit_blocks)
    if miss_blocks > 0:
        PREFIX_CACHE_MISS_BLOCKS.inc(miss_blocks)


def record_ingest_batch(messages: int, coalesced_ops: int) -> None:
    EVENT_INGEST_BATCHES.inc()
    if messages > 0:
        EVENT_INGEST_MESSAGES.inc(messages)
    if coalesced_ops > 0:
        EVENT_INGEST_COALESCED_OPS.inc(coalesced_ops)


# Native data-plane families (docs/architecture.md "Native data plane"):
# zero-copy ingest batches bypassing the per-event Python decode, the
# shared-memory ring that bypasses ZMQ entirely, and the chunk/early-exit
# accounting of the fused native score path.
INGEST_ZEROCOPY_BATCHES = Counter(
    "kvtpu_ingest_zerocopy_batches_total",
    "Packed event batches decoded as memoryview-sliced key arrays "
    "(no per-key Python objects) and fed straight to the index",
)
INGEST_SHM_MESSAGES = Counter(
    "kvtpu_ingest_shm_messages_total",
    "Event messages consumed from the same-host shared-memory ring",
)
NATIVE_SCORE_CHUNKS = Counter(
    "kvtpu_native_score_chunks_total",
    "Chunks scanned by the fused native chunked-score path",
)
NATIVE_SCORE_EARLY_EXITS = Counter(
    "kvtpu_native_score_early_exits_total",
    "Fused native chunked scores that stopped before the last key "
    "(prefix chain broke mid-prompt)",
)
SHARD_BATCH_RPCS = Counter(
    "kvtpu_shard_batch_rpcs_total",
    "Framed multi-chunk LookupBlocks fan-out RPCs by outcome "
    "(batched = native frame, fallback = legacy per-chunk replay)",
    ["outcome"],
)


def record_zerocopy_batch(shm: bool = False) -> None:
    INGEST_ZEROCOPY_BATCHES.inc()
    if shm:
        INGEST_SHM_MESSAGES.inc()


def record_shm_messages(count: int) -> None:
    if count > 0:
        INGEST_SHM_MESSAGES.inc(count)


def record_native_score(chunks: int, early_exited: int) -> None:
    if chunks > 0:
        NATIVE_SCORE_CHUNKS.inc(chunks)
    if early_exited:
        NATIVE_SCORE_EARLY_EXITS.inc()


def record_batch_rpc(outcome: str) -> None:
    SHARD_BATCH_RPCS.labels(outcome).inc()


# Event-pipeline lag & staleness (ISSUE 3): the paper's "near-real-time
# global view" claim is only checkable if the publish→ingest delay and
# per-pod sequence gaps are first-class metrics. Lag is measured as
# ingest-time minus the engine's batch timestamp (clock-skew caveat in
# docs/observability.md); sequence gaps count messages provably lost on
# the PUB/SUB hop (ZMQ drops, not reorders, within one publisher).
EVENT_LAG = Histogram(
    "kvcache_event_lag_seconds",
    "Publish-timestamp to ingest delay of event batches",
    buckets=(1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0, 30.0),
)
EVENT_POD_LAG = Gauge(
    "kvcache_event_pod_lag_seconds",
    "Most recent publish-to-ingest delay per pod",
    ["pod"],
)
EVENT_SEQ_GAPS = Counter(
    "kvcache_event_seq_gaps_total",
    "Event messages lost per pod (holes in the per-topic sequence)",
    ["pod"],
)
EVENT_QUEUE_DEPTH = Gauge(
    "kvcache_event_queue_depth",
    "Queued raw messages per event-pool shard",
    ["shard"],
)
INDEX_STALENESS = Gauge(
    "kvcache_index_staleness_seconds",
    "Upper-bound age of the index's view of the slowest live pod",
)


def record_event_lag(pod: str, lag_s: float, seq_gap: int) -> None:
    EVENT_LAG.observe(lag_s)
    EVENT_POD_LAG.labels(pod).set(lag_s)
    if seq_gap > 0:
        EVENT_SEQ_GAPS.labels(pod).inc(seq_gap)


TOKENIZATION_LATENCY = Histogram(
    "kvcache_tokenization_latency_seconds",
    "Tokenization / render latency",
    buckets=(1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0),
)

# Offload data-plane metrics, labelled by medium and direction — the
# counterpart of the reference's vllm:kv_offload_{total_bytes,total_time}
# per-medium families (llmd_fs_backend/README.md:204-218, metrics.py).
OFFLOAD_BYTES = Counter(
    "kv_offload_total_bytes",
    "Bytes moved by offload transfers",
    ["medium", "direction"],
)
OFFLOAD_SECONDS = Counter(
    "kv_offload_total_time_seconds",
    "Wall time of completed offload jobs",
    ["medium", "direction"],
)
OFFLOAD_JOBS = Counter(
    "kv_offload_jobs_total",
    "Completed offload jobs",
    ["medium", "direction", "outcome"],  # outcome: success|failure
)
OFFLOAD_SHED_BLOCKS = Counter(
    "kv_offload_shed_blocks_total",
    "Store blocks dropped by write shedding",
    ["medium"],
)

# Admission-to-first-schedule delay: a request enqueued while a fused
# decode burst is in flight waits for the burst to drain before the
# scheduler first picks it up — up to decode_burst tokens of added TTFT
# under load. This histogram makes that cost observable so operators can
# trade decode_burst against admission latency with data. Observed at the
# request's first scheduling visit, BEFORE any deferred storage restore:
# restore time is a storage-tier cost tracked by the kv_offload_* families,
# not a scheduling wait.
ENGINE_ADMISSION_DELAY = Histogram(
    "kvcache_engine_admission_delay_seconds",
    "enqueue() to first scheduler pick (burst-admission latency; excludes "
    "any deferred storage-restore wait that follows)",
    buckets=(1e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0),
)


def record_admission_delay(seconds: float) -> None:
    ENGINE_ADMISSION_DELAY.observe(max(seconds, 0.0))


# I/O pool placement: operators verify NUMA pinning and the engaged
# transfer path from metrics instead of shelling into the pod.
IO_POOL_NUMA_NODE = Gauge(
    "kv_offload_io_numa_node",
    "Resolved accelerator host NUMA node (-1 = unknown/disabled)",
)
IO_POOL_PINNED_STAGING = Gauge(
    "kv_offload_io_pinned_staging_workers",
    "I/O workers whose staging buffer is mlock'd",
)
IO_POOL_DIRECT_TRANSFERS = Gauge(
    "kv_offload_io_direct_transfers_total",
    "Transfers that took the O_DIRECT staged path",
)


def record_io_pool_placement(engine) -> None:
    """Snapshot a NativeIOEngine's placement/transfer-path gauges."""
    IO_POOL_NUMA_NODE.set(engine.numa_node())
    IO_POOL_PINNED_STAGING.set(engine.pinned_staging_workers())
    IO_POOL_DIRECT_TRANSFERS.set(engine.direct_transfers())


def record_offload_result(medium: str, result) -> None:
    """Record a TransferResult into the offload metric families."""
    direction = "store" if result.is_store else "load"
    outcome = "success" if result.success else "failure"
    OFFLOAD_JOBS.labels(medium, direction, outcome).inc()
    OFFLOAD_BYTES.labels(medium, direction).inc(result.bytes_transferred)
    OFFLOAD_SECONDS.labels(medium, direction).inc(max(result.seconds, 0.0))
    if result.shed_hashes:
        OFFLOAD_SHED_BLOCKS.labels(medium).inc(len(result.shed_hashes))


# Crash-tolerant state (recovery/): snapshot, journal replay, anti-entropy
# and drain outcomes, plus the bounded-queue overflow counter — the signals
# the docs/resilience.md "Crash recovery & drain" runbook keys off.
EVENT_DROPPED = Counter(
    "kvcache_event_dropped_events_total",
    "Raw event messages dropped by the bounded shard queues (drop-oldest)",
    ["shard"],
)
RECOVERY_SNAPSHOTS = Counter(
    "kvcache_recovery_snapshots_total",
    "Index snapshot attempts",
    ["outcome"],  # written|failed
)
RECOVERY_SNAPSHOT_BYTES = Gauge(
    "kvcache_recovery_snapshot_bytes",
    "Size of the most recent index snapshot",
)
RECOVERY_SNAPSHOT_SECONDS = Histogram(
    "kvcache_recovery_snapshot_persist_seconds",
    "Dump + encode + durable-publish time of index snapshots",
    buckets=(1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0),
)
RECOVERY_QUARANTINED = Counter(
    "kvcache_recovery_snapshots_quarantined_total",
    "Snapshots that failed verification and were quarantined",
)
RECOVERY_RESTORED_ENTRIES = Gauge(
    "kvcache_recovery_restored_entries",
    "Index entries restored from the snapshot at the last warm restart",
)
RECOVERY_REPLAYED_RECORDS = Gauge(
    "kvcache_recovery_replayed_records",
    "Journal records replayed at the last warm restart",
)
RECONCILE_RUNS = Counter(
    "kvcache_recovery_reconcile_runs_total",
    "Anti-entropy digest-exchange rounds",
    ["outcome"],  # clean|divergent
)
RECONCILE_REPAIRED = Counter(
    "kvcache_recovery_reconcile_repaired_total",
    "Index entries repaired by anti-entropy reconciliation",
    ["direction"],  # added|removed
)
DRAIN_SECONDS = Gauge(
    "kvcache_recovery_drain_seconds",
    "Wall time of the last graceful drain",
)


def record_dropped_events(shard: int, count: int) -> None:
    if count > 0:
        EVENT_DROPPED.labels(str(shard)).inc(count)


def record_snapshot(outcome: str, size_bytes: int, seconds: float) -> None:
    RECOVERY_SNAPSHOTS.labels(outcome).inc()
    if outcome == "written":
        RECOVERY_SNAPSHOT_BYTES.set(size_bytes)
        RECOVERY_SNAPSHOT_SECONDS.observe(max(seconds, 0.0))


def record_snapshot_quarantine() -> None:
    RECOVERY_QUARANTINED.inc()


def record_warm_restart(restored_entries: int, replayed_records: int) -> None:
    RECOVERY_RESTORED_ENTRIES.set(restored_entries)
    RECOVERY_REPLAYED_RECORDS.set(replayed_records)


def record_reconcile(added: int, removed: int) -> None:
    RECONCILE_RUNS.labels("divergent" if (added or removed) else "clean").inc()
    if added > 0:
        RECONCILE_REPAIRED.labels("added").inc(added)
    if removed > 0:
        RECONCILE_REPAIRED.labels("removed").inc(removed)


def record_drain(seconds: float) -> None:
    DRAIN_SECONDS.set(max(seconds, 0.0))


# --------------------------------------------------------------------------
# BucketHistogram: a histogram primitive with runtime-configurable buckets.
#
# prometheus_client Histograms fix their buckets at module import, which is
# wrong for serving-latency families (TTFT/ITL/TPOT) whose useful resolution
# depends on the deployment (CPU dev loop vs. a v5e pod differ by 100x).
# BucketHistogram takes its buckets from config at construction, supports a
# quantile readback (kvdiag phase percentiles — prometheus_client has no
# read API), and is exported through a single custom collector on the
# default registry so it appears in ``generate_latest()`` exactly like the
# native families. ``observe()`` is allocation-free after construction: one
# bisect into a preallocated bounds tuple plus three stores under a lock.
# --------------------------------------------------------------------------


class BucketHistogram:
    __slots__ = (
        "name",
        "documentation",
        "bounds",
        "_counts",
        "_sum",
        "_count",
        "_exemplars",
        "_lock",
    )

    def __init__(self, name: str, documentation: str, buckets: Sequence[float]):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("BucketHistogram needs at least one bucket bound")
        self.name = name
        self.documentation = documentation
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # per-bucket, +inf last
        self._sum = 0.0
        self._count = 0
        # Per-bucket last exemplar: (trace_id_hex, value, unix_ts) or None.
        # Keeping only the latest per bucket bounds memory and matches the
        # OpenMetrics intent: link a bucket to *a* representative trace.
        self._exemplars: list = [None] * (len(bounds) + 1)
        self._lock = new_lock()

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if trace_id:
                self._exemplars[idx] = (trace_id, float(value), time.time())

    def exemplars(self) -> list:
        """Per-bucket ``(trace_id, value, timestamp) | None``, +Inf last."""
        with self._lock:
            return list(self._exemplars)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, q: float) -> float:
        """Estimate the q-quantile (q in [0, 1]) from bucket boundaries.

        Linear interpolation inside the containing bucket; the open-ended
        +inf bucket reports its lower bound (the estimate saturates there).
        Returns 0.0 when empty.
        """
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total <= 0:
            return 0.0
        target = max(q, 0.0) * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                if i == len(self.bounds):  # +inf bucket
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.bounds[-1]

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, acc = self._count, self._sum
        cumulative, cum = [], 0
        for c in counts:
            cum += c
            cumulative.append(cum)
        les = [str(b) for b in self.bounds] + ["+Inf"]
        return {
            "count": total,
            "sum": acc,
            "buckets": dict(zip(les, cumulative)),
        }

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0
            self._exemplars = [None] * (len(self.bounds) + 1)

    def _sample_buckets(self) -> Iterable[Tuple[str, int]]:
        snap = self.snapshot()
        return list(snap["buckets"].items())


_BUCKET_HISTOGRAMS: Dict[str, BucketHistogram] = {}
_bucket_hist_lock = new_lock()
_bucket_collector_registered = False


class _BucketHistogramCollector:
    """Exports every BucketHistogram as a Prometheus histogram family.

    Buckets carry their last trace-id exemplar (when one was observed) so
    the OpenMetrics exposition (``/metrics?format=openmetrics``) renders
    ``... # {trace_id="..."} value ts`` and a bad bucket links straight to
    a retained trace in the fleet collector. The classic text format
    silently drops exemplars — that path is unchanged.
    """

    def collect(self):
        from prometheus_client.core import Exemplar, HistogramMetricFamily

        with _bucket_hist_lock:
            hists = list(_BUCKET_HISTOGRAMS.values())
        for h in hists:
            snap = h.snapshot()
            exemplars = h.exemplars()
            buckets = []
            for i, (le, cum) in enumerate(snap["buckets"].items()):
                ex = exemplars[i] if i < len(exemplars) else None
                if ex is not None:
                    trace_id, value, ts = ex
                    buckets.append(
                        (le, cum, Exemplar({"trace_id": trace_id}, value, ts))
                    )
                else:
                    buckets.append((le, cum))
            fam = HistogramMetricFamily(h.name, h.documentation)
            fam.add_metric([], buckets=buckets, sum_value=snap["sum"])
            yield fam


def bucket_histogram(
    name: str, documentation: str, buckets: Sequence[float]
) -> BucketHistogram:
    """Get-or-create a named BucketHistogram on the default registry.

    Deduped by name: several engines in one process share the instance
    (the first caller's buckets win), mirroring prometheus_client's
    process-global family semantics.
    """
    global _bucket_collector_registered
    with _bucket_hist_lock:
        hist = _BUCKET_HISTOGRAMS.get(name)
        if hist is None:
            hist = BucketHistogram(name, documentation, buckets)
            _BUCKET_HISTOGRAMS[name] = hist
        register_now = not _bucket_collector_registered
        _bucket_collector_registered = True
    if register_now:
        # Outside the lock: REGISTRY.register() calls collect(), which
        # takes _bucket_hist_lock itself.
        REGISTRY.register(_BucketHistogramCollector())
    return hist


# --------------------------------------------------------------------------
# Engine data-plane families (kvtpu_engine_*): KV-pool occupancy, restore
# outcomes, and request lifecycle counters for the TPU serving engine.
# TTFT/ITL/TPOT are BucketHistograms created by telemetry/engine_telemetry.py
# because their buckets are config-driven; the fixed-shape families live
# here with the rest of the registry.
# --------------------------------------------------------------------------

ENGINE_POOL_FREE_PAGES = Gauge(
    "kvtpu_engine_kv_pool_free_pages",
    "Free pages in the engine KV pool",
    ["group"],
)
ENGINE_POOL_CACHED_BLOCKS = Gauge(
    "kvtpu_engine_kv_pool_cached_blocks",
    "Hashed prefix blocks resident in the engine KV pool",
    ["group"],
)
ENGINE_POOL_ORPHAN_PAGES = Gauge(
    "kvtpu_engine_kv_pool_orphan_pages",
    "Pages held by in-flight requests, not yet hashed into reusable blocks",
    ["group"],
)
ENGINE_POOL_EVICTIONS = Counter(
    "kvtpu_engine_kv_pool_evictions_total",
    "Cached blocks evicted from the engine KV pool to free pages",
    ["group"],
)
ENGINE_RESTORE_JOBS = Counter(
    "kvtpu_engine_restore_jobs_total",
    "Storage-tier KV restore attempts by outcome",
    ["outcome"],  # success|failure|timeout
)
ENGINE_RESTORE_LATENCY = Histogram(
    "kvtpu_engine_restore_latency_seconds",
    "Deferred storage-restore wall time (job start to commit/abandon)",
    buckets=(1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0, 30.0),
)
ENGINE_PREFIX_HIT_BLOCKS = Counter(
    "kvtpu_engine_prefix_hit_blocks_total",
    "HBM-resident prefix blocks reused at request admission",
)
ENGINE_REQUESTS = Counter(
    "kvtpu_engine_requests_total",
    "Requests finished by the engine",
    ["outcome"],  # finished|aborted
)
ENGINE_DECODE_STEPS = Counter(
    "kvtpu_engine_decode_steps_total",
    "Engine step() calls that decoded at least one token",
)
ENGINE_PROFILE_CAPTURES = Counter(
    "kvtpu_engine_profile_captures_total",
    "On-demand jax.profiler captures by outcome",
    ["outcome"],  # success|failure
)
# Padding-waste pair (EngineTelemetry.on_dispatch_tokens): every device
# dispatch reports its real token count against the padded program size —
# the ragged single-kernel path and the padded two-kernel fallback feed
# the same counters, so rate(padded - real) is the padding-FLOP burn and
# the ratio compares the two schedulers directly.
ENGINE_RAGGED_REAL_TOKENS = Counter(
    "kvtpu_engine_ragged_real_tokens_total",
    "Real (non-padding) tokens dispatched by the engine step path",
    ["group"],
)
ENGINE_RAGGED_PADDED_TOKENS = Counter(
    "kvtpu_engine_ragged_padded_tokens_total",
    "Total padded program tokens dispatched by the engine step path",
    ["group"],
)


def record_engine_restore(outcome: str, seconds: Optional[float] = None) -> None:
    ENGINE_RESTORE_JOBS.labels(outcome).inc()
    if seconds is not None:
        ENGINE_RESTORE_LATENCY.observe(max(seconds, 0.0))


def record_profile_capture(outcome: str) -> None:
    ENGINE_PROFILE_CAPTURES.labels(outcome).inc()


def record_ragged_dispatch(group: str, real: int, padded: int) -> None:
    ENGINE_RAGGED_REAL_TOKENS.labels(group).inc(max(real, 0))
    ENGINE_RAGGED_PADDED_TOKENS.labels(group).inc(max(padded, 0))


# --------------------------------------------------------------------------
# Sharded control-plane families (kvtpu_shard_*): the scatter-gather
# router's fan-out latency, per-shard RPC outcomes, degraded lookups,
# the consistent-hash ring's primary-partition balance, and the ring-plan
# prefix cache (docs/architecture.md "Sharded control plane").
# --------------------------------------------------------------------------

SHARD_FANOUT_LATENCY = Histogram(
    "kvtpu_shard_fanout_latency_seconds",
    "Scatter-gather score latency (keys to merged scores, all shards)",
    buckets=(1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0),
)
SHARD_RPCS = Counter(
    "kvtpu_shard_rpcs_total",
    "LookupBlocks RPCs issued by the router, per shard and outcome",
    ["shard", "outcome"],  # outcome: success|failure|skipped (breaker open)
)
SHARD_DEGRADED_LOOKUPS = Counter(
    "kvtpu_shard_degraded_lookups_total",
    "Score calls that served with at least one unreachable shard",
)
SHARD_RING_PARTITIONS = Gauge(
    "kvtpu_shard_ring_partitions",
    "Primary partitions assigned per shard by the consistent-hash ring",
    ["shard"],
)
SHARD_PLAN_CACHE = Counter(
    "kvtpu_shard_plan_cache_total",
    "Ring-plan prefix-cache lookups by outcome",
    ["outcome"],  # hit|miss
)


def record_shard_fanout(seconds: float) -> None:
    SHARD_FANOUT_LATENCY.observe(max(seconds, 0.0))


def record_shard_rpc(shard: str, outcome: str) -> None:
    SHARD_RPCS.labels(shard, outcome).inc()


def record_shard_degraded_lookup(shards: int) -> None:
    if shards > 0:
        SHARD_DEGRADED_LOOKUPS.inc()


def record_shard_plan_cache(hit: bool) -> None:
    SHARD_PLAN_CACHE.labels("hit" if hit else "miss").inc()


def record_ring_load(load: Dict[str, int]) -> None:
    for shard, partitions in load.items():
        SHARD_RING_PARTITIONS.labels(shard).set(partitions)


# --------------------------------------------------------------------------
# Gray-failure tolerance families (kvtpu_hedge_*, kvtpu_shed_*): hedged
# scatter-gather outcomes and adaptive overload-shed decisions
# (docs/resilience.md "Gray failures, deadlines & overload"). Hedge
# outcomes: issued (hedge RPC sent), win (hedge answered first with fresh
# keys), loss (primary answered first, hedge cancelled), failed (hedge
# itself errored), denied (budget exhausted — no hedge sent). Shed
# outcomes: shed (rejected outright), brownout (served degraded),
# deadline (budget already expired at entry), late (served past its
# deadline, flagged degraded), restore_skip (storage restore skipped for
# deadline, recompute instead).
# --------------------------------------------------------------------------

HEDGE_ATTEMPTS = Counter(
    "kvtpu_hedge_attempts_total",
    "Hedged shard-RPC decisions by shard and outcome",
    ["shard", "outcome"],  # issued|win|loss|failed|denied
)
SHED_DECISIONS = Counter(
    "kvtpu_shed_decisions_total",
    "Overload-shed and deadline decisions by site and outcome",
    ["site", "outcome"],  # shed|brownout|deadline|late|restore_skip
)


def record_hedge(shard: str, outcome: str) -> None:
    HEDGE_ATTEMPTS.labels(shard, outcome).inc()


def record_shed(site: str, outcome: str) -> None:
    SHED_DECISIONS.labels(site, outcome).inc()


# --------------------------------------------------------------------------
# Disaggregated-handoff families (kvtpu_handoff_*): prefill→decode KV
# transfers over the offload plane — queue depth, in-flight store jobs,
# per-chunk outcomes, and end-to-end handoff latency (prefill begin to the
# decode pod holding every transferable block). Fed by
# offload.handoff.HandoffCoordinator; kvdiag's ``handoff`` section and the
# docs/architecture.md "Prefill/decode disaggregation" runbook read them.
# --------------------------------------------------------------------------

HANDOFF_QUEUE_DEPTH = Gauge(
    "kvtpu_handoff_transfer_queue_depth",
    "Active prefill-to-decode handoffs not yet completed or failed",
)
HANDOFF_IN_FLIGHT_JOBS = Gauge(
    "kvtpu_handoff_in_flight_jobs",
    "Handoff store jobs issued to the offload plane and not yet landed",
)
HANDOFF_LATENCY = Histogram(
    "kvtpu_handoff_latency_seconds",
    "Prefill-begin to decode-resident handoff wall time",
    buckets=(1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0, 30.0),
)
HANDOFF_CHUNKS = Counter(
    "kvtpu_handoff_chunks_total",
    "Per-chunk handoff transfer completions by outcome",
    ["outcome"],  # landed|failed
)
HANDOFF_REQUESTS = Counter(
    "kvtpu_handoff_requests_total",
    "Handoff requests by terminal outcome",
    ["outcome"],  # complete|failed|timeout|fallback
)


def record_handoff_gauges(queue_depth: int, in_flight_jobs: int) -> None:
    HANDOFF_QUEUE_DEPTH.set(max(queue_depth, 0))
    HANDOFF_IN_FLIGHT_JOBS.set(max(in_flight_jobs, 0))


def record_handoff_chunk(outcome: str) -> None:
    HANDOFF_CHUNKS.labels(outcome).inc()


def record_handoff_request(outcome: str, seconds: Optional[float] = None) -> None:
    HANDOFF_REQUESTS.labels(outcome).inc()
    if seconds is not None:
        HANDOFF_LATENCY.observe(max(seconds, 0.0))


# --------------------------------------------------------------------------
# Fleet observability (kvtpu_trace_*): local span-export health. The ring
# exporter (telemetry/tracing.py) evicts oldest spans once full; every
# eviction lands here so a collector whose pull cursor lags the ring can
# tell "no spans" apart from "spans dropped before I pulled".
# --------------------------------------------------------------------------

TRACE_DROPPED_SPANS = Counter(
    "kvtpu_trace_dropped_spans_total",
    "Finished spans evicted from the in-memory ring exporter before export",
)
TRACE_EXPORTED_SPANS = Counter(
    "kvtpu_trace_exported_spans_total",
    "Finished spans handed to remote pullers via /debug/spans",
)


def record_spans_exported(count: int) -> None:
    if count > 0:
        TRACE_EXPORTED_SPANS.inc(count)


# --------------------------------------------------------------------------
# Continuous profiling (kvtpu_pyprof_*): the always-on sampling profiler
# (telemetry/sampling_profiler.py). samples/overhead are the self-measured
# cost ledger — rate(overhead)/1s is the live CPU fraction the sampler
# steals, gated <1% by ``bench.py --pyprof-overhead``; dropped windows mean
# the collector's /debug/pyprof cursor is lagging the export ring.
# --------------------------------------------------------------------------

PYPROF_SAMPLES = Counter(
    "kvtpu_pyprof_samples_total",
    "Thread-stack samples folded by the sampling profiler",
)
PYPROF_OVERHEAD_SECONDS = Counter(
    "kvtpu_pyprof_overhead_seconds_total",
    "Wall time spent inside sampling-profiler passes (self-measured)",
)
PYPROF_WINDOWS_DROPPED = Counter(
    "kvtpu_pyprof_windows_dropped_total",
    "Sealed profile windows evicted before any /debug/pyprof pull",
)
PYPROF_TRIE_NODES = Gauge(
    "kvtpu_pyprof_trie_nodes",
    "Interned stack-trie nodes in the live (unsealed) profile window",
)


# --------------------------------------------------------------------------
# Per-tier restore latency (ROADMAP item 3): the engine's storage-restore
# paths label each restore with the offload medium (SHARED_STORAGE,
# OBJECT_STORE, ...) so slow-tier restores are visible per tier — and,
# via the fleet collector's restore_latency SLI, in burn-rate alerts.
# kvtpu_engine_restore_latency_seconds stays as the tier-blind aggregate.
# --------------------------------------------------------------------------

OFFLOAD_RESTORE_SECONDS = Histogram(
    "kvtpu_offload_restore_seconds",
    "Storage-tier KV restore wall time per tier (sync + deferred paths)",
    ["tier"],
    buckets=(1e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0),
)


def record_offload_restore(tier: str, seconds: float) -> None:
    OFFLOAD_RESTORE_SECONDS.labels(tier or "unknown").observe(
        max(seconds, 0.0))


# --------------------------------------------------------------------------
# Working-set analytics (kvtpu_workingset_*): the SHARDS-style reuse
# sampler (telemetry/workingset.py). sampled/overhead are its self-measured
# cost ledger — gated <1% of score p50 by ``bench.py --workingset``;
# tracked_blocks shows how much of the max_tracked_blocks budget the
# sampled working set occupies; dropped windows mean the collector's
# /debug/workingset cursor is lagging the export ring.
# --------------------------------------------------------------------------

WORKINGSET_SAMPLED_TOTAL = Counter(
    "kvtpu_workingset_sampled_accesses_total",
    "Block accesses that passed the working-set spatial sampling filter",
)
WORKINGSET_OVERHEAD_SECONDS = Counter(
    "kvtpu_workingset_overhead_seconds_total",
    "Wall time spent inside working-set tracker hooks (self-measured)",
)
WORKINGSET_TRACKED_BLOCKS = Gauge(
    "kvtpu_workingset_tracked_blocks",
    "Sampled block keys currently tracked for reuse distances (all scopes)",
)
WORKINGSET_WINDOWS_DROPPED = Counter(
    "kvtpu_workingset_windows_dropped_total",
    "Sealed working-set windows evicted before any /debug/workingset pull",
)


# --------------------------------------------------------------------------
# Ground-truth audit plane (kvtpu_audit_*): score-vs-reality calibration.
# The collector's AuditJoiner (telemetry/audit.py) joins score-time
# predictions to engine-realized outcomes per trace and lands the
# per-request error here; the calibration curves themselves are
# exemplar-linked BucketHistograms the joiner constructs
# (kvtpu_audit_predicted_hit_blocks / _realized_hit_blocks /
# _calibration_error_blocks). ``cause`` attributes mispredicted blocks to
# the index staleness observed at score time: "stale" (event lag above
# the configured threshold — the index hadn't caught up yet) vs "fresh"
# (the view was current and still wrong — look at torn restores or
# reconcile lag instead; docs/observability.md "Divergence triage").
# --------------------------------------------------------------------------

AUDIT_JOINED = Counter(
    "kvtpu_audit_joined_total",
    "Prediction/outcome pairs joined by the collector audit leg",
    ["pod"],
)
AUDIT_MISPREDICTED_BLOCKS = Counter(
    "kvtpu_audit_mispredicted_blocks_total",
    "Abs(predicted - realized) hit blocks, attributed by score-time staleness",
    ["pod", "cause"],  # stale|fresh
)
AUDIT_REGRETS = Counter(
    "kvtpu_audit_regret_total",
    "Joined requests where another pod's calibrated prediction beat the "
    "chosen pod's realized hit",
    ["pod"],  # the chosen (losing) pod
)
AUDIT_REGRET_BLOCKS = Counter(
    "kvtpu_audit_regret_blocks_total",
    "Estimated hit blocks forgone to routing regret",
    ["pod"],
)
AUDIT_DROPPED_RECORDS = Counter(
    "kvtpu_audit_dropped_records_total",
    "Audit records evicted from a pod's ring before any /debug/audit pull",
)


def record_audit_join(pod: str, error_blocks: float, cause: str) -> None:
    AUDIT_JOINED.labels(pod).inc()
    if error_blocks > 0:
        AUDIT_MISPREDICTED_BLOCKS.labels(pod, cause).inc(error_blocks)


def record_audit_regret(pod: str, blocks: float) -> None:
    AUDIT_REGRETS.labels(pod).inc()
    if blocks > 0:
        AUDIT_REGRET_BLOCKS.labels(pod).inc(blocks)


def record_audit_dropped(count: int) -> None:
    if count > 0:
        AUDIT_DROPPED_RECORDS.inc(count)


# --------------------------------------------------------------------------
# Continuous index-divergence audit (kvtpu_index_divergence_*): the
# always-on sampled XOR-digest audit (recovery.reconcile.DivergenceAuditor)
# compares each pod's indexed view against ground truth WITHOUT repairing.
# Phantom blocks: the index advertises them but the engine lacks them
# (routing overshoots — realized hits fall short of predictions). Ghost
# blocks: the engine holds them unindexed (routing undershoots — capacity
# the scorer never sees). The checked/divergent counters feed the
# ``index_divergence`` SLI burn windows in the fleet collector; the age
# histogram observes how long each divergence episode lasted when it
# healed (reconcile or natural convergence).
# --------------------------------------------------------------------------

DIVERGENCE_CHECKED = Counter(
    "kvtpu_index_divergence_checked_total",
    "Divergence-audit pod checks (one per pod per audit round)",
    ["pod"],
)
DIVERGENCE_DIVERGENT = Counter(
    "kvtpu_index_divergence_divergent_total",
    "Audit rounds where a pod's indexed view diverged from ground truth",
    ["pod"],
)
DIVERGENCE_PHANTOM_BLOCKS = Gauge(
    "kvtpu_index_divergence_phantom_blocks",
    "Blocks the index advertises on a pod that the engine lacks",
    ["pod"],
)
DIVERGENCE_GHOST_BLOCKS = Gauge(
    "kvtpu_index_divergence_ghost_blocks",
    "Blocks an engine holds that its pod's index view is missing",
    ["pod"],
)
DIVERGENCE_AGE_SECONDS = Histogram(
    "kvtpu_index_divergence_age_seconds",
    "Duration of a divergence episode at the audit round that saw it heal",
    buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0),
)


def record_divergence_audit(pod: str, divergent: bool,
                            phantom: int, ghost: int) -> None:
    DIVERGENCE_CHECKED.labels(pod).inc()
    if divergent:
        DIVERGENCE_DIVERGENT.labels(pod).inc()
    DIVERGENCE_PHANTOM_BLOCKS.labels(pod).set(max(phantom, 0))
    DIVERGENCE_GHOST_BLOCKS.labels(pod).set(max(ghost, 0))


def record_divergence_healed(age_s: float) -> None:
    DIVERGENCE_AGE_SECONDS.observe(max(age_s, 0.0))


# --------------------------------------------------------------------------
# Epoch-fenced membership plane (kvtpu_fence_* / kvtpu_topology_* /
# kvtpu_lease_*): the fencing-token discipline in cluster.membership.
# Every fence decision that refuses (or would refuse, in warn mode) a
# stale actor's traffic counts here by receiving site and reason; the
# topology-epoch gauge tracks the newest epoch this process has observed
# (minted by the controller, learned by piggyback); the lease families
# track the renewable pod leases that turn "probably dead" into
# "provably fenced".
# --------------------------------------------------------------------------

FENCE_REJECTIONS = Counter(
    "kvtpu_fence_rejections_total",
    "Stale-epoch / lapsed-lease traffic refused (or flagged in warn mode)",
    ["site", "reason"],
)
TOPOLOGY_EPOCH = Gauge(
    "kvtpu_topology_epoch",
    "Newest fleet topology epoch observed by this process",
)
LEASE_ACTIVE = Gauge(
    "kvtpu_lease_active",
    "Pod leases currently within their TTL",
)
LEASE_RENEWALS = Counter(
    "kvtpu_lease_renewals_total",
    "Successful pod lease renewals",
)
LEASE_EXPIRED = Counter(
    "kvtpu_lease_expired_total",
    "Pod leases that lapsed past their TTL (zombie fence armed)",
)
LEASE_READMISSIONS = Counter(
    "kvtpu_lease_readmissions_total",
    "Lapsed pods re-admitted through the warm-restart gate",
)


def record_fence_rejection(site: str, reason: str) -> None:
    FENCE_REJECTIONS.labels(site, reason).inc()


def record_topology_epoch(epoch: int) -> None:
    TOPOLOGY_EPOCH.set(max(int(epoch), 0))


# --------------------------------------------------------------------------
# Cache-efficiency ledger export (kvtpu_cache_ledger_*): the per-pod
# appearance/win/stored/evicted attribution the Indexer already keeps
# (scoring.indexer.CacheEfficiencyLedger), exported as metric families via
# a custom collector that snapshots the ledger at scrape time — zero cost
# on the score/ingest hot paths, and the /metrics view stays consistent
# with the /debug/vars ledger snapshot.
# --------------------------------------------------------------------------


class _CacheLedgerCollector:
    """Scrape-time bridge from a CacheEfficiencyLedger to /metrics."""

    def __init__(self, snapshot_fn):
        self._snapshot = snapshot_fn

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        try:
            snap = self._snapshot()
        except Exception:  # pragma: no cover  # lint: allow-swallow
            return
        appearances = CounterMetricFamily(
            "kvtpu_cache_ledger_appearances_total",
            "Score results a pod appeared in (cache-efficiency ledger)",
            labels=["pod"],
        )
        wins = CounterMetricFamily(
            "kvtpu_cache_ledger_wins_total",
            "Score results a pod won (highest score) per the ledger",
            labels=["pod"],
        )
        score_total = CounterMetricFamily(
            "kvtpu_cache_ledger_score_total",
            "Cumulative weighted prefix score attributed to a pod",
            labels=["pod"],
        )
        stored = GaugeMetricFamily(
            "kvtpu_cache_ledger_stored_blocks",
            "Blocks the event stream has stored minus evicted on a pod",
            labels=["pod"],
        )
        evicted = CounterMetricFamily(
            "kvtpu_cache_ledger_evicted_blocks_total",
            "Blocks the event stream has evicted from a pod",
            labels=["pod"],
        )
        for pod, st in (snap.get("pods") or {}).items():
            appearances.add_metric([pod], st.get("appearances", 0))
            wins.add_metric([pod], st.get("wins", 0))
            score_total.add_metric([pod], st.get("score_total", 0.0))
            stored.add_metric(
                [pod],
                st.get("stored_blocks", 0) - st.get("evicted_blocks", 0))
            evicted.add_metric([pod], st.get("evicted_blocks", 0))
        yield appearances
        yield wins
        yield score_total
        yield stored
        yield evicted


_ledger_collector_lock = new_lock()
_ledger_collector: Optional[_CacheLedgerCollector] = None


def register_cache_ledger(snapshot_fn) -> None:
    """Export a ledger's snapshot() as kvtpu_cache_ledger_* families.

    Process-global and last-writer-wins (one collector instance, its
    snapshot source swapped), matching prometheus_client's process-global
    family semantics — re-registration across tests must not raise.
    """
    global _ledger_collector
    with _ledger_collector_lock:
        if _ledger_collector is None:
            _ledger_collector = _CacheLedgerCollector(snapshot_fn)
            register_now = True
        else:
            _ledger_collector._snapshot = snapshot_fn
            register_now = False
    if register_now:
        REGISTRY.register(_ledger_collector)


_beat_thread: Optional[threading.Thread] = None
_beat_stop = threading.Event()


def start_metrics_logging(interval_s: float) -> None:
    """Log a periodic one-line metrics beat. Idempotent, daemon thread."""
    global _beat_thread
    if _beat_thread is not None and _beat_thread.is_alive():
        if not _beat_stop.is_set():
            return
        # A stop was requested but the old thread hasn't exited yet; wait it
        # out so the restart below actually takes effect.
        _beat_thread.join()
    _beat_stop.clear()

    def _beat() -> None:
        while not _beat_stop.wait(interval_s):
            logger.info(
                "metrics beat: admissions=%d evictions=%d lookups=%d hits=%d",
                INDEX_ADMISSIONS._value.get(),
                INDEX_EVICTIONS._value.get(),
                INDEX_LOOKUP_REQUESTS._value.get(),
                INDEX_LOOKUP_HITS._value.get(),
            )

    _beat_thread = threading.Thread(target=_beat, name="kvtpu-metrics-beat", daemon=True)
    _beat_thread.start()


def stop_metrics_logging() -> None:
    _beat_stop.set()
