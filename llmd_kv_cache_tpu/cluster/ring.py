"""Bounded-load consistent-hash ring for the sharded indexer control plane.

Partition-table variant of consistent hashing with bounded loads
(Mirrokni/Thorup/Zadimoghaddam): a fixed number of *partitions* is placed
on a 64-bit ring; each shard contributes ``virtual_nodes`` vnode points;
every partition is assigned to the first shard clockwise from it whose
partition count is under the bound ``ceil(load_factor * partitions /
shards)``. Block keys map to partitions, partitions map to shards:

- **balance within bound** — the cap is a hard invariant, not an
  expectation: no shard ever primaries more than ``ceil(load_factor *
  P / N)`` partitions.
- **minimal key movement** — membership change moves only the partitions
  whose clockwise walk now resolves differently; everything else stays
  where it was (the consistent-hashing property the fixed partition
  layer preserves).
- **deterministic across processes** — every placement comes from
  FNV-1a over stable byte strings; Python's randomized ``hash()`` is
  never involved, so N schedulers and N shard replicas that share the
  membership list derive the identical table.

The ring is immutable; membership change means building a new ring and
(optionally) diffing it with :func:`moved_partitions` for rebalance
telemetry.
"""

from __future__ import annotations

import math
from bisect import bisect_left
from typing import Iterable, Sequence

from ..utils.fnv import fnv1a_64

_MASK64 = 0xFFFFFFFFFFFFFFFF

DEFAULT_VIRTUAL_NODES = 64
DEFAULT_PARTITIONS = 1024
DEFAULT_LOAD_FACTOR = 1.25


def _mix64(h: int) -> int:
    """MurmurHash3 64-bit finalizer. FNV-1a of short, similar strings
    (vnode/partition labels differ only in trailing digits) clusters on
    the high bits of the ring; the avalanche pass restores uniform
    placement while staying pure integer arithmetic — deterministic
    everywhere."""
    h &= _MASK64
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & _MASK64
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & _MASK64
    h ^= h >> 33
    return h


def _point(data: bytes) -> int:
    return _mix64(fnv1a_64(data))


def _key_bytes(key: int) -> bytes:
    return (int(key) & _MASK64).to_bytes(8, "big")


class HashRing:
    """Immutable bounded-load consistent-hash ring over shard ids."""

    def __init__(
        self,
        shards: Iterable[str],
        virtual_nodes: int = DEFAULT_VIRTUAL_NODES,
        partitions: int = DEFAULT_PARTITIONS,
        load_factor: float = DEFAULT_LOAD_FACTOR,
        epoch: int = 0,
    ):
        members = sorted(set(shards))
        if epoch < 0:
            raise ValueError(f"epoch must be >= 0, got {epoch}")
        if not members:
            raise ValueError("HashRing needs at least one shard")
        if virtual_nodes <= 0 or partitions <= 0:
            raise ValueError("virtual_nodes and partitions must be positive")
        if load_factor < 1.0:
            raise ValueError(f"load_factor must be >= 1.0, got {load_factor}")
        self.shards: tuple[str, ...] = tuple(members)
        self.virtual_nodes = virtual_nodes
        self.partitions = partitions
        self.load_factor = load_factor
        # Topology epoch this ring was built for (cluster.membership).
        # Placement ignores it — two rings with the same members place
        # identically across epochs — but it feeds ``version`` so plan
        # caches and fingerprints distinguish "same placement, older
        # topology" from "same ring".
        self.epoch = epoch
        # Hard per-shard primary cap (the "bounded load").
        self.capacity = math.ceil(load_factor * partitions / len(members))

        points: list[tuple[int, str]] = []
        for shard in members:
            base = shard.encode("utf-8")
            for i in range(virtual_nodes):
                points.append((_point(base + b"#%d" % i), shard))
        points.sort()
        self._points = points
        self._point_keys = [p for p, _ in points]

        # Per-partition preference list: distinct shards in clockwise vnode
        # order from the partition's own ring point. The bounded-load
        # primary is the first under-cap shard in that list; replicas are
        # the following distinct shards (uncapped — replica load is a soft
        # concern, determinism and failover coverage are the hard ones).
        loads: dict[str, int] = {s: 0 for s in members}
        prefs: list[tuple[str, ...]] = []
        table: list[str] = []
        for p in range(partitions):
            point = _point(b"partition/%d" % p)
            pref = self._walk(point)
            prefs.append(pref)
            primary = next((s for s in pref if loads[s] < self.capacity), pref[0])
            loads[primary] += 1
            table.append(primary)
        self._prefs = prefs
        self._table = table
        self._loads = loads

        # Membership fingerprint for cross-process plan-cache keying: two
        # rings agree on every assignment iff they agree on this.
        sig = "|".join(members).encode("utf-8")
        sig += b"/%d/%d/%d" % (virtual_nodes, partitions, int(load_factor * 1000))
        if epoch:
            # Appended only when set so pre-epoch processes (and journals
            # holding their version numbers) keep hashing identically.
            sig += b"/e%d" % epoch
        self.version = fnv1a_64(sig)

    def with_epoch(self, epoch: int) -> "HashRing":
        """Same membership and shape, new topology epoch (the router's
        atomic swap on an epoch bump — placement provably unchanged)."""
        return HashRing(
            self.shards,
            virtual_nodes=self.virtual_nodes,
            partitions=self.partitions,
            load_factor=self.load_factor,
            epoch=epoch,
        )

    # -- placement --------------------------------------------------------

    def _walk(self, point: int) -> tuple[str, ...]:
        """Distinct shards in clockwise vnode order starting at ``point``."""
        idx = bisect_left(self._point_keys, point)
        n = len(self._points)
        seen: list[str] = []
        seen_set: set[str] = set()
        for step in range(n):
            shard = self._points[(idx + step) % n][1]
            if shard not in seen_set:
                seen_set.add(shard)
                seen.append(shard)
                if len(seen) == len(self.shards):
                    break
        return tuple(seen)

    def partition_of(self, key: int) -> int:
        """Block key → partition. Keys are re-hashed (they are already
        FNV-chained block hashes, but re-hashing decorrelates the
        partition choice from the chain structure)."""
        return _mix64(fnv1a_64(_key_bytes(key))) % self.partitions

    def owner(self, key: int) -> str:
        """Primary shard for a block key."""
        return self._table[self.partition_of(key)]

    def owner_of_partition(self, partition: int) -> str:
        return self._table[partition]

    def owners(self, key: int, n: int = 1) -> list[str]:
        """``n`` distinct shards for a block key, primary first.

        The primary is the bounded-load assignment; replicas follow the
        partition's clockwise preference order, skipping the primary.
        """
        p = self.partition_of(key)
        primary = self._table[p]
        if n <= 1:
            return [primary]
        out = [primary]
        for shard in self._prefs[p]:
            if shard != primary:
                out.append(shard)
                if len(out) >= n:
                    break
        return out

    # -- introspection ----------------------------------------------------

    def load(self) -> dict[str, int]:
        """Primary partition count per shard (skew telemetry)."""
        return dict(self._loads)

    def describe(self) -> dict:
        """JSON-able summary for the admin/debug surface."""
        return {
            "shards": list(self.shards),
            "partitions": self.partitions,
            "virtual_nodes": self.virtual_nodes,
            "capacity": self.capacity,
            "version": self.version,
            "epoch": self.epoch,
            "load": self.load(),
        }


def moved_partitions(old: HashRing, new: HashRing) -> int:
    """Partitions whose primary differs between two rings (must share the
    partition count). The rebalance cost of a membership change."""
    if old.partitions != new.partitions:
        raise ValueError("rings disagree on partition count")
    return sum(
        1
        for p in range(old.partitions)
        if old.owner_of_partition(p) != new.owner_of_partition(p)
    )


def assignment_fingerprint(ring: HashRing) -> int:
    """Order-sensitive FNV digest of the full partition table, salted
    with the ring ``version`` — equal fingerprints mean byte-identical
    assignment AND the same topology epoch, so two rings with identical
    placement but different epochs compare unequal (a stale-epoch plan
    can never masquerade as current just because membership round-
    tripped). Cross-process determinism checks rely on both halves."""
    acc = b"".join(s.encode("utf-8") + b"\x00" for s in ring._table)
    acc += b"@%d" % ring.version
    return fnv1a_64(acc)


def plan_owners(ring: HashRing, keys: Sequence[int]) -> tuple[str, ...]:
    """Primary owner per key, in key order (the router's fan-out plan)."""
    table = ring._table
    return tuple(table[ring.partition_of(k)] for k in keys)
