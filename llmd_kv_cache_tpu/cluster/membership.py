"""Epoch-fenced membership: topology epochs, pod leases, zombie fencing.

The fleet's classic split-brain/zombie failures share one root cause: an
actor keeps acting on a topology the rest of the fleet has moved past —
a pod resumes from a GC pause and keeps ingesting, a router scores
against a stale ring mid-rebalance, a warm-restarted controller re-runs
a mutation a newer controller already made. The standard remedy
(GFS/Chubby lease discipline, the fencing-token pattern) is implemented
here as two small primitives:

- a monotonic **topology epoch**, minted by the fleet controller on
  every topology mutation and stamped as tolerant wire metadata (the
  ``deadline_ms`` arrival pattern) on shard RPCs, score requests,
  KV-event batches, and handoff begins. Receivers refuse — or flag, per
  the ``fenceMode: reject|warn`` knob — traffic carrying an *older*
  epoch than their own, and **learn** newer epochs from any incoming
  stamp (gossip-by-piggyback: propagation needs no new service, any
  traffic at all carries the bump).
- renewable **pod leases** bound to the current epoch. A pod that stops
  renewing (paused, partitioned, live-locked) lapses past ``leaseTtlS``;
  from then on its writes are fenced *deterministically* — not "demoted
  when latency looks bad" but "rejected until it re-admits through the
  warm-restart gate" (:class:`~..recovery.manager.RecoveryManager`
  readiness), which forces the zombie back through snapshot/journal
  replay before its view of the world counts again.

Epoch ``0`` on any wire means "unstamped" (a legacy peer) and is never
fenced — rollout stays compatible in ``warn`` mode by construction.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

from ..metrics.collector import (
    LEASE_ACTIVE,
    LEASE_EXPIRED,
    LEASE_READMISSIONS,
    LEASE_RENEWALS,
    record_fence_rejection,
    record_topology_epoch,
)
from ..resilience.failpoints import failpoints
from ..telemetry.flight_recorder import KIND_FENCE
from ..telemetry.flight_recorder import record as fr_record
from ..utils.lockdep import new_lock
from ..utils.logging import get_logger

logger = get_logger("cluster.membership")

FENCE_WARN = "warn"
FENCE_REJECT = "reject"
_FENCE_MODES = (FENCE_WARN, FENCE_REJECT)

# Fence reasons (the {reason} label of kvtpu_fence_rejections_total).
REASON_STALE_EPOCH = "stale_epoch"
REASON_LEASE_LAPSED = "lease_lapsed"
REASON_NOT_READMITTED = "not_readmitted"

# First topology every fleet starts at; wire epoch 0 = "unstamped".
GENESIS_EPOCH = 1

# Failpoint consulted on each lease renewal: ``membership.renew.<pod>``
# armed in ``pause`` mode ages the lease by the virtual stall instead of
# renewing it — a GC-paused zombie without a real sleep anywhere.
FP_RENEW_PREFIX = "membership.renew."


@dataclass(frozen=True)
class FenceDecision:
    """Outcome of one fence check at a receiving site."""

    allowed: bool
    reason: str = ""  # "" when clean; a REASON_* otherwise
    # True when the traffic was stale but fenceMode=warn let it through
    # (the metric/flight-record still fired — dashboards see the zombie
    # before the knob is flipped to reject).
    flagged: bool = False
    # Receiver's topology epoch at decision time (stamped on responses
    # so the sender learns it — the piggyback half of gossip).
    epoch: int = 0


@dataclass
class Lease:
    """One pod's renewable membership lease."""

    pod_id: str
    epoch: int  # topology epoch the last grant/renewal bound to
    granted_ts: float
    renewed_ts: float
    ttl_s: float
    lapsed: bool = False  # set once per lapse episode (metric edge)

    def remaining_s(self, now: float) -> float:
        return self.ttl_s - (now - self.renewed_ts)

    def age_s(self, now: float) -> float:
        return now - self.renewed_ts


class MembershipTable:
    """Thread-safe epoch + lease registry shared by the receiving sites.

    One instance per process (the indexer service owns it and hands it
    to the event pool, the router, and the debug surface). All methods
    are cheap enough for the score hot path: a clean :meth:`check_request`
    is a lock-free integer compare returning a cached decision (CPython
    attribute reads are atomic; the cached decision is swapped under the
    lock whenever the epoch advances).
    """

    def __init__(
        self,
        fence_mode: str = FENCE_WARN,
        lease_ttl_s: float = 30.0,
        lease_renew_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        epoch: int = GENESIS_EPOCH,
    ):
        if fence_mode not in _FENCE_MODES:
            raise ValueError(
                f"fenceMode must be one of {_FENCE_MODES}, got {fence_mode!r}"
            )
        if lease_ttl_s <= 0 or lease_renew_s <= 0:
            raise ValueError("leaseTtlS and leaseRenewS must be positive")
        if lease_renew_s >= lease_ttl_s:
            raise ValueError(
                f"leaseRenewS ({lease_renew_s}) must be shorter than "
                f"leaseTtlS ({lease_ttl_s}) or a single missed renewal lapses"
            )
        self.fence_mode = fence_mode
        self.lease_ttl_s = float(lease_ttl_s)
        self.lease_renew_s = float(lease_renew_s)
        self._clock = clock
        self._mu = new_lock()
        self._epoch = max(int(epoch), GENESIS_EPOCH)
        self._leases: dict[str, Lease] = {}
        # Epoch-bump observers (the router swaps its ring plan here);
        # called outside the lock with the new epoch.
        self._listeners: list[Callable[[int], None]] = []
        # Last few rejections for kvdiag's membership section.
        self._recent: deque = deque(maxlen=32)
        self.rejections = 0
        self.flagged = 0
        # Singleton clean verdict for the hot path: one per epoch, so a
        # same-epoch check is a compare + cached return, no allocation.
        self._clean = FenceDecision(allowed=True, epoch=self._epoch)
        record_topology_epoch(self._epoch)

    @classmethod
    def from_cluster_config(cls, cfg, clock: Callable[[], float] = time.monotonic
                            ) -> "MembershipTable":
        return cls(
            fence_mode=getattr(cfg, "fence_mode", FENCE_WARN) or FENCE_WARN,
            lease_ttl_s=getattr(cfg, "lease_ttl_s", 30.0),
            lease_renew_s=getattr(cfg, "lease_renew_s", 10.0),
            clock=clock,
        )

    # -- topology epoch ---------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._mu:
            return self._epoch

    def add_epoch_listener(self, fn: Callable[[int], None]) -> None:
        with self._mu:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def observe_epoch(self, epoch: int, source: str = "") -> bool:
        """Learn a possibly-newer epoch from incoming traffic (or from the
        controller's commit). Returns True when the local epoch advanced."""
        epoch = int(epoch)
        with self._mu:
            if epoch <= self._epoch:
                return False
            self._epoch = epoch
            self._clean = FenceDecision(allowed=True, epoch=epoch)
            listeners = list(self._listeners)
        record_topology_epoch(epoch)
        fr_record(KIND_FENCE, {"event": "epoch_learned", "epoch": epoch,
                                  "source": source})
        logger.info("topology epoch advanced to %d (source=%s)", epoch, source)
        for fn in listeners:
            try:
                fn(epoch)
            except Exception:  # pragma: no cover - observers must not break the plane  # lint: allow-swallow
                logger.exception("epoch listener failed")
        return True

    # -- leases -----------------------------------------------------------

    def grant(self, pod_id: str) -> Lease:
        """Admit a pod under a fresh lease bound to the current epoch."""
        now = self._clock()
        with self._mu:
            lease = Lease(pod_id=pod_id, epoch=self._epoch, granted_ts=now,
                          renewed_ts=now, ttl_s=self.lease_ttl_s)
            self._leases[pod_id] = lease
        self._update_lease_gauge()
        return lease

    def renew(self, pod_id: str) -> bool:
        """One renewal heartbeat. A pod mid-GC-pause misses these; the
        ``membership.renew.<pod>`` pause failpoint simulates exactly that
        by *aging* the lease instead of renewing it."""
        stall = failpoints.pause_seconds(FP_RENEW_PREFIX + pod_id)
        now = self._clock()
        with self._mu:
            lease = self._leases.get(pod_id)
            if lease is None:
                return False
            if stall > 0.0:
                # The renewal the zombie never sent: rewind the stamp so
                # the lease looks exactly ``stall`` seconds colder.
                lease.renewed_ts -= stall
                lapsed = self._lapse_locked(lease, now)
            else:
                if self._lapse_locked(lease, now):
                    # Lapsed leases don't renew — the pod must readmit
                    # through the warm-restart gate.
                    lapsed = True
                else:
                    lease.renewed_ts = now
                    lease.epoch = self._epoch
                    LEASE_RENEWALS.inc()
                    lapsed = False
        self._update_lease_gauge()
        return not lapsed and stall == 0.0

    def lease_valid(self, pod_id: str) -> bool:
        now = self._clock()
        with self._mu:
            lease = self._leases.get(pod_id)
            if lease is None:
                return False
            return not self._lapse_locked(lease, now)

    def readmit(self, pod_id: str, gate=None) -> bool:
        """Re-admit a lapsed pod through the PR 4 warm-restart gate.

        ``gate`` is the pod's :class:`~..recovery.manager.RecoveryManager`
        (anything with a truthy ``ready``): a zombie cannot simply ask
        back in — it must have re-run snapshot-restore + journal replay
        so its index view is rebuilt, not resumed."""
        if gate is not None:
            ready = gate.ready() if callable(getattr(gate, "ready", None)) \
                else getattr(gate, "ready", False)
            if not ready:
                self._reject("membership.readmit", REASON_NOT_READMITTED,
                             pod_id=pod_id, hard=True)
                return False
        self.grant(pod_id)
        LEASE_READMISSIONS.inc()
        fr_record(KIND_FENCE, {"event": "readmitted", "pod": pod_id,
                                  "epoch": self.epoch})
        return True

    def _lapse_locked(self, lease: Lease, now: float) -> bool:
        """Check + latch a lease's lapse state (callers hold the lock)."""
        if lease.remaining_s(now) >= 0.0:
            return lease.lapsed
        if not lease.lapsed:
            lease.lapsed = True
            LEASE_EXPIRED.inc()
            logger.warning("lease for pod %s lapsed (%.1fs past TTL)",
                           lease.pod_id, -lease.remaining_s(now))
        return True

    def _update_lease_gauge(self) -> None:
        now = self._clock()
        with self._mu:
            live = sum(1 for l in self._leases.values()
                       if l.remaining_s(now) >= 0.0)
        LEASE_ACTIVE.set(live)

    # -- fence checks -----------------------------------------------------

    def check_request(self, epoch: int, site: str) -> FenceDecision:
        """Read-path fence (score/lookup): epoch staleness only.

        Newer stamps are learned (piggyback); epoch 0 is a legacy peer
        and always clean."""
        # Hot path: same-epoch (or unstamped legacy) traffic. Lock-free —
        # a torn read across _epoch/_clean at worst detours to the slow
        # path below, never misclassifies.
        clean = self._clean
        if epoch == clean.epoch or not epoch:
            return clean
        epoch = int(epoch or 0)
        with self._mu:
            mine = self._epoch
        if epoch > mine:
            self.observe_epoch(epoch, source=site)
            return FenceDecision(allowed=True, epoch=epoch)
        if epoch and epoch < mine:
            return self._reject(site, REASON_STALE_EPOCH, stamp=epoch)
        return self._clean

    def check_write(self, pod_id: str, epoch: int, site: str) -> FenceDecision:
        """Write-path fence (event ingest, handoff): the epoch check plus
        the zombie check — a pod under lease management whose lease
        lapsed gets its writes refused until it re-admits. Pods never
        granted a lease (legacy / solo deployments) are not fenced."""
        now = self._clock()
        with self._mu:
            mine = self._epoch
            lease = self._leases.get(pod_id)
            lapsed = lease is not None and self._lapse_locked(lease, now)
        if lapsed:
            return self._reject(site, REASON_LEASE_LAPSED, pod_id=pod_id)
        return self.check_request(epoch, site)

    def _reject(self, site: str, reason: str, pod_id: str = "",
                stamp: int = 0, hard: bool = False) -> FenceDecision:
        mine = self.epoch
        record_fence_rejection(site, reason)
        fr_record(KIND_FENCE, {"event": "rejected", "site": site,
                                  "reason": reason, "pod": pod_id,
                                  "stamp": stamp, "epoch": mine})
        entry = {"ts": time.time(), "site": site, "reason": reason,
                 "pod": pod_id, "stamp": stamp, "epoch": mine}
        rejecting = hard or self.fence_mode == FENCE_REJECT
        with self._mu:
            self._recent.append(entry)
            if rejecting:
                self.rejections += 1
            else:
                self.flagged += 1
        if rejecting:
            return FenceDecision(allowed=False, reason=reason, epoch=mine)
        return FenceDecision(allowed=True, reason=reason, flagged=True,
                             epoch=mine)

    # -- introspection ----------------------------------------------------

    def debug_view(self) -> dict:
        """The ``/debug/membership`` payload (and kvdiag's fleet section):
        epoch, per-pod lease ages, and the recent rejection ring."""
        now = self._clock()
        with self._mu:
            leases = {
                pod: {
                    "epoch": l.epoch,
                    "age_s": round(l.age_s(now), 3),
                    "remaining_s": round(l.remaining_s(now), 3),
                    "lapsed": l.lapsed or l.remaining_s(now) < 0.0,
                }
                for pod, l in sorted(self._leases.items())
            }
            return {
                "epoch": self._epoch,
                "fence_mode": self.fence_mode,
                "lease_ttl_s": self.lease_ttl_s,
                "lease_renew_s": self.lease_renew_s,
                "leases": leases,
                "rejections": self.rejections,
                "flagged": self.flagged,
                "recent_rejections": list(self._recent),
            }
