"""Sharded indexer control plane (docs/architecture.md "Sharded control
plane"): consistent-hash partitioning of the block index across N
indexer shard replicas, scatter-gather scoring, replica failover, and
the epoch-fenced membership plane (leases + fencing tokens)."""

from .config import ClusterConfig
from .membership import FenceDecision, Lease, MembershipTable
from .ring import HashRing, assignment_fingerprint, moved_partitions, plan_owners
from .router import DegradedShardError, RouterScore, ShardRouter
from .sharded_index import ShardedIndex, ShardFilterIndex

__all__ = [
    "ClusterConfig",
    "DegradedShardError",
    "FenceDecision",
    "HashRing",
    "Lease",
    "MembershipTable",
    "RouterScore",
    "ShardRouter",
    "ShardedIndex",
    "ShardFilterIndex",
    "assignment_fingerprint",
    "moved_partitions",
    "plan_owners",
]
