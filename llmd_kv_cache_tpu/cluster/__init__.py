"""Sharded indexer control plane (docs/architecture.md "Sharded control
plane"): consistent-hash partitioning of the block index across N
indexer shard replicas, scatter-gather scoring, and replica failover."""

from .config import ClusterConfig
from .ring import HashRing, assignment_fingerprint, moved_partitions, plan_owners
from .router import DegradedShardError, RouterScore, ShardRouter
from .sharded_index import ShardedIndex, ShardFilterIndex

__all__ = [
    "ClusterConfig",
    "DegradedShardError",
    "HashRing",
    "RouterScore",
    "ShardRouter",
    "ShardedIndex",
    "ShardFilterIndex",
    "assignment_fingerprint",
    "moved_partitions",
    "plan_owners",
]
