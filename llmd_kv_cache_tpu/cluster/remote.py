"""Inter-shard RPC client + cross-replica anti-entropy digest source.

:class:`ShardClient` speaks the msgpack-over-gRPC shard surface of
``services.indexer_service`` (``LookupBlocks`` for scatter-gather,
``ListPods``/``GetPodDigest``/``GetPodBlocks`` for repair), over the
shared channel pool and under the same retry policy as scoring RPCs.

:class:`RemoteShardDigestSource` lifts the PR 4 intra-process
anti-entropy reconciler to inter-node repair: it implements the
``recovery.reconcile.DigestSource`` protocol over the *other* replicas
of a shard's key range. A restarted shard bootstraps from its own
snapshot+journal, then reconciles against its peers — every key it owns
with ``replication_factor >= 2`` has at least one other live owner, so
the union of peer views (filtered to locally-owned keys) is the truth
to converge to.
"""

from __future__ import annotations

from typing import Optional, Sequence

import msgpack

from ..core.keys import BlockHash, PodEntry
from ..recovery.reconcile import digest_from_blocks
from ..resilience.policy import RetryPolicy
from ..utils.logging import get_logger
from .ring import HashRing

logger = get_logger("cluster.remote")


def entry_from_row(row: Sequence) -> PodEntry:
    """Snapshot wire row ``[pod, tier, flags, group_idx]`` → PodEntry."""
    return PodEntry(
        pod_identifier=row[0],
        device_tier=row[1],
        speculative=bool(int(row[2]) & 1),
        has_group=bool(int(row[2]) & 2),
        group_idx=row[3],
    )


def _pack(d: dict) -> bytes:
    return msgpack.packb(d, use_bin_type=True)


def _unpack(b: bytes) -> dict:
    return msgpack.unpackb(b, raw=False, strict_map_key=False)


class ShardClient:
    """Router/peer-side client for one indexer shard replica."""

    def __init__(self, address: str, timeout_s: float = 2.0,
                 retry_policy: Optional[RetryPolicy] = None):
        # Deferred to call time elsewhere would hide config typos; the
        # shared pool makes construction cheap enough to do eagerly.
        from ..services import channel_pool
        from ..services.indexer_service import DEFAULT_RPC_RETRY_POLICY, SERVICE_NAME

        self.address = address
        self._channel = channel_pool.acquire(address)
        self._timeout = timeout_s
        self.retry_policy = retry_policy or DEFAULT_RPC_RETRY_POLICY

        def method(name: str):
            return self._channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=_pack,
                response_deserializer=_unpack,
            )

        self._lookup_blocks = method("LookupBlocks")
        self._lookup_blocks_batch = method("LookupBlocksBatch")
        self._list_pods = method("ListPods")
        self._pod_digest = method("GetPodDigest")
        self._pod_blocks = method("GetPodBlocks")

    def lookup_blocks(
        self,
        keys: Sequence[BlockHash],
        pods: Optional[Sequence[str]] = None,
        timeout: Optional[float] = None,
        deadline: Optional["object"] = None,
        hedge: bool = False,
        epoch: int = 0,
    ) -> dict:
        """Raw lookup: ``{"hits": {key: [PodEntry,...]}, "degraded": bool,
        "shard": str, "epoch": int}``. Raises grpc.RpcError on transport
        failure (the router's breaker/failover logic owns error handling).

        ``deadline`` (a resilience.deadline.Deadline) rides the frame as
        the tolerant ``deadline_ms`` relative budget and caps the client
        timeout; ``hedge`` tags the frame so shards can count hedged load;
        ``epoch`` stamps the caller's topology epoch (cluster.membership)
        the same tolerant way, and the server's own epoch rides back on
        the response for piggyback learning (all three keys are ignored
        by older peers)."""
        from ..resilience.deadline import Deadline
        from ..services.indexer_service import _call_rpc

        frame = {"keys": [int(k) for k in keys], "pods": list(pods or [])}
        eff_timeout = timeout if timeout is not None else self._timeout
        if isinstance(deadline, Deadline):
            frame["deadline_ms"] = deadline.to_wire_ms()
            eff_timeout = deadline.cap_timeout(eff_timeout)
        if hedge:
            frame["hedge"] = True
        if epoch:
            frame["epoch"] = int(epoch)
        resp = _call_rpc(
            self._lookup_blocks,
            frame,
            eff_timeout,
            self.retry_policy,
        )
        hits: dict[BlockHash, list[PodEntry]] = {}
        for key, rows in resp.get("hits", []):
            hits[int(key)] = [entry_from_row(r) for r in rows]
        return {
            "hits": hits,
            "degraded": bool(resp.get("degraded", False)),
            "shard": resp.get("shard", "") or "",
            "epoch": int(resp.get("epoch", 0) or 0),
        }

    def lookup_blocks_batch(
        self,
        chunks: Sequence[Sequence[BlockHash]],
        pods: Optional[Sequence[str]] = None,
        timeout: Optional[float] = None,
        deadline: Optional["object"] = None,
        hedge: bool = False,
        epoch: int = 0,
    ) -> dict:
        """Framed multi-chunk lookup (the batched fan-out data plane):
        one RPC carries a whole gather window's worth of early-exit
        chunks and the shard answers them in order with per-chunk
        continuation flags, early-exiting at its first incomplete chunk.

        Returns ``{"hits": {key: [PodEntry,...]}, "cont": [bool,...],
        "degraded": bool, "shard": str}`` with ``hits`` flattened across
        the answered chunks — the router re-derives chunk boundaries from
        its own key order, so a response missing ``cont`` (or answering
        the flat old-frame shape) degrades gracefully to "every answered
        key counts". Raises grpc.RpcError on transport failure, including
        UNIMPLEMENTED from a pre-batch shard (the router's cue to fall
        back to per-chunk ``lookup_blocks``)."""
        from ..resilience.deadline import Deadline
        from ..services.indexer_service import _call_rpc

        frame = {
            "chunks": [[int(k) for k in c] for c in chunks],
            "pods": list(pods or []),
        }
        eff_timeout = timeout if timeout is not None else self._timeout
        if isinstance(deadline, Deadline):
            frame["deadline_ms"] = deadline.to_wire_ms()
            eff_timeout = deadline.cap_timeout(eff_timeout)
        if hedge:
            frame["hedge"] = True
        if epoch:
            frame["epoch"] = int(epoch)
        resp = _call_rpc(
            self._lookup_blocks_batch,
            frame,
            eff_timeout,
            self.retry_policy,
        )
        raw = resp.get("chunks")
        if raw is None:
            # Old-frame tolerance: a peer that answered the flat
            # LookupBlocks layout — one implicit chunk.
            raw = [resp.get("hits", [])]
        hits: dict[BlockHash, list[PodEntry]] = {}
        for chunk_hits in raw:
            for key, rows in chunk_hits:
                hits[int(key)] = [entry_from_row(r) for r in rows]
        return {
            "hits": hits,
            "cont": [bool(f) for f in resp.get("cont", []) or []],
            "degraded": bool(resp.get("degraded", False)),
            "shard": resp.get("shard", "") or "",
            "epoch": int(resp.get("epoch", 0) or 0),
        }

    def list_pods(self, timeout: Optional[float] = None) -> list[str]:
        from ..services.indexer_service import _call_rpc

        resp = _call_rpc(self._list_pods, {},
                         timeout if timeout is not None else self._timeout,
                         self.retry_policy)
        return list(resp.get("pods", []))

    def pod_digest(self, pod: str, timeout: Optional[float] = None) -> dict:
        from ..services.indexer_service import _call_rpc

        resp = _call_rpc(self._pod_digest, {"pod": pod},
                         timeout if timeout is not None else self._timeout,
                         self.retry_policy)
        return {"count": int(resp.get("count", 0)),
                "digest": int(resp.get("digest", 0))}

    def pod_blocks(self, pod: str, timeout: Optional[float] = None) -> dict:
        """``{request_key: {row_tuple, ...}}`` — the reconcile wire shape."""
        from ..services.indexer_service import _call_rpc

        resp = _call_rpc(self._pod_blocks, {"pod": pod},
                         timeout if timeout is not None else self._timeout,
                         self.retry_policy)
        return {
            int(key): {tuple(r) for r in rows}
            for key, rows in resp.get("blocks", [])
        }

    def close(self) -> None:
        from ..services import channel_pool

        channel_pool.release(self.address)


class RemoteShardDigestSource:
    """``DigestSource`` over the union of a shard's replica peers.

    ``blocks(pod)`` merges every reachable peer's advertised blocks,
    filtered to the keys ``shard_id`` owns — exactly the set the local
    index should converge to. ``digest(pod)`` is computed client-side
    from that merged view (peers answer with their *own* key ranges, so
    their server-side digests are not directly comparable); this trades
    a full fetch per round for correctness, which is fine at repair
    cadence. Unreachable peers are skipped — repair proceeds on the
    replicas that are up.
    """

    def __init__(self, peers: Sequence[ShardClient], ring: HashRing,
                 shard_id: str, replication_factor: int = 2):
        self.peers = list(peers)
        self.ring = ring
        self.shard_id = shard_id
        self.replication_factor = max(1, replication_factor)

    def _owns(self, key: BlockHash) -> bool:
        return self.shard_id in self.ring.owners(key, self.replication_factor)

    def pods(self) -> list:
        seen: set[str] = set()
        for peer in self.peers:
            try:
                seen.update(peer.list_pods())
            except Exception:  # lint: allow-swallow (dead peer; repair on the rest)
                logger.warning("digest peer %s unreachable (ListPods)", peer.address)
        return sorted(seen)

    def blocks(self, pod: str) -> dict:
        merged: dict = {}
        for peer in self.peers:
            try:
                view = peer.pod_blocks(pod)
            except Exception:  # lint: allow-swallow (dead peer; repair on the rest)
                logger.warning("digest peer %s unreachable (GetPodBlocks)", peer.address)
                continue
            for key, rows in view.items():
                if self._owns(key):
                    merged.setdefault(key, set()).update(rows)
        return merged

    def digest(self, pod: str) -> dict:
        return digest_from_blocks(self.blocks(pod))
