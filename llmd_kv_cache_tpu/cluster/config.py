"""Cluster configuration: shard membership + fan-out knobs.

Rides the usual camelCase/snake_case ``from_dict`` convention
(docs/configuration.md "clusterConfig"). The membership list is static
config — the same list every scheduler and every shard replica reads —
so all parties derive the identical :class:`~.ring.HashRing` (the ring's
determinism guarantee depends on it).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .ring import (
    DEFAULT_LOAD_FACTOR,
    DEFAULT_PARTITIONS,
    DEFAULT_VIRTUAL_NODES,
    HashRing,
)

# A degraded shard's keys are simply treated as index misses: the prefix
# chain breaks at the first unavailable block and scoring proceeds on
# what the healthy shards returned. The alternative ("fail") turns an
# unreachable shard into a scoring error — only for deployments that
# prefer loud failure over quietly shorter prefixes.
DEGRADED_SERVE_SKIP = "skip"
DEGRADED_SERVE_FAIL = "fail"


@dataclass
class ClusterConfig:
    """Sharded indexer control-plane knobs."""

    # Shard membership: one gRPC address per indexer shard replica. The
    # addresses double as shard ids unless shard_ids overrides them.
    shard_addresses: list[str] = field(default_factory=list)
    # Optional stable shard ids (defaults to the addresses). Useful when
    # addresses are ephemeral but identity must survive reschedules.
    shard_ids: list[str] = field(default_factory=list)
    # This replica's own shard id; empty on scheduler/router-side configs.
    shard_id: str = ""
    # shardCount is advisory/validation only: when set it must match the
    # membership size (catching config drift between fleet manifests).
    shard_count: int = 0
    # Ring shape (see cluster.ring).
    virtual_nodes: int = DEFAULT_VIRTUAL_NODES
    partitions: int = DEFAULT_PARTITIONS
    load_factor: float = DEFAULT_LOAD_FACTOR
    # How many distinct shards ingest each block key (1 = no redundancy;
    # 2 lets scoring fail over and anti-entropy repair a restarted shard).
    replication_factor: int = 2
    # Scatter-gather fan-out: per-chunk RPC deadline and the chunk size in
    # block keys (generalizes the single-index lookupChunkSize early exit
    # to cross-shard fan-out).
    fanout_timeout_s: float = 2.0
    fanout_chunk_blocks: int = 128
    # Batched fan-out (docs/architecture.md "Native data plane"): how many
    # early-exit chunks ride one framed LookupBlocksBatch RPC per shard.
    # Each gather window covers fanout_chunk_blocks * fanout_batch_chunks
    # keys with ONE RPC per owning shard instead of one per chunk; the
    # shard early-exits server-side at its first incomplete chunk and the
    # router truncates the merged map in chunk order, so scores stay
    # byte-identical to the per-chunk path. 0 disables (per-chunk RPCs).
    fanout_batch_chunks: int = 8
    degraded_serve_mode: str = DEGRADED_SERVE_SKIP
    # Ring-plan prefix cache entries (0 disables): (ring version, key
    # count, last chained key) → per-key owner plan.
    plan_cache_size: int = 2048
    # One overall scatter-gather deadline per chunk (seconds). 0 derives
    # it from fanout_timeout_s (the gather never outlives one RPC budget,
    # however many failovers/hedges run inside it). The ambient request
    # deadline, when present, caps it further.
    fanout_deadline_s: float = 0.0
    # Inter-shard circuit breaker (resilience.policy.CircuitBreaker).
    breaker_failure_threshold: int = 3
    breaker_reset_timeout_s: float = 5.0
    # Tail-tolerant hedged fan-out (resilience.hedging): when a shard's
    # RPC outlives its adaptive latency-quantile trigger, the same lookup
    # is issued to the keys' replica owner and the first response wins.
    hedge_enabled: bool = True
    # Latency quantile that arms the hedge trigger per shard (p95: only
    # the slowest ~5% of RPCs ever hedge on a healthy shard).
    hedge_quantile: float = 0.95
    # Floor on the hedge trigger delay — never hedge faster than this
    # even when a shard's quantile estimate collapses.
    hedge_min_delay_s: float = 0.002
    # Hedge budget: token bucket refilled by primary traffic. rate is the
    # steady-state hedge fraction cap; burst bounds accumulated credit.
    hedge_budget_rate: float = 0.1
    hedge_budget_burst: float = 8.0
    # Epoch fencing (cluster.membership): how receivers treat traffic
    # stamped with an older topology epoch than their own, and the
    # renewable pod-lease window that fences zombies deterministically.
    # "warn" counts/flags but serves (safe rollout default — legacy peers
    # never stamp an epoch at all); "reject" refuses stale writes.
    fence_mode: str = "warn"
    lease_ttl_s: float = 30.0
    lease_renew_s: float = 10.0

    def membership(self) -> list[str]:
        """Shard ids, index-aligned with shard_addresses."""
        if self.shard_ids:
            if len(self.shard_ids) != len(self.shard_addresses):
                raise ValueError(
                    f"shardIds ({len(self.shard_ids)}) and shardAddresses "
                    f"({len(self.shard_addresses)}) must be index-aligned"
                )
            return list(self.shard_ids)
        return list(self.shard_addresses)

    def address_of(self, shard_id: str) -> str:
        members = self.membership()
        try:
            return self.shard_addresses[members.index(shard_id)]
        except ValueError:
            raise KeyError(f"unknown shard id {shard_id!r}") from None

    def build_ring(self, epoch: int = 0) -> HashRing:
        members = self.membership()
        if self.shard_count and self.shard_count != len(members):
            raise ValueError(
                f"shardCount={self.shard_count} disagrees with the "
                f"{len(members)}-entry membership list"
            )
        return HashRing(
            members,
            virtual_nodes=self.virtual_nodes,
            partitions=self.partitions,
            load_factor=self.load_factor,
            epoch=epoch,
        )

    @property
    def enabled(self) -> bool:
        return len(self.shard_addresses) > 0

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ClusterConfig":
        if not d:
            return cls()
        vnodes = d.get("virtualNodes", d.get("virtual_nodes"))
        parts = d.get("partitions")
        rf = d.get("replicationFactor", d.get("replication_factor"))
        chunk = d.get("fanoutChunkBlocks", d.get("fanout_chunk_blocks"))
        batch = d.get("fanoutBatchChunks", d.get("fanout_batch_chunks"))
        plan = d.get("planCacheSize", d.get("plan_cache_size"))
        thresh = d.get("breakerFailureThreshold", d.get("breaker_failure_threshold"))
        return cls(
            shard_addresses=list(
                d.get("shardAddresses", d.get("shard_addresses", []))
            ),
            shard_ids=list(d.get("shardIds", d.get("shard_ids", []))),
            shard_id=d.get("shardId", d.get("shard_id", "")) or "",
            shard_count=d.get("shardCount", d.get("shard_count", 0)) or 0,
            virtual_nodes=DEFAULT_VIRTUAL_NODES if vnodes is None else vnodes,
            partitions=DEFAULT_PARTITIONS if parts is None else parts,
            load_factor=d.get(
                "loadFactor", d.get("load_factor", DEFAULT_LOAD_FACTOR)
            ),
            replication_factor=2 if rf is None else rf,
            fanout_timeout_s=d.get(
                "fanoutTimeoutS", d.get("fanout_timeout_s", 2.0)
            ),
            fanout_chunk_blocks=128 if chunk is None else chunk,
            fanout_batch_chunks=8 if batch is None else batch,
            degraded_serve_mode=d.get(
                "degradedServeMode",
                d.get("degraded_serve_mode", DEGRADED_SERVE_SKIP),
            )
            or DEGRADED_SERVE_SKIP,
            plan_cache_size=2048 if plan is None else plan,
            fanout_deadline_s=d.get(
                "fanoutDeadlineS", d.get("fanout_deadline_s", 0.0)
            ),
            breaker_failure_threshold=3 if thresh is None else thresh,
            breaker_reset_timeout_s=d.get(
                "breakerResetTimeoutS", d.get("breaker_reset_timeout_s", 5.0)
            ),
            hedge_enabled=bool(d.get(
                "hedgeEnabled", d.get("hedge_enabled", True)
            )),
            hedge_quantile=d.get(
                "hedgeQuantile", d.get("hedge_quantile", 0.95)
            ),
            hedge_min_delay_s=d.get(
                "hedgeMinDelayS", d.get("hedge_min_delay_s", 0.002)
            ),
            hedge_budget_rate=d.get(
                "hedgeBudgetRate", d.get("hedge_budget_rate", 0.1)
            ),
            hedge_budget_burst=d.get(
                "hedgeBudgetBurst", d.get("hedge_budget_burst", 8.0)
            ),
            fence_mode=d.get("fenceMode", d.get("fence_mode", "warn"))
            or "warn",
            lease_ttl_s=d.get("leaseTtlS", d.get("lease_ttl_s", 30.0)),
            lease_renew_s=d.get("leaseRenewS", d.get("lease_renew_s", 10.0)),
        )
