"""Scatter-gather scoring router over the sharded indexer fleet.

The scheduler-side counterpart of the shard replicas: tokens are
content-addressed locally (same ``ChunkedTokenDatabase`` + prefix-key
cache as an embedded indexer), block keys are partitioned by the
consistent-hash ring, and ``LookupBlocks`` RPCs fan out per owning
shard. Scoring then runs locally with the ordinary
``LongestPrefixScorer`` over the merged hit map.

Early exit generalizes PR 2's chunked lookup to cross-shard fan-out:
keys are processed in chain order, ``fanoutChunkBlocks`` at a time, and
fanning stops at the first chunk that breaks the longest-prefix chain —
deep misses never pay cross-shard round trips.

Failure policy lifts the PR 1 primitives to inter-node scope: every
shard sits behind a :class:`~llmd_kv_cache_tpu.resilience.policy.
CircuitBreaker`; a broken or unreachable shard is skipped, its keys
retried on their replica owners (``replicationFactor``), and only if no
owner is reachable are the keys served *degraded* — treated as index
misses under ``degradedServeMode: skip`` (the default), so scoring
never blocks on a dead shard.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..core.keys import BlockHash, PodEntry
from ..core.token_processor import ChunkedTokenDatabase, TokenProcessorConfig
from ..resilience.policy import CircuitBreaker
from ..scoring.scorer import KVBlockScorerConfig, create_scorer
from ..telemetry import tracer
from ..utils.logging import get_logger
from ..utils.lru import LRUCache
from .config import DEGRADED_SERVE_FAIL, ClusterConfig
from .remote import ShardClient
from .ring import HashRing

logger = get_logger("cluster.router")


class DegradedShardError(RuntimeError):
    """Raised under ``degradedServeMode: fail`` when owners of some keys
    are all unreachable."""

    def __init__(self, shards: Sequence[str]):
        super().__init__(f"shards unreachable: {sorted(shards)}")
        self.shards = sorted(shards)


@dataclass
class RouterScore:
    """One scatter-gather scoring result."""

    scores: dict[str, float] = field(default_factory=dict)
    # Unreachable shards whose keys no replica owner could serve either.
    # Non-empty means the prefix view was incomplete and scores are a
    # lower bound. A failed shard fully covered by replica failover is
    # NOT listed (scores stayed exact).
    degraded_shards: list[str] = field(default_factory=list)
    # True when any serving shard was itself warming (post-restart) or
    # any shard was skipped — routers should widen their fallback.
    degraded: bool = False
    # Fan-out accounting (bench/debug).
    blocks: int = 0
    hit_blocks: int = 0
    rpcs: int = 0


class ShardRouter:
    """Client-side scatter-gather scorer for a sharded indexer fleet."""

    def __init__(
        self,
        config: ClusterConfig,
        token_processor_config: Optional[TokenProcessorConfig] = None,
        scorer_config: Optional[KVBlockScorerConfig] = None,
        clients: Optional[dict[str, ShardClient]] = None,
    ):
        if not config.enabled:
            raise ValueError("ClusterConfig has no shardAddresses")
        self.cfg = config
        self.ring: HashRing = config.build_ring()
        self.token_processor = ChunkedTokenDatabase(
            token_processor_config or TokenProcessorConfig()
        )
        self.scorer = create_scorer(
            scorer_config or KVBlockScorerConfig(),
            block_size_tokens=self.token_processor.block_size,
        )
        members = config.membership()
        self.clients = clients if clients is not None else {
            sid: ShardClient(config.address_of(sid),
                             timeout_s=config.fanout_timeout_s)
            for sid in members
        }
        self.breakers = {
            sid: CircuitBreaker(
                target=f"shard:{sid}",
                failure_threshold=config.breaker_failure_threshold,
                reset_timeout_s=config.breaker_reset_timeout_s,
            )
            for sid in members
        }
        # Ring-plan prefix cache: block keys are chained FNV hashes, so
        # keys[-1] fingerprints the entire chain — (ring version, chain
        # length, last key) uniquely identifies the per-key owner plan at
        # the same trust level as the token-processor's prefix-key cache.
        self._plan_cache: Optional[LRUCache] = (
            LRUCache(config.plan_cache_size) if config.plan_cache_size > 0 else None
        )
        self.plan_hits = 0
        self.plan_misses = 0
        self._executor = ThreadPoolExecutor(
            max_workers=max(4, 2 * len(members)),
            thread_name_prefix="kvtpu-shard-fanout",
        )
        # Residency-aware disaggregated routing (scoring.residency): when
        # attached, ``score(role="decode")`` adds each decode pod's
        # transferred-prefix bonus on top of the scatter-gathered prefix
        # scores — the shards know nothing about in-flight handoffs, the
        # tracker is router-local state fed by the handoff coordinator.
        self.residency = None
        self._publish_ring_metrics()

    def attach_residency(self, tracker) -> None:
        """Wire a :class:`~..scoring.residency.ResidencyTracker` for
        role-aware decode scoring."""
        self.residency = tracker

    # -- plan cache -------------------------------------------------------

    def plan(self, keys: Sequence[BlockHash]) -> tuple[str, ...]:
        """Primary owner per key, via the chained-fingerprint plan cache."""
        if not keys:
            return ()
        cache = self._plan_cache
        if cache is None:
            return tuple(self.ring.owner(k) for k in keys)
        cache_key = (self.ring.version, len(keys), keys[-1])
        plan = cache.get(cache_key)
        hit = plan is not None
        if hit:
            self.plan_hits += 1
        else:
            self.plan_misses += 1
            plan = tuple(self.ring.owner(k) for k in keys)
            cache.add(cache_key, plan)
        try:
            from ..metrics.collector import record_shard_plan_cache

            record_shard_plan_cache(hit)
        except Exception:  # pragma: no cover - metrics must never break scoring  # lint: allow-swallow
            pass
        return plan

    # -- fan-out ----------------------------------------------------------

    def _shard_rpc(
        self, shard: str, keys: list[BlockHash], pods: Optional[Sequence[str]]
    ) -> dict:
        """One breaker-guarded LookupBlocks against one shard."""
        breaker = self.breakers[shard]
        if not breaker.allow():
            self._record_rpc(shard, "skipped")
            raise ConnectionError(f"breaker open for shard {shard}")
        try:
            res = self.clients[shard].lookup_blocks(
                keys, pods, timeout=self.cfg.fanout_timeout_s
            )
        except Exception:
            breaker.record_failure()
            self._record_rpc(shard, "failure")
            raise
        breaker.record_success()
        self._record_rpc(shard, "success")
        return res

    def _fanout_chunk(
        self,
        keys: Sequence[BlockHash],
        pods: Optional[Sequence[str]],
        plan: Sequence[str],
        stats: RouterScore,
    ) -> dict[BlockHash, list[PodEntry]]:
        """Scatter one chunk across its owning shards, failing keys over
        to replica owners; returns the merged hit map."""
        remaining: dict[str, list[BlockHash]] = {}
        for key, owner in zip(keys, plan):
            remaining.setdefault(owner, []).append(key)

        merged: dict[BlockHash, list[PodEntry]] = {}
        excluded: set[str] = set()
        dropped = False
        for _attempt in range(max(1, self.cfg.replication_factor)):
            if not remaining:
                break
            futures = {
                shard: self._executor.submit(
                    self._shard_rpc, shard, skeys, pods
                )
                for shard, skeys in remaining.items()
            }
            stats.rpcs += len(futures)
            failed: dict[str, list[BlockHash]] = {}
            for shard, fut in futures.items():
                try:
                    res = fut.result(timeout=self.cfg.fanout_timeout_s * 2)
                except Exception:
                    failed[shard] = remaining[shard]
                    continue
                merged.update(res["hits"])
                if res["degraded"]:
                    stats.degraded = True
            if not failed:
                remaining = {}
                break
            excluded.update(failed)
            # Re-route each failed shard's keys to their next distinct
            # owner; keys whose owners are all excluded go unserved.
            remaining = {}
            dead_keys = 0
            for skeys in failed.values():
                for key in skeys:
                    nxt = next(
                        (s for s in self.ring.owners(
                            key, self.cfg.replication_factor)
                         if s not in excluded),
                        None,
                    )
                    if nxt is None:
                        dead_keys += 1
                    else:
                        remaining.setdefault(nxt, []).append(key)
            if dead_keys:
                dropped = True
                break
        # A failed shard whose keys a replica fully served does NOT
        # degrade the result (scores are exact; the failure still shows
        # in breaker state and kvtpu_shard_rpcs_total). Only keys no
        # reachable owner could serve make scores a lower bound.
        if remaining:
            dropped = True
        if dropped and excluded:
            stats.degraded = True
            stats.degraded_shards = sorted(
                set(stats.degraded_shards) | excluded
            )
            self._record_degraded(len(excluded))
        return merged

    # -- scoring ----------------------------------------------------------

    def score(
        self,
        tokens: Sequence[int],
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        role: str = "",
    ) -> RouterScore:
        """Scatter-gather GetPodScores: returns scores plus degradation
        detail (shard metadata mirrors the ScoreResponse wire fields).

        ``role="decode"`` adds transferred-prefix residency bonuses when
        a tracker is attached (``attach_residency``) — same semantics as
        the embedded indexer's role-aware scoring.
        """
        started = time.perf_counter()
        result = RouterScore()
        with tracer().span(
            "llm_d.kv_cache.cluster.fanout",
            model=model_name,
            token_count=len(tokens),
            shard_count=len(self.ring.shards),
            role=role,
            process="router",
        ) as span:
            keys = self.token_processor.tokens_to_kv_block_keys(
                0, list(tokens), model_name
            )
            result.blocks = len(keys)
            if not keys:
                return result
            plan = self.plan(keys)
            merged: dict[BlockHash, list[PodEntry]] = {}
            chunk = self.cfg.fanout_chunk_blocks
            if chunk <= 0:
                chunk = len(keys)
            for start in range(0, len(keys), chunk):
                ckeys = keys[start:start + chunk]
                found = self._fanout_chunk(
                    ckeys, pod_identifiers, plan[start:start + chunk], result
                )
                if not found:
                    break
                merged.update(found)
                # Same soundness argument as Index.lookup_chunked: a
                # partial chunk proves the consecutive-from-0 run ended
                # inside it, so later chunks cannot change any score.
                if len(found) < len(ckeys):
                    break
            if result.degraded_shards and (
                self.cfg.degraded_serve_mode == DEGRADED_SERVE_FAIL
            ):
                raise DegradedShardError(result.degraded_shards)
            result.hit_blocks = len(merged)
            result.scores = self.scorer.score(keys, merged)
            if role == "decode" and self.residency is not None:
                bonus = self.residency.bonus(
                    keys,
                    set(pod_identifiers) if pod_identifiers else None,
                )
                for pod, b in bonus.items():
                    result.scores[pod] = result.scores.get(pod, 0.0) + b
            span.set_attribute("block_count", len(keys))
            span.set_attribute("block_hit_count", len(merged))
            span.set_attribute("rpcs", result.rpcs)
            span.set_attribute("degraded_shards", len(result.degraded_shards))
        self._record_fanout(time.perf_counter() - started)
        return result

    def get_pod_scores(
        self,
        tokens: Sequence[int],
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
    ) -> dict[str, float]:
        return self.score(tokens, model_name, pod_identifiers).scores

    # -- telemetry --------------------------------------------------------

    def _record_rpc(self, shard: str, outcome: str) -> None:
        try:
            from ..metrics.collector import record_shard_rpc

            record_shard_rpc(shard, outcome)
        except Exception:  # pragma: no cover - metrics must never break fan-out  # lint: allow-swallow
            pass

    def _record_degraded(self, shards: int) -> None:
        try:
            from ..metrics.collector import record_shard_degraded_lookup

            record_shard_degraded_lookup(shards)
        except Exception:  # pragma: no cover - metrics must never break fan-out  # lint: allow-swallow
            pass

    def _record_fanout(self, seconds: float) -> None:
        try:
            from ..metrics.collector import record_shard_fanout

            record_shard_fanout(seconds)
        except Exception:  # pragma: no cover - metrics must never break fan-out  # lint: allow-swallow
            pass

    def _publish_ring_metrics(self) -> None:
        try:
            from ..metrics.collector import record_ring_load

            record_ring_load(self.ring.load())
        except Exception:  # pragma: no cover - metrics must never break startup  # lint: allow-swallow
            pass

    def debug_view(self) -> dict:
        return {
            "ring": self.ring.describe(),
            "breakers": {s: b.state for s, b in self.breakers.items()},
            "plan_cache": {
                "hits": self.plan_hits,
                "misses": self.plan_misses,
                "size": len(self._plan_cache) if self._plan_cache else 0,
            },
        }

    def close(self) -> None:
        self._executor.shutdown(wait=False)
        for client in self.clients.values():
            client.close()
