"""Scatter-gather scoring router over the sharded indexer fleet.

The scheduler-side counterpart of the shard replicas: tokens are
content-addressed locally (same ``ChunkedTokenDatabase`` + prefix-key
cache as an embedded indexer), block keys are partitioned by the
consistent-hash ring, and ``LookupBlocks`` RPCs fan out per owning
shard. Scoring then runs locally with the ordinary
``LongestPrefixScorer`` over the merged hit map.

Early exit generalizes PR 2's chunked lookup to cross-shard fan-out:
keys are processed in chain order, ``fanoutChunkBlocks`` at a time, and
fanning stops at the first chunk that breaks the longest-prefix chain —
deep misses never pay cross-shard round trips.

Failure policy lifts the PR 1 primitives to inter-node scope: every
shard sits behind a :class:`~llmd_kv_cache_tpu.resilience.policy.
CircuitBreaker`; a broken or unreachable shard is skipped, its keys
retried on their replica owners (``replicationFactor``), and only if no
owner is reachable are the keys served *degraded* — treated as index
misses under ``degradedServeMode: skip`` (the default), so scoring
never blocks on a dead shard.

Gray failures — a shard that is slow rather than dead — never trip the
breaker, so the gather *hedges* instead ("The Tail at Scale"): each
shard's RPC latency feeds a streaming quantile estimate, and a lookup
that outlives its shard's ``hedgeQuantile`` trigger is re-issued to the
keys' next replica owner; the first response wins and the loser is
cancelled. Hedges are capped by a token-bucket budget refilled by
primary traffic (``hedgeBudgetRate``), so a melting-down fleet cannot
double its own load. The whole chunk gather runs under ONE overall
deadline — ``fanoutDeadlineS`` capped by the ambient request deadline —
rather than accumulating per-future waits; keys still unresolved at the
deadline are served degraded, never silently late.
"""

from __future__ import annotations

import time
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..core.keys import BlockHash, PodEntry
from ..resilience.deadline import Deadline, current_deadline
from ..resilience.hedging import HedgeBudget, LatencyQuantileTracker
from ..core.token_processor import ChunkedTokenDatabase, TokenProcessorConfig
from ..resilience.policy import CircuitBreaker
from ..scoring.scorer import KVBlockScorerConfig, create_scorer
from ..telemetry import tracer
from ..telemetry.flight_recorder import KIND_HEDGE, record as record_event
from ..utils.logging import get_logger
from ..utils.lru import LRUCache
from .config import DEGRADED_SERVE_FAIL, ClusterConfig
from .remote import ShardClient
from .ring import HashRing

logger = get_logger("cluster.router")


class DegradedShardError(RuntimeError):
    """Raised under ``degradedServeMode: fail`` when owners of some keys
    are all unreachable."""

    def __init__(self, shards: Sequence[str]):
        super().__init__(f"shards unreachable: {sorted(shards)}")
        self.shards = sorted(shards)


@dataclass
class RouterScore:
    """One scatter-gather scoring result."""

    scores: dict[str, float] = field(default_factory=dict)
    # Unreachable shards whose keys no replica owner could serve either.
    # Non-empty means the prefix view was incomplete and scores are a
    # lower bound. A failed shard fully covered by replica failover is
    # NOT listed (scores stayed exact).
    degraded_shards: list[str] = field(default_factory=list)
    # True when any serving shard was itself warming (post-restart) or
    # any shard was skipped — routers should widen their fallback.
    degraded: bool = False
    # Fan-out accounting (bench/debug).
    blocks: int = 0
    hit_blocks: int = 0
    rpcs: int = 0
    # Hedged fan-out accounting: hedges issued for this score, and how
    # many beat their primary (the rest were wasted-but-bounded work).
    hedges: int = 0
    hedge_wins: int = 0
    # True when the overall gather deadline expired with lookups still in
    # flight — the result is a degraded lower bound, not silently late.
    deadline_expired: bool = False
    # Topology epoch this scatter-gather was pinned to (0 = no membership
    # plane attached) and how many responses arrived stamped with a newer
    # epoch — those are served degraded-not-fatal while the router's ring
    # catches up for the next score.
    epoch: int = 0
    cross_epoch: int = 0


@dataclass
class _Attempt:
    """One in-flight LookupBlocks attempt inside a chunk gather."""

    shard: str
    keys: list[BlockHash]
    keyset: frozenset
    future: Future
    started: float
    kind: str  # "primary" (incl. failover) | "hedge"
    hedged: bool = False  # a hedge decision was already made for this attempt
    settled: bool = False


class ShardRouter:
    """Client-side scatter-gather scorer for a sharded indexer fleet."""

    def __init__(
        self,
        config: ClusterConfig,
        token_processor_config: Optional[TokenProcessorConfig] = None,
        scorer_config: Optional[KVBlockScorerConfig] = None,
        clients: Optional[dict[str, ShardClient]] = None,
    ):
        if not config.enabled:
            raise ValueError("ClusterConfig has no shardAddresses")
        self.cfg = config
        self.ring: HashRing = config.build_ring()
        self.token_processor = ChunkedTokenDatabase(
            token_processor_config or TokenProcessorConfig()
        )
        self.scorer = create_scorer(
            scorer_config or KVBlockScorerConfig(),
            block_size_tokens=self.token_processor.block_size,
        )
        members = config.membership()
        self.clients = clients if clients is not None else {
            sid: ShardClient(config.address_of(sid),
                             timeout_s=config.fanout_timeout_s)
            for sid in members
        }
        self.breakers = {
            sid: CircuitBreaker(
                target=f"shard:{sid}",
                failure_threshold=config.breaker_failure_threshold,
                reset_timeout_s=config.breaker_reset_timeout_s,
            )
            for sid in members
        }
        # Ring-plan prefix cache: block keys are chained FNV hashes, so
        # keys[-1] fingerprints the entire chain — (ring version, chain
        # length, last key) uniquely identifies the per-key owner plan at
        # the same trust level as the token-processor's prefix-key cache.
        self._plan_cache: Optional[LRUCache] = (
            LRUCache(config.plan_cache_size) if config.plan_cache_size > 0 else None
        )
        self.plan_hits = 0
        self.plan_misses = 0
        # Hedging holds extra attempts in flight, and a gray-slow shard's
        # RPCs linger on their worker threads long after the gather moved
        # on (cancel() cannot stop a running future) — size the pool for
        # primary + hedge + several stale stragglers per shard, so a slow
        # shard cannot starve the next gather's submits.
        per_shard = 4 if config.hedge_enabled else 2
        self._executor = ThreadPoolExecutor(
            max_workers=max(4, per_shard * len(members)),
            thread_name_prefix="kvtpu-shard-fanout",
        )
        # Tail-tolerant hedging state: per-shard latency quantiles arm the
        # trigger, the budget caps hedges at a fraction of primary load.
        self.hedge_latency = LatencyQuantileTracker(
            quantile=config.hedge_quantile
        )
        self.hedge_budget = HedgeBudget(
            rate=config.hedge_budget_rate, burst=config.hedge_budget_burst
        )
        # Residency-aware disaggregated routing (scoring.residency): when
        # attached, ``score(role="decode")`` adds each decode pod's
        # transferred-prefix bonus on top of the scatter-gathered prefix
        # scores — the shards know nothing about in-flight handoffs, the
        # tracker is router-local state fed by the handoff coordinator.
        self.residency = None
        # Batched fan-out (docs/architecture.md "Native data plane"): one
        # framed multi-chunk RPC per shard per gather window instead of
        # one RPC per chunk. Engaged only when every client speaks the
        # batch surface — injected test doubles that implement only
        # lookup_blocks keep the per-chunk wire untouched. Shards whose
        # *server* predates the frame (UNIMPLEMENTED) are remembered here
        # and served through the legacy per-chunk call from then on.
        self._batch_capable = config.fanout_batch_chunks > 0 and all(
            hasattr(c, "lookup_blocks_batch") for c in self.clients.values()
        )
        self._legacy_shards: set[str] = set()
        self.batch_rpcs = 0
        self.batch_fallbacks = 0
        # Epoch discipline (cluster.membership): each scatter-gather pins
        # one epoch, responses stamped newer are degraded-not-fatal, and
        # an epoch bump swaps the ring plan atomically (one attribute
        # store — in-flight gathers keep their pinned ring snapshot).
        self.membership = None
        self.epoch_bumps = 0
        self.cross_epoch_responses = 0
        self._publish_ring_metrics()

    def attach_residency(self, tracker) -> None:
        """Wire a :class:`~..scoring.residency.ResidencyTracker` for
        role-aware decode scoring."""
        self.residency = tracker

    def attach_membership(self, table) -> None:
        """Wire a :class:`~.membership.MembershipTable`: scores stamp its
        epoch on every shard RPC, piggybacked newer epochs are learned
        back into it, and its bumps swap this router's ring plan."""
        self.membership = table
        table.add_epoch_listener(self._on_epoch_bump)
        if table.epoch != self.ring.epoch:
            self._on_epoch_bump(table.epoch)

    def _on_epoch_bump(self, epoch: int) -> None:
        """Atomic ring-plan swap on a topology-epoch bump. Membership is
        unchanged (a membership change builds a whole new router config);
        the new ring differs only in ``version``/``epoch``, so the plan
        cache misses cleanly and in-flight gathers finish on the ring
        object they captured."""
        self.ring = self.ring.with_epoch(epoch)
        self.epoch_bumps += 1
        self._publish_ring_metrics()

    # -- plan cache -------------------------------------------------------

    def plan(self, keys: Sequence[BlockHash],
             ring: Optional[HashRing] = None) -> tuple[str, ...]:
        """Primary owner per key, via the chained-fingerprint plan cache.

        ``ring`` lets a scatter-gather plan against the ring snapshot it
        pinned at entry rather than ``self.ring`` (which an epoch bump
        may swap mid-score)."""
        if not keys:
            return ()
        if ring is None:
            ring = self.ring
        cache = self._plan_cache
        if cache is None:
            return tuple(ring.owner(k) for k in keys)
        cache_key = (ring.version, len(keys), keys[-1])
        plan = cache.get(cache_key)
        hit = plan is not None
        if hit:
            self.plan_hits += 1
        else:
            self.plan_misses += 1
            plan = tuple(ring.owner(k) for k in keys)
            cache.add(cache_key, plan)
        try:
            from ..metrics.collector import record_shard_plan_cache

            record_shard_plan_cache(hit)
        except Exception:  # pragma: no cover - metrics must never break scoring  # lint: allow-swallow
            pass
        return plan

    # -- fan-out ----------------------------------------------------------

    def _shard_rpc(
        self,
        shard: str,
        keys: list[BlockHash],
        pods: Optional[Sequence[str]],
        timeout: Optional[float] = None,
        deadline: Optional[Deadline] = None,
        hedge: bool = False,
        epoch: int = 0,
    ) -> dict:
        """One breaker-guarded LookupBlocks against one shard."""
        breaker = self.breakers[shard]
        if not breaker.allow():
            self._record_rpc(shard, "skipped")
            raise ConnectionError(f"breaker open for shard {shard}")
        timeout_s = self.cfg.fanout_timeout_s if timeout is None else timeout
        kwargs = {}
        if deadline is not None:
            kwargs["deadline"] = deadline
        if hedge:
            kwargs["hedge"] = True
        if epoch:
            kwargs["epoch"] = epoch
        try:
            try:
                res = self.clients[shard].lookup_blocks(
                    keys, pods, timeout=timeout_s, **kwargs
                )
            except TypeError:
                # Injected test doubles may predate the deadline/hedge
                # kwargs; the wire fields are best-effort metadata.
                res = self.clients[shard].lookup_blocks(
                    keys, pods, timeout=timeout_s
                )
        except Exception:
            breaker.record_failure()
            self._record_rpc(shard, "failure")
            raise
        breaker.record_success()
        self._record_rpc(shard, "success")
        return res

    def _shard_rpc_batch(
        self,
        shard: str,
        keys: list[BlockHash],
        key_chunk: dict[BlockHash, int],
        pods: Optional[Sequence[str]],
        timeout: Optional[float] = None,
        deadline: Optional[Deadline] = None,
        hedge: bool = False,
        epoch: int = 0,
    ) -> dict:
        """One breaker-guarded LookupBlocksBatch: the shard's keys for a
        whole gather window, framed as ordered chunks. Falls back to the
        flat per-chunk wire *inside the same attempt* when the shard's
        server predates the batch frame (UNIMPLEMENTED), and remembers it
        in ``_legacy_shards`` so later gathers skip the probe."""
        breaker = self.breakers[shard]
        if not breaker.allow():
            self._record_rpc(shard, "skipped")
            raise ConnectionError(f"breaker open for shard {shard}")
        timeout_s = self.cfg.fanout_timeout_s if timeout is None else timeout
        by_chunk: dict[int, list[BlockHash]] = {}
        for k in keys:
            by_chunk.setdefault(key_chunk[k], []).append(k)
        chunks = [by_chunk[i] for i in sorted(by_chunk)]
        kwargs = {}
        if deadline is not None:
            kwargs["deadline"] = deadline
        if hedge:
            kwargs["hedge"] = True
        if epoch:
            kwargs["epoch"] = epoch
        try:
            if shard not in self._legacy_shards:
                try:
                    res = self.clients[shard].lookup_blocks_batch(
                        chunks, pods, timeout=timeout_s, **kwargs
                    )
                    self.batch_rpcs += 1
                    self._record_batch_rpc("batched")
                    breaker.record_success()
                    self._record_rpc(shard, "success")
                    return res
                except Exception as e:
                    if not self._unimplemented(e):
                        raise
                    # Old shard: not a failure, just an older wire. Replay
                    # the window flat — the plain lookup has no per-chunk
                    # state, so one call over all keys answers the same
                    # hits the per-chunk loop would have gathered.
                    self._legacy_shards.add(shard)
            self.batch_fallbacks += 1
            self._record_batch_rpc("fallback")
            try:
                res = self.clients[shard].lookup_blocks(
                    keys, pods, timeout=timeout_s, **kwargs
                )
            except TypeError:
                res = self.clients[shard].lookup_blocks(
                    keys, pods, timeout=timeout_s
                )
        except Exception:
            breaker.record_failure()
            self._record_rpc(shard, "failure")
            raise
        breaker.record_success()
        self._record_rpc(shard, "success")
        return res

    @staticmethod
    def _unimplemented(exc: BaseException) -> bool:
        try:
            import grpc

            if isinstance(exc, grpc.RpcError):
                code = exc.code() if callable(getattr(exc, "code", None)) else None
                return code == grpc.StatusCode.UNIMPLEMENTED
        except Exception:  # pragma: no cover - grpc always importable here  # lint: allow-swallow
            pass
        return isinstance(exc, (AttributeError, NotImplementedError))

    def _fanout_chunk(
        self,
        keys: Sequence[BlockHash],
        pods: Optional[Sequence[str]],
        plan: Sequence[str],
        stats: RouterScore,
        key_chunk: Optional[dict[BlockHash, int]] = None,
        ring: Optional[HashRing] = None,
        epoch: int = 0,
    ) -> dict[BlockHash, list[PodEntry]]:
        """Scatter one chunk across its owning shards under one overall
        gather deadline, hedging slow lookups and failing dead shards'
        keys over to replica owners; returns the merged hit map.

        With ``key_chunk`` (key → global chunk index) the unit is a whole
        gather *window*: each shard gets ONE framed LookupBlocksBatch RPC
        carrying its keys grouped by chunk, instead of one RPC per chunk.
        All the per-key machinery — rf-bounded failover, hedging, the
        overall deadline — is chunk-agnostic and applies unchanged;
        hedged and rerouted attempts re-frame their keys the same way.

        ``ring``/``epoch`` are the snapshot this gather is pinned to:
        reroutes and hedges resolve replica owners against that ring
        even if an epoch bump swaps ``self.ring`` mid-gather, and every
        RPC of the gather carries the same epoch stamp."""
        if ring is None:
            ring = self.ring
        rf = max(1, self.cfg.replication_factor)
        deadline = current_deadline()
        overall_s = self.cfg.fanout_deadline_s or self.cfg.fanout_timeout_s
        if deadline is not None:
            overall_s = deadline.cap_timeout(overall_s)
        gather_deadline = time.monotonic() + overall_s

        merged: dict[BlockHash, list[PodEntry]] = {}
        resolved: set[BlockHash] = set()
        dead: set[BlockHash] = set()
        # Per-key shards already attempted (primary, failover, or hedge):
        # a key visits each of its <= rf owners at most once, bounding the
        # gather at rf attempts per key.
        tried: dict[BlockHash, set[str]] = {
            k: {o} for k, o in zip(keys, plan)
        }
        failed_shards: set[str] = set()
        late_shards: set[str] = set()
        # Shards whose attempt in THIS gather ran slow enough to be
        # hedged (or failed outright): re-issues prefer other owners, so
        # a healthy shard's natural tail hedge never routes keys INTO
        # the straggler it is racing around.
        suspect: set[str] = set()
        attempts: list[_Attempt] = []

        def submit(shard: str, skeys: list[BlockHash], kind: str) -> None:
            budget_s = gather_deadline - time.monotonic()
            timeout_s = min(self.cfg.fanout_timeout_s, max(0.001, budget_s))
            if key_chunk is not None:
                fut = self._executor.submit(
                    self._shard_rpc_batch, shard, skeys, key_chunk, pods,
                    timeout_s, deadline, kind == "hedge", epoch,
                )
            else:
                fut = self._executor.submit(
                    self._shard_rpc, shard, skeys, pods, timeout_s, deadline,
                    kind == "hedge", epoch,
                )
            attempts.append(_Attempt(
                shard=shard, keys=skeys, keyset=frozenset(skeys),
                future=fut, started=time.monotonic(), kind=kind,
            ))
            stats.rpcs += 1
            if kind != "hedge":
                self.hedge_budget.on_primary()

        def covered_elsewhere(key: BlockHash, exclude: _Attempt) -> bool:
            return any(
                a is not exclude and not a.settled and key in a.keyset
                for a in attempts
            )

        def cancel_covered_losers() -> None:
            # First response won: cancel in-flight attempts whose keys are
            # all resolved. cancel() only stops a not-yet-running future;
            # one mid-RPC completes harmlessly and still feeds the
            # breaker/latency trackers from its worker thread.
            for a in attempts:
                if a.settled or not a.keyset.issubset(resolved):
                    continue
                a.settled = True
                a.future.cancel()
                if a.kind == "hedge":
                    self._record_hedge(a.shard, "loss")
                    record_event(KIND_HEDGE, {
                        "shard": a.shard, "outcome": "loss",
                    })

        def next_owner(key: BlockHash) -> Optional[str]:
            cands = [
                s for s in ring.owners(key, rf) if s not in tried[key]
            ]
            if not cands:
                return None
            return next((s for s in cands if s not in suspect), cands[0])

        def reroute(failed_keys: list[BlockHash]) -> None:
            regroup: dict[str, list[BlockHash]] = {}
            for key in failed_keys:
                nxt = next_owner(key)
                if nxt is None:
                    dead.add(key)
                else:
                    tried[key].add(nxt)
                    regroup.setdefault(nxt, []).append(key)
            for shard, skeys in regroup.items():
                submit(shard, skeys, "primary")

        def settle(a: _Attempt) -> None:
            a.settled = True
            try:
                res = a.future.result(timeout=0)
            except Exception:
                failed_shards.add(a.shard)
                suspect.add(a.shard)
                if a.kind == "hedge":
                    self._record_hedge(a.shard, "failed")
                orphans = [
                    k for k in a.keys
                    if k not in resolved and k not in dead
                    and not covered_elsewhere(k, a)
                ]
                if orphans:
                    reroute(orphans)
                return
            self.hedge_latency.observe(
                a.shard, time.monotonic() - a.started
            )
            fresh = [k for k in a.keys if k not in resolved]
            resolved.update(fresh)
            for key, entries in res["hits"].items():
                merged.setdefault(key, entries)
            if res["degraded"]:
                stats.degraded = True
            # Cross-epoch response: the shard has moved to a newer
            # topology than this gather pinned. Its hits still count —
            # degraded-not-fatal — and the piggybacked epoch advances
            # the membership table so the NEXT score plans on the new
            # ring (the in-flight gather keeps its pinned snapshot).
            resp_epoch = int(res.get("epoch", 0) or 0)
            if epoch and resp_epoch > epoch:
                stats.degraded = True
                stats.cross_epoch += 1
                self.cross_epoch_responses += 1
                if self.membership is not None:
                    self.membership.observe_epoch(
                        resp_epoch, source=f"router:{a.shard}")
            if a.kind == "hedge" and fresh:
                stats.hedge_wins += 1
                self._record_hedge(a.shard, "win")
                record_event(KIND_HEDGE, {
                    "shard": a.shard, "outcome": "win",
                    "keys": len(fresh),
                })
            cancel_covered_losers()

        def maybe_hedge(a: _Attempt) -> None:
            a.hedged = True  # one hedge decision per attempt
            # Slow enough to hedge = suspect for the rest of the gather,
            # whether or not the budget grants the hedge.
            suspect.add(a.shard)
            if not self.hedge_budget.spend():
                self._record_hedge(a.shard, "denied")
                return
            regroup: dict[str, list[BlockHash]] = {}
            for key in a.keys:
                if key in resolved or key in dead:
                    continue
                nxt = next_owner(key)
                if nxt is not None:
                    tried[key].add(nxt)
                    regroup.setdefault(nxt, []).append(key)
            if not regroup:
                return
            for shard, skeys in regroup.items():
                submit(shard, skeys, "hedge")
                stats.hedges += 1
                self._record_hedge(shard, "issued")
                record_event(KIND_HEDGE, {
                    "shard": shard, "outcome": "issued",
                    "slow_shard": a.shard, "keys": len(skeys),
                })

        # Initial scatter: group keys by primary owner.
        groups: dict[str, list[BlockHash]] = {}
        for key, owner in zip(keys, plan):
            groups.setdefault(owner, []).append(key)
        for shard, skeys in groups.items():
            submit(shard, skeys, "primary")

        hedging = self.cfg.hedge_enabled and rf > 1
        while True:
            if all(k in resolved or k in dead for k in keys):
                break
            live = [a for a in attempts if not a.settled]
            if not live:
                dead.update(
                    k for k in keys if k not in resolved and k not in dead
                )
                break
            now = time.monotonic()
            if now >= gather_deadline:
                # Overall gather deadline: stop waiting. The straggler
                # RPCs finish (or time out) on their worker threads and
                # feed breakers/latency stats; their keys are served
                # degraded rather than late.
                for a in live:
                    a.settled = True
                    a.future.cancel()
                    late_shards.add(a.shard)
                dead.update(
                    k for k in keys if k not in resolved and k not in dead
                )
                stats.deadline_expired = True
                break
            wait_s = gather_deadline - now
            if hedging:
                for a in live:
                    if a.hedged or a.kind == "hedge":
                        continue
                    trigger = self.hedge_latency.value(a.shard)
                    if trigger is None:
                        continue  # cold estimate: never hedge blind
                    due_in = (a.started
                              + max(trigger, self.cfg.hedge_min_delay_s)
                              - now)
                    if due_in <= 0:
                        maybe_hedge(a)
                    else:
                        wait_s = min(wait_s, due_in)
                live = [a for a in attempts if not a.settled]
            done, _pending = wait(
                [a.future for a in live],
                timeout=max(0.0005, wait_s),
                return_when=FIRST_COMPLETED,
            )
            if done:
                for a in [x for x in attempts if not x.settled]:
                    if a.future.done():
                        settle(a)

        # A failed shard whose keys a replica fully served does NOT
        # degrade the result (scores are exact; the failure still shows
        # in breaker state and kvtpu_shard_rpcs_total). Only keys no
        # reachable owner could serve make scores a lower bound.
        if dead:
            unreachable = (failed_shards | late_shards) or set(
                plan[i] for i, k in enumerate(keys) if k in dead
            )
            stats.degraded = True
            stats.degraded_shards = sorted(
                set(stats.degraded_shards) | unreachable
            )
            self._record_degraded(len(unreachable))
        return merged

    # -- scoring ----------------------------------------------------------

    def score(
        self,
        tokens: Sequence[int],
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
        role: str = "",
    ) -> RouterScore:
        """Scatter-gather GetPodScores: returns scores plus degradation
        detail (shard metadata mirrors the ScoreResponse wire fields).

        ``role="decode"`` adds transferred-prefix residency bonuses when
        a tracker is attached (``attach_residency``) — same semantics as
        the embedded indexer's role-aware scoring.
        """
        started = time.perf_counter()
        result = RouterScore()
        dl = current_deadline()
        if dl is not None:
            # Fail fast before any fan-out work: an already-expired
            # request must be shed by the caller, not served late.
            dl.check("cluster.router.score")
        # Pin the whole scatter-gather to ONE ring/epoch snapshot: an
        # epoch bump mid-score swaps self.ring for the next caller, but
        # this gather's plan, failovers, and hedges all resolve against
        # the topology it entered with.
        ring = self.ring
        epoch = self.membership.epoch if self.membership is not None else 0
        result.epoch = epoch
        with tracer().span(
            "llm_d.kv_cache.cluster.fanout",
            model=model_name,
            token_count=len(tokens),
            shard_count=len(ring.shards),
            role=role,
            process="router",
        ) as span:
            keys = self.token_processor.tokens_to_kv_block_keys(
                0, list(tokens), model_name
            )
            result.blocks = len(keys)
            if not keys:
                return result
            plan = self.plan(keys, ring=ring)
            merged: dict[BlockHash, list[PodEntry]] = {}
            chunk = self.cfg.fanout_chunk_blocks
            if chunk <= 0:
                chunk = len(keys)
            # Batched fan-out: one gather window covers fanoutBatchChunks
            # early-exit chunks with a single framed RPC per shard.
            batch = self.cfg.fanout_batch_chunks if self._batch_capable else 0
            window = chunk * batch if batch > 0 else chunk
            stop = False
            for start in range(0, len(keys), window):
                wkeys = keys[start:start + window]
                key_chunk = None
                if batch > 0 and len(wkeys) > chunk:
                    key_chunk = {
                        k: (start + i) // chunk for i, k in enumerate(wkeys)
                    }
                found = self._fanout_chunk(
                    wkeys, pod_identifiers, plan[start:start + window],
                    result, key_chunk=key_chunk, ring=ring, epoch=epoch,
                )
                # Chunk-order truncation: replay the per-chunk loop's
                # early-exit decisions over the window's merged map, so a
                # batched gather is byte-identical to the per-chunk wire.
                # Same soundness argument as Index.lookup_chunked: a
                # partial chunk proves the consecutive-from-0 run ended
                # inside it, so later chunks cannot change any score.
                for cstart in range(start, start + len(wkeys), chunk):
                    ckeys = keys[cstart:cstart + chunk]
                    cfound = {k: found[k] for k in ckeys if k in found}
                    if not cfound:
                        stop = True
                        break
                    merged.update(cfound)
                    if len(cfound) < len(ckeys):
                        stop = True
                        break
                if stop:
                    break
            if result.degraded_shards and (
                self.cfg.degraded_serve_mode == DEGRADED_SERVE_FAIL
            ):
                raise DegradedShardError(result.degraded_shards)
            result.hit_blocks = len(merged)
            result.scores = self.scorer.score(keys, merged)
            if role == "decode" and self.residency is not None:
                bonus = self.residency.bonus(
                    keys,
                    set(pod_identifiers) if pod_identifiers else None,
                )
                for pod, b in bonus.items():
                    result.scores[pod] = result.scores.get(pod, 0.0) + b
            span.set_attribute("block_count", len(keys))
            span.set_attribute("block_hit_count", len(merged))
            span.set_attribute("rpcs", result.rpcs)
            span.set_attribute("degraded_shards", len(result.degraded_shards))
            span.set_attribute("hedges", result.hedges)
        self._record_fanout(time.perf_counter() - started)
        return result

    def get_pod_scores(
        self,
        tokens: Sequence[int],
        model_name: str,
        pod_identifiers: Optional[Sequence[str]] = None,
    ) -> dict[str, float]:
        return self.score(tokens, model_name, pod_identifiers).scores

    # -- telemetry --------------------------------------------------------

    def _record_rpc(self, shard: str, outcome: str) -> None:
        try:
            from ..metrics.collector import record_shard_rpc

            record_shard_rpc(shard, outcome)
        except Exception:  # pragma: no cover - metrics must never break fan-out  # lint: allow-swallow
            pass

    def _record_batch_rpc(self, outcome: str) -> None:
        try:
            from ..metrics.collector import record_batch_rpc

            record_batch_rpc(outcome)
        except Exception:  # pragma: no cover - metrics must never break fan-out  # lint: allow-swallow
            pass

    def _record_hedge(self, shard: str, outcome: str) -> None:
        try:
            from ..metrics.collector import record_hedge

            record_hedge(shard, outcome)
        except Exception:  # pragma: no cover - metrics must never break fan-out  # lint: allow-swallow
            pass

    def _record_degraded(self, shards: int) -> None:
        try:
            from ..metrics.collector import record_shard_degraded_lookup

            record_shard_degraded_lookup(shards)
        except Exception:  # pragma: no cover - metrics must never break fan-out  # lint: allow-swallow
            pass

    def _record_fanout(self, seconds: float) -> None:
        try:
            from ..metrics.collector import record_shard_fanout

            record_shard_fanout(seconds)
        except Exception:  # pragma: no cover - metrics must never break fan-out  # lint: allow-swallow
            pass

    def _publish_ring_metrics(self) -> None:
        try:
            from ..metrics.collector import record_ring_load

            record_ring_load(self.ring.load())
        except Exception:  # pragma: no cover - metrics must never break startup  # lint: allow-swallow
            pass

    def debug_view(self) -> dict:
        return {
            "ring": self.ring.describe(),
            "breakers": {s: b.state for s, b in self.breakers.items()},
            "plan_cache": {
                "hits": self.plan_hits,
                "misses": self.plan_misses,
                "size": len(self._plan_cache) if self._plan_cache else 0,
            },
            "hedging": {
                "enabled": self.cfg.hedge_enabled,
                "budget": self.hedge_budget.stats(),
                "latency_quantiles_ms": {
                    shard: round(v * 1e3, 3)
                    for shard, v in self.hedge_latency.snapshot().items()
                },
            },
            "data_plane": {
                "batch_capable": self._batch_capable,
                "batch_chunks": self.cfg.fanout_batch_chunks,
                "batch_rpcs": self.batch_rpcs,
                "batch_fallbacks": self.batch_fallbacks,
                "legacy_shards": sorted(self._legacy_shards),
            },
            "epoch": {
                "pinned": self.ring.epoch,
                "membership": (self.membership.epoch
                               if self.membership is not None else None),
                "bumps": self.epoch_bumps,
                "cross_epoch_responses": self.cross_epoch_responses,
            },
        }

    def close(self) -> None:
        self._executor.shutdown(wait=False)
        for client in self.clients.values():
            client.close()
