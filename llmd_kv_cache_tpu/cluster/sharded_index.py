"""Index partitioning: per-block-key routing and per-shard ownership filters.

Two complementary pieces of the sharded control plane:

- :class:`ShardedIndex` — an :class:`~llmd_kv_cache_tpu.index.base.Index`
  over N child backends routed by the consistent-hash ring. One event
  pool writes through it and every block key lands on its owning child
  — the single-process form of sharded ingestion (also what bench.py
  uses to populate a toy cluster deterministically). The pool's
  write-combining ``_IngestCoalescer`` sits above it per drained batch;
  routed writes arrive already batched and are re-grouped per shard
  here, so each child sees one call per (shard, op) instead of one per
  key.

- :class:`ShardFilterIndex` — wraps ONE shard replica's local backend so
  the replica can ingest the full broadcast event stream but persist
  only the keys it owns (``shard_id ∈ owners(key, replication_factor)``).
  Engine→request *mappings* are kept for every key regardless of
  ownership: they are small ints, and chained parent resolution
  (``events.pool._handle_block_stored``) must never dead-end just
  because the parent block belongs to another shard. Each replica keeps
  its own pool, ``_IngestCoalescer``, journal and snapshots — the PR 2/4
  machinery is reused per shard unchanged.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..core.keys import BlockHash, KeyType, PodEntry
from ..index.base import Index, infer_engine_mappings
from ..utils.logging import get_logger
from .ring import HashRing

logger = get_logger("cluster.sharded_index")


class ShardedIndex(Index):
    """Route every Index operation to the owning child by block key."""

    def __init__(self, children: dict[str, Index], ring: HashRing):
        missing = set(ring.shards) - set(children)
        if missing:
            raise ValueError(f"no child index for shards: {sorted(missing)}")
        self.children = dict(children)
        self.ring = ring

    def _child(self, key: BlockHash) -> Index:
        return self.children[self.ring.owner(key)]

    def _group(self, keys: Sequence[BlockHash]) -> dict[str, list[BlockHash]]:
        groups: dict[str, list[BlockHash]] = {}
        for key in keys:
            groups.setdefault(self.ring.owner(key), []).append(key)
        return groups

    # -- reads ------------------------------------------------------------

    def lookup(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set: Optional[set[str]] = None,
    ) -> dict[BlockHash, list[PodEntry]]:
        result: dict[BlockHash, list[PodEntry]] = {}
        for shard, keys in self._group(request_keys).items():
            result.update(self.children[shard].lookup(keys, pod_identifier_set))
        return result

    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        return self._child(engine_key).get_request_key(engine_key)

    def get_request_keys(self, engine_key: BlockHash) -> Optional[list[BlockHash]]:
        return self._child(engine_key).get_request_keys(engine_key)

    # -- writes -----------------------------------------------------------

    def add(
        self,
        engine_keys: Optional[Sequence[BlockHash]],
        request_keys: Sequence[BlockHash],
        entries: Sequence[PodEntry],
    ) -> None:
        # Mappings route by ENGINE key (get_request_key asks that owner);
        # entries route by REQUEST key. The two families shard
        # independently, so the inferred mapping is distributed explicitly
        # instead of letting each child re-infer from a partial list.
        if engine_keys is not None:
            by_shard: dict[str, dict[BlockHash, list[BlockHash]]] = {}
            for ek, rks in infer_engine_mappings(engine_keys, request_keys).items():
                by_shard.setdefault(self.ring.owner(ek), {})[ek] = rks
            for shard, mappings in by_shard.items():
                self.children[shard].add_mappings(mappings)
        for shard, keys in self._group(request_keys).items():
            self.children[shard].add(None, keys, entries)

    def evict(
        self,
        key: BlockHash,
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        if key_type is KeyType.ENGINE:
            # The mapping owner resolves; the entry owners evict.
            rks = self._child(key).get_request_keys(key)
            if not rks:
                return
            for shard, keys in self._group(rks).items():
                self.children[shard].evict_batch(keys, KeyType.REQUEST, entries)
            return
        self._child(key).evict(key, key_type, entries)

    def evict_batch(
        self,
        keys: Sequence[BlockHash],
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        if key_type is KeyType.ENGINE:
            resolved: list[BlockHash] = []
            for key in keys:
                rks = self._child(key).get_request_keys(key)
                if rks:
                    resolved.extend(rks)
            if not resolved:
                return
            for shard, group in self._group(resolved).items():
                self.children[shard].evict_batch(group, KeyType.REQUEST, entries)
            return
        for shard, group in self._group(keys).items():
            self.children[shard].evict_batch(group, key_type, entries)

    def clear(self, pod_identifier: str) -> None:
        for child in self.children.values():
            child.clear(pod_identifier)

    # -- snapshot capability ----------------------------------------------

    def dump_state(self) -> Optional[dict]:
        """Merged view across children (digest sources, tests). Real shard
        replicas snapshot their own child; this merge is the coordinator's
        whole-cluster view."""
        entries: list = []
        mappings: list = []
        for shard in self.ring.shards:
            state = self.children[shard].dump_state()
            if not state:
                return None
            entries.extend(state.get("entries", []))
            mappings.extend(state.get("mappings", []))
        return {"entries": entries, "mappings": mappings}

    def restore_state(self, state: dict) -> int:
        restored = 0
        by_shard: dict[str, dict] = {
            s: {"entries": [], "mappings": []} for s in self.ring.shards
        }
        for row in state.get("entries", []):
            by_shard[self.ring.owner(row[0])]["entries"].append(row)
        for row in state.get("mappings", []):
            by_shard[self.ring.owner(row[0])]["mappings"].append(row)
        for shard, sub in by_shard.items():
            if sub["entries"] or sub["mappings"]:
                restored += self.children[shard].restore_state(sub)
        return restored


class ShardFilterIndex(Index):
    """One replica's ownership filter over its local backend.

    Reads and writes pass through for owned keys; entry writes for keys
    this shard does not own are dropped (another replica owns them).
    Mappings always pass through — see the module docstring.
    """

    def __init__(
        self,
        inner: Index,
        ring: HashRing,
        shard_id: str,
        replication_factor: int = 2,
    ):
        if shard_id not in ring.shards:
            raise ValueError(f"shard id {shard_id!r} not in ring membership")
        self.inner = inner
        self.ring = ring
        self.shard_id = shard_id
        self.replication_factor = max(1, replication_factor)
        # Ingest accounting for the shard debug view.
        self.owned_writes = 0
        self.filtered_writes = 0

    def owns(self, key: BlockHash) -> bool:
        return self.shard_id in self.ring.owners(key, self.replication_factor)

    # -- reads ------------------------------------------------------------

    def lookup(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set: Optional[set[str]] = None,
    ) -> dict[BlockHash, list[PodEntry]]:
        return self.inner.lookup(request_keys, pod_identifier_set)

    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        return self.inner.get_request_key(engine_key)

    def get_request_keys(self, engine_key: BlockHash) -> Optional[list[BlockHash]]:
        return self.inner.get_request_keys(engine_key)

    # -- writes -----------------------------------------------------------

    def add(
        self,
        engine_keys: Optional[Sequence[BlockHash]],
        request_keys: Sequence[BlockHash],
        entries: Sequence[PodEntry],
    ) -> None:
        owned = [rk for rk in request_keys if self.owns(rk)]
        if engine_keys is not None:
            # Full mapping table regardless of ownership (parent chains).
            self.inner.add_mappings(infer_engine_mappings(engine_keys, request_keys))
        self.owned_writes += len(owned)
        self.filtered_writes += len(request_keys) - len(owned)
        if owned:
            self.inner.add(None, owned, entries)

    def add_mappings(self, mappings: dict[BlockHash, list[BlockHash]]) -> None:
        self.inner.add_mappings(mappings)

    def evict(
        self,
        key: BlockHash,
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        # Evicting a key we never stored is a no-op in every backend, so
        # ENGINE-type evicts (which resolve through the always-complete
        # mapping table) and non-owned REQUEST evicts are safe to forward.
        self.inner.evict(key, key_type, entries)

    def evict_batch(
        self,
        keys: Sequence[BlockHash],
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        self.inner.evict_batch(keys, key_type, entries)

    def clear(self, pod_identifier: str) -> None:
        self.inner.clear(pod_identifier)

    # -- snapshot capability ----------------------------------------------

    def dump_state(self) -> Optional[dict]:
        return self.inner.dump_state()

    def restore_state(self, state: dict) -> int:
        return self.inner.restore_state(state)

    def debug_view(self) -> dict:
        return {
            "shard_id": self.shard_id,
            "replication_factor": self.replication_factor,
            "owned_writes": self.owned_writes,
            "filtered_writes": self.filtered_writes,
            "ring": self.ring.describe(),
        }
