"""Sharded, per-pod-ordered event processing pool.

Counterpart of reference ``pkg/kvevents/pool.go``. Messages are sharded
across worker queues by FNV-1a(pod id) % concurrency (``pool.go:161-173``)
so all events from one pod land on one worker and are processed in order —
the system's own "parallelism". Workers ingest parsed events into the index:

- BlockStored with tokens → learn HMA group, resolve parent engine key to a
  request key, parse + realign extra keys to canonical granularity,
  recompute request keys, ``index.add`` (``pool.go:312-425``)
- BlockStored without tokens → device-tier (offload) update for known
  blocks (``pool.go:262-299``)
- BlockRemoved → evict each engine key (``pool.go:427-451``)
- AllBlocksCleared → pod-wide ``index.clear`` (``pool.go:453-473``)
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..utils.lockdep import new_lock
from ..core.extra_keys import BlockExtraFeatures, parse_raw_extra_keys
from ..core.hma import GroupCatalog, GroupMetadata
from ..core.keys import EMPTY_BLOCK_HASH, TIER_TPU_HBM, BlockHash, KeyType, PodEntry
from ..core.token_processor import ChunkedTokenDatabase
from ..index.base import Index
from ..resilience.liveness import PodLivenessTracker
from ..telemetry import flight_recorder, tracer
from ..telemetry.flight_recorder import KIND_INGEST, KIND_OVERFLOW
from ..utils.fnv import fnv1a_32
from ..utils.logging import get_logger
from .adapters import create_adapter
from .model import (
    AllBlocksClearedEvent,
    BlockRemovedEvent,
    BlockStoredEvent,
    EventBatch,
    EngineAdapter,
    RawMessage,
)

logger = get_logger("events.pool")

# Default tier for events that omit a medium. The reference defaults to
# "gpu" (pool.go:32); on a TPU fleet the engine-resident tier is TPU HBM.
DEFAULT_EVENT_SOURCE_TIER = TIER_TPU_HBM


@dataclass
class PodDiscoveryConfig:
    """Kubernetes pod-reconciler knobs (``pool.go:56-76``)."""

    pod_label_selector: str = "llm-d.ai/inference-serving=true"
    pod_namespace: str = ""
    socket_port: int = 5557


@dataclass
class PoolConfig:
    """Event pool configuration (``pool.go:37-86``)."""

    zmq_endpoint: str = ""
    topic_filter: str = "kv@"
    concurrency: int = 4
    engine_type: str = "vllm"
    discover_pods: bool = False
    pod_discovery_config: PodDiscoveryConfig = field(default_factory=PodDiscoveryConfig)
    # TPU addition closing the reference's documented DP gap
    # (vllm_adapter.go:95, architecture.md "DP ranks WIP"): when True, pod
    # identifiers become "<pod>|dp<rank>" for events tagged with a
    # data-parallel rank, so routing can target a specific rank.
    track_dp_rank: bool = False
    # Pod-liveness degradation (resilience.liveness): a pod whose last
    # event is older than liveness_stale_after_s starts losing score
    # weight, reaching zero at liveness_drop_after_s. 0 disables tracking.
    liveness_stale_after_s: float = 30.0
    liveness_drop_after_s: float = 120.0
    # Batched ingestion: a worker drains up to this many queued messages
    # per wake-up and coalesces consecutive same-pod BlockStored /
    # BlockRemoved digests into single index calls. 1 restores strict
    # one-message-at-a-time processing.
    ingest_batch_max: int = 64
    # Per-shard queue bound. When a shard backs up to this depth, the
    # *oldest* queued message is dropped to admit the newest (fresh events
    # carry the current truth; anti-entropy repairs the hole). 0 restores
    # the old unbounded behavior — and its unbounded-memory failure mode.
    ingest_queue_max: int = 8192
    # Zero-copy ingest (docs/architecture.md "Native data plane"): accept
    # packed KZC1 frames (events.packed) alongside msgpack and decode
    # them as numpy views over the received buffer — no per-key/per-token
    # Python objects. Off turns packed frames into parse failures.
    ingest_zero_copy: bool = True
    # Same-host shared-memory ring (events.shm_ring): when set, a reader
    # thread drains packed frames from this ring file in addition to the
    # socket wire. Empty disables. The writer side creates the file; the
    # pool attaches (and retries until it appears).
    shm_ring_path: str = ""
    shm_ring_bytes: int = 1 << 20
    shm_ring_poll_s: float = 0.0005

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PoolConfig":
        if not d:
            return cls()
        batch_max = d.get("ingestBatchMax", d.get("ingest_batch_max"))
        queue_max = d.get("ingestQueueMax", d.get("ingest_queue_max"))
        zero_copy = d.get("ingestZeroCopy", d.get("ingest_zero_copy"))
        ring_bytes = d.get("shmRingBytes", d.get("shm_ring_bytes"))
        cfg = cls(
            zmq_endpoint=d.get("zmqEndpoint", d.get("zmq_endpoint", "")),
            topic_filter=d.get("topicFilter", d.get("topic_filter", "kv@")),
            concurrency=d.get("concurrency", 4) or 4,
            engine_type=d.get("engineType", d.get("engine_type", "vllm")) or "vllm",
            discover_pods=d.get("discoverPods", d.get("discover_pods", False)),
            track_dp_rank=d.get("trackDPRank", d.get("track_dp_rank", False)),
            ingest_batch_max=64 if batch_max is None else batch_max,
            ingest_queue_max=8192 if queue_max is None else queue_max,
            ingest_zero_copy=True if zero_copy is None else bool(zero_copy),
            shm_ring_path=d.get("shmRingPath", d.get("shm_ring_path", "")) or "",
            shm_ring_bytes=(1 << 20) if ring_bytes is None else ring_bytes,
            shm_ring_poll_s=d.get(
                "shmRingPollS", d.get("shm_ring_poll_s", 0.0005)
            ),
            liveness_stale_after_s=d.get(
                "livenessStaleAfterSeconds",
                d.get("liveness_stale_after_s", 30.0),
            ),
            liveness_drop_after_s=d.get(
                "livenessDropAfterSeconds",
                d.get("liveness_drop_after_s", 120.0),
            ),
        )
        pdc = d.get("podDiscoveryConfig", d.get("pod_discovery_config"))
        if pdc:
            cfg.pod_discovery_config = PodDiscoveryConfig(
                pod_label_selector=pdc.get(
                    "podLabelSelector",
                    pdc.get("pod_label_selector", "llm-d.ai/inference-serving=true"),
                ),
                pod_namespace=pdc.get("podNamespace", pdc.get("pod_namespace", "")),
                socket_port=pdc.get("socketPort", pdc.get("socket_port", 5557)) or 5557,
            )
        return cfg


class Pool:
    """Sharded worker pool ingesting KV events into an index.

    Stateless: all key mappings are delegated to the Index, so multiple
    replicas ingesting the same stream converge to the same soft state.
    """

    def __init__(
        self,
        cfg: Optional[PoolConfig],
        index: Index,
        token_processor: ChunkedTokenDatabase,
        adapter: Optional[EngineAdapter] = None,
    ):
        self.cfg = cfg or PoolConfig()
        self.index = index
        self.token_processor = token_processor
        self.adapter = adapter if adapter is not None else create_adapter(self.cfg.engine_type)
        self.group_catalog = GroupCatalog()
        # Per-pod last-event tracking; scorers attached to this pool (via
        # Indexer.attach_liveness) demote pods whose index view went stale.
        self.liveness: Optional[PodLivenessTracker] = None
        if self.cfg.liveness_stale_after_s > 0:
            self.liveness = PodLivenessTracker(
                stale_after_s=self.cfg.liveness_stale_after_s,
                drop_after_s=max(self.cfg.liveness_drop_after_s,
                                 self.cfg.liveness_stale_after_s * 2),
            )
        # maxsize=0 means unbounded (queue.Queue semantics); see
        # PoolConfig.ingest_queue_max for the drop-oldest overflow policy.
        self._queues: list[queue.Queue] = [
            queue.Queue(maxsize=max(0, self.cfg.ingest_queue_max))
            for _ in range(self.cfg.concurrency)
        ]
        self._threads: list[threading.Thread] = []
        self._started = False
        self._shutdown = object()  # queue sentinel
        # Sharding-key → shard memo: pod cardinality is small and stable,
        # so add_task skips re-encoding + FNV-hashing per message. Bounded
        # defensively; a full reset on overflow just re-hashes.
        self._shard_cache: dict[str, int] = {}
        self._stats_mu = new_lock()
        # Ingestion telemetry, mirrored into Prometheus per drained batch.
        self.ingest_batches = 0
        self.ingest_messages = 0
        self.coalesced_ops = 0
        # Native data plane accounting (kvdiag "data_plane" section):
        # packed frames decoded zero-copy, and messages that arrived over
        # the shared-memory ring instead of the socket wire.
        self.zerocopy_batches = 0
        self.shm_messages = 0
        self._shm_ring = None
        self._shm_stop = threading.Event()
        self._shm_thread: Optional[threading.Thread] = None
        # Event-pipeline lag/staleness (ISSUE 3): per-pod last sequence +
        # timestamps for gap detection and index-staleness estimation, and
        # a bounded sample window for p50/p99 lag readouts (admin, bench).
        self._lag_mu = new_lock()
        self._pod_lag: dict[str, dict] = {}
        self.lag_samples: collections.deque = collections.deque(maxlen=4096)
        # Per-pod cache-efficiency ledger (Indexer owns it; the service
        # wires the same object here so store/evict events attribute).
        self.ledger = None
        # Queue-overflow accounting (bounded shards drop the oldest
        # message; recovery's anti-entropy repairs the resulting holes).
        self.dropped_events = 0
        # Optional journal hook (recovery.manager.attach_journal): called
        # with (pod_id, sequence, topic, payload, event_ts) for every
        # successfully parsed live message.
        self.journal_sink = None
        # Epoch-fenced membership (cluster.membership.MembershipTable,
        # attach_membership): live batches are write-fenced against the
        # publishing pod's lease + stamped epoch; a zombie's post-lease
        # writes never reach the index. Replay (warm restart) bypasses
        # the fence — those writes were already accepted once.
        self.membership = None
        self.fenced_batches = 0
        self._replaying = False
        self._tracer = tracer()
        self._recorder = flight_recorder()

    # -- lifecycle --

    def start(self) -> None:
        """Start worker threads (non-blocking, idempotent)."""
        if self._started:
            return
        self._started = True
        for i in range(self.cfg.concurrency):
            t = threading.Thread(
                target=self._worker, args=(i,), name=f"kvevents-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        if self.cfg.shm_ring_path:
            self._shm_stop.clear()
            self._shm_thread = threading.Thread(
                target=self._shm_reader, name="kvevents-shm-reader",
                daemon=True,
            )
            self._shm_thread.start()
        logger.info("started sharded event pool with %d workers", self.cfg.concurrency)

    def shutdown(self) -> None:
        """Drain queues and stop workers (idempotent)."""
        if not self._started:
            return
        if self._shm_thread is not None:
            self._shm_stop.set()
            self._shm_thread.join()
            self._shm_thread = None
        if self._shm_ring is not None:
            self._shm_ring.close()
            self._shm_ring = None
        for q in self._queues:
            q.put(self._shutdown)
        for t in self._threads:
            t.join()
        self._threads.clear()
        self._started = False

    def join(self) -> None:
        """Block until all currently queued tasks are processed (testing aid)."""
        for q in self._queues:
            q.join()

    # -- ingestion --

    def add_task(self, task: RawMessage) -> None:
        """Queue a raw message on the shard owned by its pod."""
        key = self.adapter.sharding_key(task)
        shard = self._shard_cache.get(key)
        if shard is None:
            if len(self._shard_cache) >= 8192:
                self._shard_cache.clear()
            shard = fnv1a_32(key.encode("utf-8")) % self.cfg.concurrency
            self._shard_cache[key] = shard
        q = self._queues[shard]
        dropped = 0
        while True:
            try:
                q.put_nowait(task)
                break
            except queue.Full:
                # Drop-oldest: the newest message carries the pod's current
                # truth, so it must land; the evicted hole is repaired by
                # anti-entropy (recovery.reconcile). task_done keeps the
                # unfinished-task count balanced for Pool.join().
                try:
                    q.get_nowait()
                    q.task_done()
                    dropped += 1
                except queue.Empty:  # lint: allow-swallow (worker drained the shard; retry the put)
                    pass
        if dropped:
            first = self.dropped_events == 0
            with self._stats_mu:
                self.dropped_events += dropped
            if first:
                self._recorder.record(
                    KIND_OVERFLOW,
                    {
                        "shard": shard,
                        "queue_max": self.cfg.ingest_queue_max,
                        "dropped": dropped,
                    },
                )
                logger.warning(
                    "event shard %d overflowed (ingestQueueMax=%d); dropping oldest",
                    shard, self.cfg.ingest_queue_max,
                )
            try:
                from ..metrics.collector import record_dropped_events

                record_dropped_events(shard, dropped)
            except Exception:  # pragma: no cover - metrics must never break intake  # lint: allow-swallow
                pass

    def _worker(self, worker_index: int) -> None:
        q = self._queues[worker_index]
        budget = max(1, self.cfg.ingest_batch_max)
        # One write-combining coalescer per worker for its whole lifetime
        # (flushed at every batch boundary): same sequential semantics as
        # a per-batch instance, without reallocating the buffers per drain
        # — and single-message batches whose one message carries several
        # digests now coalesce too.
        sink = _IngestCoalescer(self.index)
        while True:
            batch = [q.get()]  # lint: allow-no-deadline (worker parks for work; shutdown via sentinel)
            shutdown = batch[0] is self._shutdown
            # Opportunistic drain: everything already queued on this shard
            # (up to the budget) is one batch; the blocking get above keeps
            # the idle path latency-free.
            while not shutdown and len(batch) < budget:
                try:
                    nxt = q.get_nowait()
                except queue.Empty:
                    break
                batch.append(nxt)
                shutdown = nxt is self._shutdown
            try:
                msgs = [t for t in batch if t is not self._shutdown]
                if msgs:
                    self._process_raw_batch(msgs, worker_index, sink)
            finally:
                for _ in batch:
                    q.task_done()
            if shutdown:
                return

    def _process_raw_batch(self, msgs: list[RawMessage],
                           worker_index: int = 0, sink=None) -> None:
        """Process one drained batch, write-combining through a coalescer.

        ``sink`` is the worker's persistent :class:`_IngestCoalescer`;
        ``saved_ops`` accumulates across batches there, so this reports
        the delta. A None sink (direct calls in tests) gets a throwaway.
        """
        if sink is None:
            sink = _IngestCoalescer(self.index)
        ops_before = sink.saved_ops
        for msg in msgs:
            self._process_raw_message(msg, sink)
        sink.flush()
        coalesced = sink.saved_ops - ops_before
        with self._stats_mu:
            self.ingest_batches += 1
            self.ingest_messages += len(msgs)
            self.coalesced_ops += coalesced
        self._recorder.record(
            KIND_INGEST,
            {"shard": worker_index, "messages": len(msgs), "coalesced_ops": coalesced},
        )
        try:
            from ..metrics.collector import (
                EVENT_QUEUE_DEPTH,
                INDEX_STALENESS,
                record_ingest_batch,
            )

            record_ingest_batch(len(msgs), coalesced)
            EVENT_QUEUE_DEPTH.labels(str(worker_index)).set(
                self._queues[worker_index].qsize()
            )
            INDEX_STALENESS.set(self.index_staleness_s())
        except Exception:  # pragma: no cover - metrics must never break ingestion  # lint: allow-swallow
            pass

    def _process_raw_message(self, msg: RawMessage, sink=None) -> None:
        # Zero-copy data plane: packed KZC1 frames (events.packed) skip
        # the msgpack adapter entirely. 4-byte sniff, no import cost on
        # the msgpack path.
        if self.cfg.ingest_zero_copy and msg.payload[:4] == b"KZC1":
            self._process_packed_message(msg, sink)
            return
        try:
            pod_id, model_name, batch = self.adapter.parse_message(msg)
        except Exception:
            logger.exception("failed to parse message on topic %s", msg.topic)
            return
        self._track_lag(pod_id, msg.sequence, batch.timestamp)
        if self.journal_sink is not None:
            try:
                self.journal_sink(
                    pod_id, msg.sequence, msg.topic, msg.payload, batch.timestamp
                )
            except Exception:
                # Journaling is best-effort durability; it must never stall
                # or kill live ingestion.
                logger.exception("journal append failed for pod %s", pod_id)
        try:
            with self._tracer.span(
                "llm_d.kv_cache.events.ingest",
                parent_traceparent=batch.traceparent,
                pod=pod_id,
                model=model_name,
                event_count=len(batch.events),
                sequence=msg.sequence,
            ):
                self.process_event_batch(batch, pod_id, model_name, sink=sink)
        except Exception:
            # Catch-all: a backend failure on one message must never kill
            # the shard's worker thread.
            logger.exception("failed to process event batch from %s", pod_id)

    def _process_packed_message(self, msg: RawMessage, sink=None) -> None:
        """Zero-copy BlockStored ingest (docs/architecture.md "Native
        data plane"): decode one packed frame into numpy views over the
        payload buffer and feed the uint64/uint32 arrays straight through
        the native hash chain into the index — no per-key or per-token
        Python object is materialized on the hot path. Packed frames are
        engine-resident stores (DEFAULT_EVENT_SOURCE_TIER) with no
        extra-keys/LoRA/dp-rank sidecars; events needing those stay on
        the msgpack wire."""
        try:
            from .packed import decode_packed_batch

            pb = decode_packed_batch(msg.payload)
        except Exception:
            logger.exception(
                "failed to decode packed frame on topic %s", msg.topic
            )
            return
        self._track_lag(pb.pod_id, msg.sequence, pb.timestamp)
        if self.journal_sink is not None:
            try:
                self.journal_sink(
                    pb.pod_id, msg.sequence, msg.topic, msg.payload,
                    pb.timestamp,
                )
            except Exception:
                logger.exception("journal append failed for pod %s", pb.pod_id)
        if self.liveness is not None:
            self.liveness.touch(pb.pod_id)
        try:
            with self._tracer.span(
                "llm_d.kv_cache.events.ingest",
                pod=pb.pod_id,
                model=pb.model_name,
                event_count=1,
                sequence=msg.sequence,
                zero_copy=True,
            ):
                self._ingest_packed(pb, sink)
        except Exception:
            logger.exception(
                "failed to process packed batch from %s", pb.pod_id
            )
            return
        with self._stats_mu:
            self.zerocopy_batches += 1
        try:
            from ..metrics.collector import record_zerocopy_batch

            record_zerocopy_batch()
        except Exception:  # pragma: no cover - metrics must never break ingestion  # lint: allow-swallow
            pass

    def _ingest_packed(self, pb, sink=None) -> None:
        """Apply one decoded packed frame to the index."""
        ops = sink if sink is not None else self.index
        parent_request_key = EMPTY_BLOCK_HASH
        if pb.parent_hash != 0:
            resolved = ops.get_request_key(pb.parent_hash)
            if resolved is None:
                logger.debug(
                    "no request key for packed parent %d (pod %s); dropping",
                    pb.parent_hash, pb.pod_id,
                )
                return
            parent_request_key = resolved
        tp = self.token_processor
        # Same chain the msgpack path derives, minus the Python detour:
        # model-seeded root, then the native FNV chain over the uint32
        # token view. Falls back to the ordinary token-processor path
        # (materializing ints) when the native library is absent.
        keys_arr = None
        request_keys = None
        try:
            from ..index import native as native_mod

            if native_mod.native_available():
                parent = (parent_request_key
                          if parent_request_key != EMPTY_BLOCK_HASH
                          else tp._get_init_hash(pb.model_name))
                request_keys, keys_arr = native_mod.hash_chain_with_array(
                    parent, pb.tokens, tp.block_size
                )
                tp.hash_calls += len(request_keys)
        except Exception:  # lint: allow-swallow (fall back to the Python chain)
            request_keys, keys_arr = None, None
        if request_keys is None:
            request_keys = tp.tokens_to_kv_block_keys(
                parent_request_key, [int(t) for t in pb.tokens],
                pb.model_name,
            )
        if not request_keys:
            return
        pod_entries = [PodEntry(pod_identifier=pb.pod_id,
                                device_tier=DEFAULT_EVENT_SOURCE_TIER)]
        try:
            if keys_arr is not None and getattr(
                self.index, "accepts_key_arrays", False
            ):
                # Array fast path: hand the views straight to the native
                # index. The coalescer buffers Python lists, so it is
                # flushed (ordering preserved) and bypassed here.
                if sink is not None:
                    sink.flush()
                self.index.add(pb.engine_keys, keys_arr, pod_entries)
            else:
                ops.add(pb.engine_keys.tolist(), request_keys, pod_entries)
        except Exception:
            logger.exception(
                "failed to add packed batch to index for pod %s", pb.pod_id
            )
            return
        if self.ledger is not None:
            self.ledger.record_store(pb.pod_id, len(request_keys))

    def _shm_reader(self) -> None:
        """Drain packed frames from the shared-memory ring into the
        normal sharded queues. Attach-side: the writer creates the ring
        file, so keep retrying until it exists."""
        from .shm_ring import ShmRing

        poll_s = max(0.0001, self.cfg.shm_ring_poll_s)
        seqs: dict[str, int] = {}
        while not self._shm_stop.is_set():
            if self._shm_ring is None:
                try:
                    self._shm_ring = ShmRing(self.cfg.shm_ring_path)
                except (OSError, ValueError):  # lint: allow-swallow (writer not up yet; retry)
                    self._shm_stop.wait(0.05)
                    continue
            record = self._shm_ring.read()
            if record is None:
                self._shm_stop.wait(poll_s)
                continue
            # Cheap header peek for the sharding topic; the worker decodes
            # the same frame again (struct-only, no array copies either
            # time).
            try:
                from .packed import decode_packed_batch

                pb = decode_packed_batch(record)
            except Exception:
                logger.exception("malformed shm-ring record; skipping")
                continue
            seq = seqs.get(pb.pod_id, 0) + 1
            seqs[pb.pod_id] = seq
            with self._stats_mu:
                self.shm_messages += 1
            try:
                from ..metrics.collector import record_shm_messages

                record_shm_messages(1)
            except Exception:  # pragma: no cover - metrics must never break ingestion  # lint: allow-swallow
                pass
            self.add_task(RawMessage(
                topic=f"kv@{pb.pod_id}@{pb.model_name}",
                sequence=seq,
                payload=record,
            ))

    def _track_lag(self, pod_id: str, sequence: int, event_ts: float) -> None:
        """Per-pod sequence-gap + publish→ingest lag bookkeeping.

        Lag compares the publisher's wall clock against ours, so cross-host
        skew leaks in; within one cluster (NTP-disciplined) it is still the
        right staleness signal, and sequence gaps are skew-free.
        """
        now = time.time()
        lag_s = max(0.0, now - event_ts)
        with self._lag_mu:
            st = self._pod_lag.get(pod_id)
            if st is None:
                st = self._pod_lag[pod_id] = {
                    "last_seq": sequence,
                    "last_event_ts": event_ts,
                    "last_ingest_ts": now,
                    "lag_s": lag_s,
                    "seq_gaps": 0,
                    "messages": 1,
                }
                gap = 0
            else:
                gap = max(0, sequence - st["last_seq"] - 1) if sequence > st["last_seq"] else 0
                st["seq_gaps"] += gap
                st["last_seq"] = max(st["last_seq"], sequence)
                st["last_event_ts"] = max(st["last_event_ts"], event_ts)
                st["last_ingest_ts"] = now
                st["lag_s"] = lag_s
                st["messages"] += 1
            self.lag_samples.append(lag_s)
        try:
            from ..metrics.collector import record_event_lag

            record_event_lag(pod_id, lag_s, gap)
        except Exception:  # pragma: no cover - metrics must never break ingestion  # lint: allow-swallow
            pass

    def replay_record(self, topic: str, sequence: int, payload: bytes) -> None:
        """Synchronously re-ingest one journaled message (warm restart).

        Runs the normal parse → track-lag → process path on the caller's
        thread, bypassing the shard queues; call before ``start()`` /
        before live subscriptions so replay is ordered ahead of live
        traffic. The journal sink must not be attached yet, or replayed
        records would be re-journaled.
        """
        self._replaying = True
        try:
            self._process_raw_message(RawMessage(topic=topic,
                                                 sequence=sequence,
                                                 payload=payload))
        finally:
            self._replaying = False

    def seed_sequences(self, pod_seqs: dict, event_ts: float) -> None:
        """Seed per-pod watermarks from a snapshot (recovery.manager).

        Lets sequence-gap detection span a restart, and makes
        ``index_staleness_s`` reflect the snapshot's age until live events
        catch up — which is the warmup readiness gate. Pods that already
        progressed past the seed (journal replay, live traffic) keep their
        newer watermark.
        """
        now = time.time()
        with self._lag_mu:
            for pod, seq in pod_seqs.items():
                st = self._pod_lag.get(pod)
                if st is None:
                    self._pod_lag[pod] = {
                        "last_seq": int(seq),
                        "last_event_ts": float(event_ts),
                        "last_ingest_ts": now,
                        "lag_s": 0.0,
                        "seq_gaps": 0,
                        "messages": 0,
                    }
                elif int(seq) > st["last_seq"]:
                    st["last_seq"] = int(seq)
                    st["last_event_ts"] = max(st["last_event_ts"], float(event_ts))

    def index_staleness_s(self, now: Optional[float] = None) -> float:
        """Upper-bound age of the index's view of the slowest pod: the
        oldest per-pod last-event timestamp, measured against now. 0 when
        no events have been seen."""
        now = time.time() if now is None else now
        with self._lag_mu:
            if not self._pod_lag:
                return 0.0
            oldest = min(st["last_event_ts"] for st in self._pod_lag.values())
        return max(0.0, now - oldest)

    def attach_membership(self, membership) -> None:
        """Enable the ingest write fence: every live batch is checked
        against ``membership`` (publisher lease validity + stamped epoch)
        before its events touch the index."""
        self.membership = membership

    def data_plane_debug(self) -> dict:
        """Zero-copy / shm-ring ingest counters (kvdiag ``data_plane``)."""
        with self._stats_mu:
            return {
                "zerocopy_batches": self.zerocopy_batches,
                "shm_messages": self.shm_messages,
                "fenced_batches": self.fenced_batches,
            }

    def lag_stats(self) -> dict:
        """Lag/staleness snapshot for the admin endpoint and kvdiag."""
        with self._lag_mu:
            pods = {
                pod: {k: v for k, v in st.items()}
                for pod, st in self._pod_lag.items()
            }
            samples = list(self.lag_samples)
            # Inline (index_staleness_s re-takes the non-reentrant lock).
            oldest = min(
                (st["last_event_ts"] for st in self._pod_lag.values()),
                default=None,
            )
        stats: dict = {
            "pods": pods,
            "staleness_s": 0.0 if oldest is None else max(0.0, time.time() - oldest),
            "queue_depths": [q.qsize() for q in self._queues],
        }
        if samples:
            samples.sort()
            n = len(samples)
            stats["lag_p50_s"] = samples[n // 2]
            stats["lag_p99_s"] = samples[min(n - 1, (n * 99) // 100)]
        return stats

    # -- event semantics --

    def process_event_batch(
        self, batch: EventBatch, pod_identifier: str, model_name: str,
        sink=None,
    ) -> None:
        """Apply a parsed event batch to the index (``pool.go:302-479``).

        ``sink`` (an :class:`_IngestCoalescer`) substitutes for the index
        during batched worker drains; all index writes/reads route through
        it so consecutive digests can be write-combined.
        """
        if self.membership is not None and not self._replaying:
            # Zombie fence (cluster.membership): a publisher whose lease
            # lapsed — a pod that stalled past its TTL and resumed — or
            # whose stamped epoch is stale gets its writes dropped (or
            # flagged, per fenceMode) BEFORE they can poison the index
            # with placement the fleet no longer agrees on.
            fence = self.membership.check_write(
                pod_identifier, batch.epoch, "events.ingest")
            if not fence.allowed:
                self.fenced_batches += 1
                logger.warning(
                    "dropped fenced event batch from pod %s (%s; epoch=%d)",
                    pod_identifier, fence.reason, batch.epoch)
                return
        if (
            self.cfg.track_dp_rank
            and batch.data_parallel_rank is not None
            and batch.data_parallel_rank >= 0
        ):
            pod_identifier = f"{pod_identifier}|dp{batch.data_parallel_rank}"

        # Any event from a pod proves its publisher (and thus our view of
        # it) is alive; touch AFTER dp-rank suffixing so routing-visible
        # identifiers are the ones tracked.
        if self.liveness is not None:
            self.liveness.touch(pod_identifier)

        ops = sink if sink is not None else self.index
        for event in batch.events:
            if isinstance(event, BlockStoredEvent):
                self._handle_block_stored(event, pod_identifier, model_name, ops)
            elif isinstance(event, BlockRemovedEvent):
                self._handle_block_removed(event, pod_identifier, ops)
            elif isinstance(event, AllBlocksClearedEvent):
                # Pod-wide: engines emit this with no tier; a tier-scoped
                # clear is unsupported and would over-wipe.
                try:
                    ops.clear(pod_identifier)
                except Exception:
                    logger.exception("failed to clear pod %s", pod_identifier)
                else:
                    if self.ledger is not None:
                        self.ledger.record_clear(pod_identifier)
            else:  # pragma: no cover - adapter produces only known events
                logger.debug("unknown event from pod %s: %r", pod_identifier, event)

    def _handle_block_stored(
        self, ev: BlockStoredEvent, pod_identifier: str, model_name: str,
        ops: Index,
    ) -> None:
        device_tier = ev.device_tier.lower() if ev.device_tier else DEFAULT_EVENT_SOURCE_TIER

        # LoRA adapters are distinct cache namespaces: use the LoRA name as
        # the effective model for key derivation (pool.go:319-323).
        effective_model = ev.lora_name if ev.lora_name else model_name

        pod_entry = PodEntry(pod_identifier=pod_identifier, device_tier=device_tier)
        if ev.group_idx is not None:
            self.group_catalog.learn(
                pod_identifier,
                ev.group_idx,
                GroupMetadata(
                    kind=ev.kv_cache_spec_kind,
                    block_size=ev.block_size,
                    sliding_window_size=ev.kv_cache_spec_sliding_window,
                ),
            )
            pod_entry = PodEntry(
                pod_identifier=pod_identifier,
                device_tier=device_tier,
                has_group=True,
                group_idx=ev.group_idx,
            )
        pod_entries = [pod_entry]

        engine_keys: list[BlockHash] = ev.block_hashes

        parent_request_key = EMPTY_BLOCK_HASH
        if ev.parent_hash != 0:
            try:
                resolved = ops.get_request_key(ev.parent_hash)
            except Exception:
                logger.exception("parent key resolution failed (pod %s)", pod_identifier)
                resolved = None
            if resolved is None:
                logger.debug(
                    "no request key for parent engine key %d (pod %s); dropping event",
                    ev.parent_hash, pod_identifier,
                )
                return
            parent_request_key = resolved

        extra_features: Optional[list[Optional[BlockExtraFeatures]]] = None
        if ev.extra_keys is not None:
            try:
                extra_features = parse_raw_extra_keys(ev.extra_keys)
            except Exception:
                logger.exception("failed to parse extra keys from pod %s", pod_identifier)
                return

        # Realign extra features from engine-block to canonical-block
        # granularity (pool.go:366-378).
        if extra_features is not None:
            canonical_count = len(ev.tokens) // self.token_processor.block_size
            if canonical_count == 0:
                extra_features = None
            elif len(extra_features) != canonical_count:
                extra_features = realign_extra_features(extra_features, canonical_count)

        try:
            request_keys = self.token_processor.tokens_to_kv_block_keys(
                parent_request_key, ev.tokens, effective_model, extra_features
            )
        except ValueError:
            logger.exception("failed to generate request keys for pod %s", pod_identifier)
            return

        if not request_keys:
            self._handle_device_tier_update(
                ev.tokens, engine_keys, pod_entries, pod_identifier, device_tier, ops
            )
            return

        try:
            ops.add(engine_keys, request_keys, pod_entries)
        except Exception:
            logger.exception("failed to add event to index for pod %s", pod_identifier)
        else:
            if self.ledger is not None:
                self.ledger.record_store(pod_identifier, len(request_keys))

    def _handle_device_tier_update(
        self,
        tokens: list[int],
        engine_keys: list[BlockHash],
        pod_entries: list[PodEntry],
        pod_identifier: str,
        device_tier: str,
        ops: Index,
    ) -> None:
        """Tokenless BlockStored = offload/location update (``pool.go:262-299``).

        Resolve known engine keys to request keys and add the new tier entry.
        Partial-block events (0 < tokens < block size) are skipped entirely.
        """
        if tokens or not engine_keys:
            return

        seen: set[BlockHash] = set()
        resolved: list[BlockHash] = []
        for ek in engine_keys:
            try:
                rk = ops.get_request_key(ek)
            except Exception:
                logger.exception("engine key resolution failed (pod %s)", pod_identifier)
                continue
            if rk is None or rk in seen:
                continue
            seen.add(rk)
            resolved.append(rk)

        if resolved:
            try:
                ops.add(None, resolved, pod_entries)
            except Exception:
                logger.exception(
                    "failed to add device-tier update (pod %s, tier %s)",
                    pod_identifier, device_tier,
                )
        else:
            logger.debug(
                "no indexed engine keys for device-tier update (pod %s, %d keys)",
                pod_identifier, len(engine_keys),
            )

    def _handle_block_removed(
        self, ev: BlockRemovedEvent, pod_identifier: str, ops: Index
    ) -> None:
        device_tier = ev.device_tier.lower() if ev.device_tier else DEFAULT_EVENT_SOURCE_TIER
        pod_entry = PodEntry(pod_identifier=pod_identifier, device_tier=device_tier)
        if ev.group_idx is not None:
            pod_entry = PodEntry(
                pod_identifier=pod_identifier,
                device_tier=device_tier,
                has_group=True,
                group_idx=ev.group_idx,
            )
        if not ev.block_hashes:
            return
        try:
            ops.evict_batch(ev.block_hashes, KeyType.ENGINE, [pod_entry])
        except Exception:
            logger.exception(
                "failed to evict %d engine keys from pod %s",
                len(ev.block_hashes), pod_identifier,
            )
        else:
            if self.ledger is not None:
                self.ledger.record_evict(pod_identifier, len(ev.block_hashes))


class _IngestCoalescer:
    """Write-combining Index facade for one drained worker batch.

    Duck-types the slice of the Index contract the event handlers use
    (``add``/``evict_batch``/``get_request_key``/``clear``). Consecutive
    homogeneous writes buffer and merge; any differing operation flushes
    the buffer first, so the index observes the same sequential semantics
    as per-message processing — just with fewer calls (fewer lock
    acquisitions, interning passes and Redis round-trips).

    Coalescing rules:

    - only 1:1 engine:request ``add`` digests with identical pod entries
      merge — concatenation preserves the inferred mappings exactly when
      each position maps to itself and no engine key repeats in the buffer
    - ``evict_batch`` runs with identical key type + entries merge
    - ``get_request_key`` is answered from the pending add buffer when
      possible (chained digests stay coalesced); otherwise pending evicts
      flush first (they could have removed the mapping), then the index is
      asked. A pending add for *other* keys cannot change the answer and
      stays buffered.
    - ``clear`` flushes everything, then clears.
    """

    def __init__(self, index: Index):
        self.index = index
        self.saved_ops = 0  # index calls absorbed by merging
        # pending add: [engine_keys, request_keys, entries_sig, entries,
        #               engine_key → request_key]
        self._add: Optional[list] = None
        # pending evict: [(key_type, entries_sig), keys, entries]
        self._evict: Optional[list] = None

    # -- flushing ---------------------------------------------------------

    def _flush_add(self) -> None:
        if self._add is None:
            return
        engine_keys, request_keys, _, entries, _ = self._add
        self._add = None
        try:
            self.index.add(engine_keys, request_keys, entries)
        except Exception:
            logger.exception("coalesced add of %d keys failed", len(request_keys))

    def _flush_evict(self) -> None:
        if self._evict is None:
            return
        (key_type, _), keys, entries = self._evict
        self._evict = None
        try:
            self.index.evict_batch(keys, key_type, entries)
        except Exception:
            logger.exception("coalesced evict of %d keys failed", len(keys))

    def flush(self) -> None:
        """Write out all buffered operations (end of the drained batch)."""
        # At most one kind is pending (starting either flushes the other).
        self._flush_evict()
        self._flush_add()

    # -- Index surface used by the handlers -------------------------------

    def add(self, engine_keys, request_keys, entries) -> None:
        self._flush_evict()
        if engine_keys is None or len(engine_keys) != len(request_keys):
            self._flush_add()
            self.index.add(engine_keys, request_keys, entries)
            return
        sig = tuple(entries)
        if self._add is not None:
            b_ek, b_rk, b_sig, _, b_map = self._add
            if b_sig == sig and not any(ek in b_map for ek in engine_keys):
                b_ek.extend(engine_keys)
                b_rk.extend(request_keys)
                b_map.update(zip(engine_keys, request_keys))
                self.saved_ops += 1
                return
            self._flush_add()
        self._add = [
            list(engine_keys), list(request_keys), sig, list(entries),
            dict(zip(engine_keys, request_keys)),
        ]

    def evict_batch(self, keys, key_type, entries) -> None:
        self._flush_add()
        sig = (key_type, tuple(entries))
        if self._evict is not None:
            if self._evict[0] == sig:
                self._evict[1].extend(keys)
                self.saved_ops += 1
                return
            self._flush_evict()
        self._evict = [sig, list(keys), list(entries)]

    def get_request_key(self, engine_key):
        if self._add is not None:
            rk = self._add[4].get(engine_key)
            if rk is not None:
                return rk
        self._flush_evict()
        return self.index.get_request_key(engine_key)

    def clear(self, pod_identifier: str) -> None:
        self.flush()
        self.index.clear(pod_identifier)


def realign_extra_features(
    engine_features: list[Optional[BlockExtraFeatures]], canonical_block_count: int
) -> Optional[list[Optional[BlockExtraFeatures]]]:
    """Convert per-engine-block features to per-canonical-block granularity.

    Mirrors reference ``pool.go:227-260``: for 1:many (engine block larger)
    replicate each engine feature onto its canonical sub-blocks; for many:1
    merge (union of MM hashes) constituent engine features into each
    canonical block.
    """
    engine_count = len(engine_features)
    if canonical_block_count == 0:
        return None
    if engine_count == 0 or engine_count == canonical_block_count:
        return engine_features

    canonical: list[Optional[BlockExtraFeatures]] = [None] * canonical_block_count

    if engine_count < canonical_block_count:
        for i in range(canonical_block_count):
            canonical[i] = engine_features[i * engine_count // canonical_block_count]
    else:
        for i, ef in enumerate(engine_features):
            if ef is None:
                continue
            ci = i * canonical_block_count // engine_count
            if canonical[ci] is None:
                canonical[ci] = BlockExtraFeatures()
            canonical[ci].mm_hashes.extend(ef.mm_hashes)

    return canonical
