"""ZMQ PUB publisher for KV events.

Engine-side counterpart of the subscriber wire: 3 frames ``[topic,
big-endian uint64 seq, msgpack([ts, [events], dp_rank?])]`` with events as
positional arrays (msgspec ``array_like=True, omit_defaults=True`` style:
trailing default fields trimmed).

Two users:

- the in-tree TPU serving engine (``models.engine``) publishing its block
  store/remove/clear events, topic ``kv@<pod>@<model>``
- the offload data plane's **StorageEventPublisher** (reference
  ``llmd_fs_backend/event_publisher.py:45-158``): tokenless BlockStored /
  BlockRemoved with the *medium* in the pod slot, topic
  ``kv@<MEDIUM>@<model>``, hashes masked to 64 bits.
"""

from __future__ import annotations

import struct
import time
from typing import Optional, Sequence

import msgpack
import zmq

from ..utils.lockdep import new_lock
from ..telemetry import current_traceparent
from ..utils.logging import get_logger
from .model import AllBlocksClearedEvent, BlockRemovedEvent, BlockStoredEvent, GenericEvent

logger = get_logger("events.publisher")

_MASK64 = 0xFFFFFFFFFFFFFFFF
DEFAULT_HWM = 100_000  # publisher high-water mark (event_publisher.py:28,72)

MEDIUM_SHARED_STORAGE = "SHARED_STORAGE"
MEDIUM_OBJECT_STORE = "OBJECT_STORE"


def encode_event(event: GenericEvent) -> list:
    """Encode a domain event as its positional wire array, trailing
    defaults trimmed."""
    if isinstance(event, BlockStoredEvent):
        fields = [
            "BlockStored",
            [h & _MASK64 for h in event.block_hashes],
            (event.parent_hash & _MASK64) if event.parent_hash else None,
            list(event.tokens),
            event.block_size,
            event.lora_id,
            event.device_tier or None,
            event.lora_name,
            event.extra_keys,
            event.group_idx,
            event.kv_cache_spec_kind or None,
            event.kv_cache_spec_sliding_window,
        ]
    elif isinstance(event, BlockRemovedEvent):
        fields = [
            "BlockRemoved",
            [h & _MASK64 for h in event.block_hashes],
            event.device_tier or None,
            event.group_idx,
        ]
    elif isinstance(event, AllBlocksClearedEvent):
        fields = ["AllBlocksCleared"]
    else:
        raise TypeError(f"cannot encode event {type(event)!r}")

    while len(fields) > 1 and fields[-1] is None:
        fields.pop()
    return fields


class KVEventPublisher:
    """ZMQ PUB socket emitting KV-event batches for one topic."""

    def __init__(
        self,
        endpoint: str,
        pod_identifier: str,
        model_name: str,
        bind: bool = True,
        context: Optional[zmq.Context] = None,
        hwm: int = DEFAULT_HWM,
    ):
        self.topic = f"kv@{pod_identifier}@{model_name}"
        self._ctx = context or zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        self._sock.setsockopt(zmq.SNDHWM, hwm)
        self._sock.setsockopt(zmq.LINGER, 0)
        if bind:
            self._sock.bind(endpoint)
        else:
            self._sock.connect(endpoint)
        self.endpoint = endpoint
        self._seq = 0
        self._lock = new_lock()

    def publish(
        self,
        events: Sequence[GenericEvent],
        timestamp: Optional[float] = None,
        data_parallel_rank: Optional[int] = None,
        traceparent: Optional[str] = None,
        epoch: int = 0,
    ) -> int:
        """Publish one batch; returns the sequence number used.

        The ambient W3C trace context (or an explicit ``traceparent``)
        rides as wire element [3]; the publisher's topology epoch
        (``epoch`` > 0; cluster.membership) as wire element [4], with
        absent middle elements padded nil. Length-tolerant adapters on
        old subscribers ignore both, so the wire stays engine-compatible.
        """
        ts = timestamp if timestamp is not None else time.time()
        if traceparent is None:
            traceparent = current_traceparent()
        batch: list = [ts, [encode_event(e) for e in events]]
        if data_parallel_rank is not None or traceparent is not None or epoch:
            batch.append(data_parallel_rank)
        if traceparent is not None or epoch:
            batch.append(traceparent)
        if epoch:
            batch.append(int(epoch))
        payload = msgpack.packb(batch, use_bin_type=True)
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._sock.send_multipart(
                [self.topic.encode("utf-8"), struct.pack(">Q", seq), payload]
            )
        return seq

    def close(self) -> None:
        self._sock.close()


class StorageEventPublisher(KVEventPublisher):
    """Publishes storage-tier events (offload data plane → indexer).

    Mirrors reference ``event_publisher.py``: the "pod" slot carries the
    storage medium, events are tokenless so the pool resolves them through
    the engine→request mapping as device-tier updates.
    """

    def __init__(
        self,
        endpoint: str,
        model_name: str,
        medium: str = MEDIUM_SHARED_STORAGE,
        bind: bool = False,
        context: Optional[zmq.Context] = None,
    ):
        super().__init__(
            endpoint,
            pod_identifier=medium,
            model_name=model_name,
            bind=bind,
            context=context,
        )
        self.medium = medium

    def publish_block_stored(self, block_hashes: Sequence[int], block_size: int) -> int:
        """Tokenless BlockStored: blocks now present on this medium."""
        return self.publish(
            [
                BlockStoredEvent(
                    block_hashes=[h & _MASK64 for h in block_hashes],
                    tokens=[],
                    parent_hash=0,
                    block_size=block_size,
                    device_tier=self.medium,
                )
            ]
        )

    def publish_block_removed(self, block_hashes: Sequence[int]) -> int:
        return self.publish(
            [
                BlockRemovedEvent(
                    block_hashes=[h & _MASK64 for h in block_hashes],
                    device_tier=self.medium,
                )
            ]
        )
