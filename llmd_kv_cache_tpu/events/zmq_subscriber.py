"""ZMQ SUB subscriber for KV events.

Counterpart of reference ``pkg/kvevents/zmq_subscriber.go``. Wire protocol:
three frames ``[topic, 8-byte big-endian sequence, msgpack payload]``
(``zmq_subscriber.go:121-135``). Two delivery modes:

- **centralized**: the indexer *binds* a local endpoint and every engine
  connects its PUB to it
- **pod-discovery**: one subscriber per pod *dials* the pod's PUB endpoint

Crash-only: an outer retry loop re-establishes the socket forever; a dead
pod's subscriber just keeps retrying until the reconciler removes it. The
reference retries on a fixed 5 s cadence (``zmq_subscriber.go:54-76``);
here the delay is jittered exponential (fast first reconnect after a
transient blip, capped for a truly dead peer, reset after a successful
receive) so a restarted fleet neither hammers a recovering indexer nor
waits 5 s to heal a 50 ms hiccup.
"""

from __future__ import annotations

import struct
import threading
from typing import Callable, Optional

import zmq

from ..resilience.failpoints import failpoints
from ..resilience.policy import RetryPolicy
from ..telemetry import flight_recorder
from ..telemetry.flight_recorder import KIND_RECONNECT
from ..utils.logging import get_logger
from .model import RawMessage

logger = get_logger("events.zmq")

# Backoff cap; kept as the historical name — stop() joins against it and
# external tooling references it as the worst-case reconnect cadence.
RETRY_INTERVAL_S = 5.0
_POLL_INTERVAL_MS = 200

# Error-mode fires inside the subscriber loop right after the socket is
# established, forcing a teardown/reconnect cycle (chaos: flapping peer).
FP_ZMQ_CONNECT = "events.zmq.connect"

# max_attempts is a per-call concept; the subscriber loop retries forever
# and only uses delay(attempt) with the attempt counter it maintains.
DEFAULT_RECONNECT_POLICY = RetryPolicy(
    max_attempts=1, base_delay_s=0.25, max_delay_s=RETRY_INTERVAL_S,
    multiplier=2.0, jitter=True,
)


class ZMQSubscriber:
    """A resilient SUB socket feeding a Pool."""

    def __init__(
        self,
        endpoint: str,
        topic_filter: str,
        on_message: Callable[[RawMessage], None],
        bind: bool = False,
        context: Optional[zmq.Context] = None,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.endpoint = endpoint
        self.topic_filter = topic_filter
        self.on_message = on_message
        self.bind = bind
        self.retry_policy = retry_policy or DEFAULT_RECONNECT_POLICY
        self._ctx = context or zmq.Context.instance()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Consecutive failed connection cycles since the last successful
        # receive; drives the backoff exponent.
        self._consecutive_failures = 0
        # Total reconnect cycles over the subscriber's lifetime
        # (observability/chaos-test hook).
        self.reconnects = 0

    def start(self) -> None:
        """Start the subscriber loop in a daemon thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"zmq-sub-{self.endpoint}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * RETRY_INTERVAL_S)
            self._thread = None

    def next_delay(self) -> float:
        """Backoff before the next reconnect, from the failure streak."""
        return self.retry_policy.delay(self._consecutive_failures)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._run_subscriber()
            except Exception:
                logger.exception("subscriber error for %s", self.endpoint)
            if self._stop.is_set():
                return
            delay = self.next_delay()
            self._consecutive_failures += 1
            self.reconnects += 1
            flight_recorder().record(
                KIND_RECONNECT,
                {
                    "endpoint": self.endpoint,
                    "streak": self._consecutive_failures,
                    "delay_s": delay,
                },
            )
            logger.info("reconnecting to %s in %.2fs (streak=%d)",
                        self.endpoint, delay, self._consecutive_failures)
            if self._stop.wait(delay):
                return

    def _run_subscriber(self) -> None:
        sock = self._ctx.socket(zmq.SUB)
        try:
            sock.setsockopt(zmq.LINGER, 0)
            sock.setsockopt_string(zmq.SUBSCRIBE, self.topic_filter)
            if self.bind:
                sock.bind(self.endpoint)
            else:
                sock.connect(self.endpoint)
            logger.info("subscribed to %s (%s, filter=%r)",
                        self.endpoint, "bind" if self.bind else "connect", self.topic_filter)
            failpoints.hit(FP_ZMQ_CONNECT)

            while not self._stop.is_set():
                failpoints.hit(FP_ZMQ_CONNECT)
                if not sock.poll(_POLL_INTERVAL_MS):
                    continue
                frames = sock.recv_multipart()
                # A delivered message proves the link: reset the backoff so
                # the next outage starts from the fast end again.
                self._consecutive_failures = 0
                msg = self._parse_frames(frames)
                if msg is not None:
                    self.on_message(msg)
        finally:
            sock.close()

    @staticmethod
    def _parse_frames(frames: list[bytes]) -> Optional[RawMessage]:
        if len(frames) != 3:
            logger.warning("dropping message with %d frames (want 3)", len(frames))
            return None
        topic_raw, seq_raw, payload = frames
        try:
            topic = topic_raw.decode("utf-8")
        except UnicodeDecodeError:
            logger.warning("dropping message with non-utf8 topic")
            return None
        if len(seq_raw) < 8:
            logger.warning("dropping message with %d-byte seq frame (want >= 8)", len(seq_raw))
            return None
        # Decode the first 8 bytes; longer frames are tolerated for interop
        # (reference zmq_subscriber.go:130).
        (sequence,) = struct.unpack(">Q", seq_raw[:8])
        return RawMessage(topic=topic, sequence=sequence, payload=payload)
