"""Per-pod subscriber lifecycle management.

Counterpart of reference ``pkg/kvevents/subscriber_manager.go``: one
subscriber per discovered pod, idempotent ``ensure_subscriber`` with
endpoint-change handling, individual stop on pod removal. Driven by a pod
reconciler (Kubernetes watch) or any discovery source.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..utils.lockdep import new_lock
from ..utils.logging import get_logger
from .model import RawMessage
from .zmq_subscriber import ZMQSubscriber

logger = get_logger("events.submgr")


class SubscriberManager:
    """Tracks one ZMQSubscriber per pod."""

    def __init__(
        self,
        on_message: Callable[[RawMessage], None],
        topic_filter: str = "kv@",
    ):
        self._on_message = on_message
        self._topic_filter = topic_filter
        self._lock = new_lock()
        self._subscribers: dict[str, tuple[str, ZMQSubscriber]] = {}

    def ensure_subscriber(self, pod_name: str, endpoint: str) -> bool:
        """Create (or re-create on endpoint change) a pod's subscriber.

        Returns True when a new subscriber was started. Idempotent for an
        unchanged endpoint (``subscriber_manager.go:52-93``).
        """
        old_sub = None
        with self._lock:
            existing = self._subscribers.get(pod_name)
            if existing is not None:
                old_endpoint, old_sub = existing
                if old_endpoint == endpoint:
                    return False
                logger.info("pod %s endpoint changed %s → %s; restarting subscriber",
                            pod_name, old_endpoint, endpoint)
                del self._subscribers[pod_name]

            sub = ZMQSubscriber(
                endpoint=endpoint,
                topic_filter=self._topic_filter,
                on_message=self._on_message,
                bind=False,
            )
            sub.start()
            self._subscribers[pod_name] = (endpoint, sub)

        # Stop the replaced subscriber outside the lock: stop() joins its
        # thread (seconds) and must not stall other pods' reconciliation.
        if old_sub is not None:
            old_sub.stop()
        logger.info("subscriber started for pod %s at %s", pod_name, endpoint)
        return True

    def remove_subscriber(self, pod_name: str) -> bool:
        """Stop and drop a pod's subscriber (pod deleted)."""
        with self._lock:
            existing = self._subscribers.pop(pod_name, None)
        if existing is None:
            return False
        existing[1].stop()
        logger.info("subscriber removed for pod %s", pod_name)
        return True

    def pods(self) -> list[str]:
        with self._lock:
            return list(self._subscribers.keys())

    def endpoint_of(self, pod_name: str) -> Optional[str]:
        with self._lock:
            entry = self._subscribers.get(pod_name)
            return entry[0] if entry else None

    def shutdown(self) -> None:
        with self._lock:
            subs = list(self._subscribers.values())
            self._subscribers.clear()
        for _, sub in subs:
            sub.stop()
