"""KV-event domain model.

Counterpart of reference ``pkg/kvevents/events.go``: parsed engine events
plus the raw transport envelope. Parsing is deferred to per-engine adapters.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Protocol

EVENT_TYPE_BLOCK_STORED = "BlockStored"
EVENT_TYPE_BLOCK_REMOVED = "BlockRemoved"
EVENT_TYPE_ALL_BLOCKS_CLEARED = "AllBlocksCleared"
EVENT_TYPE_TRANSFER_AVAILABLE = "TransferBlocksAvailable"


@dataclass
class RawMessage:
    """Raw transport-level pub/sub message: topic, sequence, undecoded payload."""

    topic: str
    sequence: int
    payload: bytes


@dataclass
class BlockStoredEvent:
    """Blocks added to an engine's cache (``events.go:83-98``).

    ``block_hashes`` are the engine's own keys; ``tokens``+``parent_hash``
    let the pool recompute canonical request keys. Tokenless events signal
    device-tier (offload) updates for already-known blocks.
    """

    block_hashes: list[int]
    tokens: list[int] = field(default_factory=list)
    parent_hash: int = 0
    block_size: int = 0
    device_tier: str = ""
    lora_id: Optional[int] = None
    lora_name: Optional[str] = None
    extra_keys: Optional[list[Optional[list[Any]]]] = None
    group_idx: Optional[int] = None
    kv_cache_spec_kind: str = ""
    kv_cache_spec_sliding_window: Optional[int] = None

    @property
    def type(self) -> str:
        return EVENT_TYPE_BLOCK_STORED


@dataclass
class BlockRemovedEvent:
    """Blocks evicted from an engine's cache (``events.go:106-111``)."""

    block_hashes: list[int]
    device_tier: str = ""
    group_idx: Optional[int] = None

    @property
    def type(self) -> str:
        return EVENT_TYPE_BLOCK_REMOVED


@dataclass
class AllBlocksClearedEvent:
    """Pod-wide cache reset (``events.go:119-121``), e.g. an RL weight rollout."""

    device_tier: str = ""

    @property
    def type(self) -> str:
        return EVENT_TYPE_ALL_BLOCKS_CLEARED


@dataclass
class TransferBlocksAvailableEvent:
    """Handoff transfer availability (prefill/decode disaggregation).

    A prefill pod committed ``block_hashes`` for ``request_id`` to the
    shared transfer tier; the targeted decode pod may pull them now.
    ``done`` marks the final chunk (no more blocks will be published for
    this request). Deliberately NOT part of :data:`GenericEvent` — the
    index pool learns storage residency from the tier's own tokenless
    BlockStored events; this event is the *streamed per-chunk completion*
    a remote handoff coordinator forwards to the decode pod, so the pull
    can start before the prefill tail finishes.
    """

    request_id: str
    block_hashes: list[int]
    decode_pod: str = ""
    done: bool = False

    @property
    def type(self) -> str:
        return EVENT_TYPE_TRANSFER_AVAILABLE


GenericEvent = BlockStoredEvent | BlockRemovedEvent | AllBlocksClearedEvent


@dataclass
class EventBatch:
    """A batch of parsed events from one engine message.

    ``traceparent`` carries the publisher's W3C trace context across the
    ZMQ hop (wire element [3], after dp_rank) so ingest spans parent into
    the trace that caused the cache mutation; None when the publisher was
    untraced or the engine predates the field.

    ``epoch`` is the publishing pod's topology epoch (wire element [4],
    after traceparent; cluster.membership) — the ingest fence rejects or
    flags batches from pods whose view of the fleet is stale. 0 when the
    publisher predates the epoch plane (never fenced).
    """

    timestamp: float
    events: list[GenericEvent]
    data_parallel_rank: Optional[int] = None
    traceparent: Optional[str] = None
    epoch: int = 0


class EngineAdapter(Protocol):
    """Engine-specific message parser (``events.go:71-80``)."""

    def parse_message(self, msg: RawMessage) -> tuple[str, str, EventBatch]:
        """Parse a raw message → (pod_id, model_name, batch)."""
        ...

    def sharding_key(self, msg: RawMessage) -> str:
        """Key that shards messages across worker queues; messages sharing a
        key are processed in order."""
        ...
