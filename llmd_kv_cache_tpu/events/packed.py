"""Packed zero-copy event frames (docs/architecture.md "Native data plane").

The msgpack event wire materializes one Python object per block hash and
per token before the pool can touch them — at fleet ingest rates the
decode alloc churn, not the index, dominates the worker profile. This
module defines the packed alternative: a fixed struct header plus raw
little-endian key/token arrays, decoded with ``np.frombuffer`` into
*views over the received buffer*. No per-element Python object is ever
created; the uint64 engine keys and uint32 tokens flow from the socket
buffer straight into the native hash chain and ``kvidx_add``.

Frame layout (little-endian, offsets in bytes)::

    0   4s  magic  b"KZC1"
    4   H   pod_id byte length
    6   H   model_name byte length
    8   I   engine block size (tokens per engine block; 0 = unknown)
    12  d   event batch timestamp (unix seconds, publisher clock)
    20  Q   parent engine hash (0 = chain root)
    28  I   n_engine_keys
    32  I   n_tokens
    36  ... pod_id bytes, model_name bytes, zero padding to an 8-byte
            boundary, engine_keys (n*u64), tokens (n*u32)

One frame is one BlockStored digest — the hot-path event shape; removal
and clear events stay on the msgpack wire (they are rare and cheap).
Consumers sniff the 4-byte magic, so packed and msgpack frames can share
one transport. The same frames ride the shared-memory ring
(:mod:`.shm_ring`) unchanged.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

MAGIC = b"KZC1"
_HEADER = struct.Struct("<4sHHIdQII")
HEADER_SIZE = _HEADER.size  # 36


def is_packed(payload: bytes) -> bool:
    """Cheap transport-side sniff: does this payload carry a packed frame?"""
    return len(payload) >= 4 and payload[:4] == MAGIC


def _pad8(n: int) -> int:
    return (n + 7) & ~7


@dataclass
class PackedBatch:
    """Decoded view of one packed frame.

    ``engine_keys``/``tokens`` are read-only numpy views over the frame
    buffer — hold the frame alive as long as they are in use (the pool
    consumes them within one worker iteration, so this never bites in
    practice).
    """

    pod_id: str
    model_name: str
    timestamp: float
    parent_hash: int
    block_size: int
    engine_keys: np.ndarray  # uint64 view
    tokens: np.ndarray  # uint32 view


def encode_packed_batch(
    pod_id: str,
    model_name: str,
    engine_keys,
    tokens,
    *,
    timestamp: float,
    parent_hash: int = 0,
    block_size: int = 0,
) -> bytes:
    """Assemble one frame (publisher side / tests / bench)."""
    pod_b = pod_id.encode("utf-8")
    model_b = model_name.encode("utf-8")
    ek = np.ascontiguousarray(
        np.asarray(engine_keys, dtype=np.uint64).ravel()
    )
    tok = np.ascontiguousarray(
        np.asarray(tokens, dtype=np.uint32).ravel()
    )
    strings_end = HEADER_SIZE + len(pod_b) + len(model_b)
    arrays_off = _pad8(strings_end)
    buf = bytearray(arrays_off + ek.nbytes + tok.nbytes)
    _HEADER.pack_into(
        buf, 0, MAGIC, len(pod_b), len(model_b), block_size,
        float(timestamp), int(parent_hash) & 0xFFFFFFFFFFFFFFFF,
        len(ek), len(tok),
    )
    buf[HEADER_SIZE:HEADER_SIZE + len(pod_b)] = pod_b
    buf[HEADER_SIZE + len(pod_b):strings_end] = model_b
    buf[arrays_off:arrays_off + ek.nbytes] = ek.tobytes()
    tok_off = arrays_off + ek.nbytes
    buf[tok_off:tok_off + tok.nbytes] = tok.tobytes()
    return bytes(buf)


def decode_packed_batch(payload: bytes) -> PackedBatch:
    """Decode one frame into buffer views. Raises ValueError on a
    malformed frame (bad magic, truncated arrays) — callers treat that
    like any other parse failure."""
    if len(payload) < HEADER_SIZE:
        raise ValueError("packed frame shorter than header")
    (magic, pod_len, model_len, block_size, ts, parent_hash,
     n_ek, n_tok) = _HEADER.unpack_from(payload, 0)
    if magic != MAGIC:
        raise ValueError(f"bad packed-frame magic {magic!r}")
    strings_end = HEADER_SIZE + pod_len + model_len
    arrays_off = _pad8(strings_end)
    need = arrays_off + n_ek * 8 + n_tok * 4
    if len(payload) < need:
        raise ValueError(
            f"truncated packed frame: {len(payload)} < {need} bytes"
        )
    pod_id = payload[HEADER_SIZE:HEADER_SIZE + pod_len].decode("utf-8")
    model_name = payload[HEADER_SIZE + pod_len:strings_end].decode("utf-8")
    engine_keys = np.frombuffer(payload, np.uint64, n_ek, arrays_off)
    tokens = np.frombuffer(payload, np.uint32, n_tok, arrays_off + n_ek * 8)
    return PackedBatch(
        pod_id=pod_id,
        model_name=model_name,
        timestamp=float(ts),
        parent_hash=int(parent_hash),
        block_size=int(block_size),
        engine_keys=engine_keys,
        tokens=tokens,
    )
