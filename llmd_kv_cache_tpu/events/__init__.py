"""KV-event plane: ingestion of engine cache events over ZMQ.

Counterpart of reference ``pkg/kvevents/``. Engines (vLLM-TPU, SGLang, or
this repo's ``models.engine``) publish BlockStored / BlockRemoved /
AllBlocksCleared events; a sharded worker pool ingests them into the index
with per-pod ordering.
"""

from .model import (
    AllBlocksClearedEvent,
    BlockRemovedEvent,
    BlockStoredEvent,
    EventBatch,
    RawMessage,
)
from .pool import Pool, PoolConfig
from .publisher import StorageEventPublisher
from .subscriber_manager import SubscriberManager
from .zmq_subscriber import ZMQSubscriber

__all__ = [
    "AllBlocksClearedEvent",
    "BlockRemovedEvent",
    "BlockStoredEvent",
    "EventBatch",
    "RawMessage",
    "Pool",
    "PoolConfig",
    "StorageEventPublisher",
    "SubscriberManager",
    "ZMQSubscriber",
]
