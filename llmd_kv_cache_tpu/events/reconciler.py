"""Pod discovery → subscriber lifecycle reconciliation.

Counterpart of reference ``examples/kv_events/pod_reconciler`` (a
controller-runtime watch driving ``SubscriberManager.EnsureSubscriber``).
Discovery is pluggable:

- ``KubernetesDiscovery``: watches pods by label selector via the official
  client when importable (in-cluster deployments)
- ``StaticDiscovery``: fixed pod→endpoint map (config-file deployments)
- ``FileDiscovery``: polls a JSON file ``{"pod-name": "tcp://ip:5557"}`` —
  the test/compose-friendly source; anything that can write a file can
  drive discovery

The reconcile loop is source-agnostic: ensure subscribers for present
pods, remove for departed ones. Crash-only: unreachable endpoints are
harmless (the subscriber retries forever until the pod is removed).
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional, Protocol

from ..utils.logging import get_logger
from .pool import PodDiscoveryConfig
from .subscriber_manager import SubscriberManager

logger = get_logger("events.reconciler")


class DiscoverySource(Protocol):
    def discover(self) -> dict[str, str]:
        """Return the current pod-name → ZMQ endpoint map."""
        ...


class StaticDiscovery:
    def __init__(self, pods: dict[str, str]):
        self._pods = dict(pods)

    def discover(self) -> dict[str, str]:
        return dict(self._pods)

    def set(self, pods: dict[str, str]) -> None:
        self._pods = dict(pods)


class FileDiscovery:
    """Reads a JSON pod map from a file; missing file means no pods."""

    def __init__(self, path: str):
        self.path = path

    def discover(self) -> dict[str, str]:
        try:
            with open(self.path) as f:
                data = json.load(f)
            return {str(k): str(v) for k, v in data.items()}
        except (FileNotFoundError, json.JSONDecodeError):
            return {}


class KubernetesDiscovery:
    """Lists ready pods by label selector via the kubernetes client.

    Endpoint per pod: ``tcp://<pod-ip>:<socket_port>`` (reference
    ``pod_reconciler.go:86-162``). Requires the optional ``kubernetes``
    package and in-cluster or kubeconfig credentials — unless a
    ``core_api`` is injected (tests stub the CoreV1Api surface;
    ``discover`` itself is then exercised without a cluster).
    """

    def __init__(self, cfg: PodDiscoveryConfig, core_api=None):
        if core_api is not None:
            self._core = core_api
            self.cfg = cfg
            return
        try:
            from kubernetes import client, config as k8s_config
        except ImportError as e:  # pragma: no cover - optional dep
            raise RuntimeError(
                "KubernetesDiscovery requires the 'kubernetes' package"
            ) from e
        try:
            k8s_config.load_incluster_config()
        except Exception:  # pragma: no cover - local kubeconfig fallback
            k8s_config.load_kube_config()
        self._core = client.CoreV1Api()
        self.cfg = cfg

    def discover(self) -> dict[str, str]:
        kwargs = {"label_selector": self.cfg.pod_label_selector}
        if self.cfg.pod_namespace:
            pods = self._core.list_namespaced_pod(self.cfg.pod_namespace, **kwargs)
        else:
            pods = self._core.list_pod_for_all_namespaces(**kwargs)
        result = {}
        for pod in pods.items:
            if pod.status.pod_ip and pod.status.phase == "Running":
                result[pod.metadata.name] = (
                    f"tcp://{pod.status.pod_ip}:{self.cfg.socket_port}"
                )
        return result


class PodReconciler:
    """Periodic reconcile loop between a discovery source and the
    SubscriberManager."""

    def __init__(
        self,
        source: DiscoverySource,
        manager: SubscriberManager,
        interval_s: float = 5.0,
        on_change: Optional[Callable[[dict[str, str]], None]] = None,
    ):
        self.source = source
        self.manager = manager
        self.interval_s = interval_s
        self.on_change = on_change
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def reconcile_once(self) -> tuple[int, int]:
        """One reconcile pass; returns (added_or_updated, removed)."""
        try:
            desired = self.source.discover()
        except Exception:
            logger.exception("discovery failed; keeping current subscribers")
            return (0, 0)

        changed = 0
        for pod, endpoint in desired.items():
            if self.manager.ensure_subscriber(pod, endpoint):
                changed += 1
        removed = 0
        for pod in self.manager.pods():
            if pod not in desired:
                self.manager.remove_subscriber(pod)
                removed += 1
        if (changed or removed) and self.on_change is not None:
            self.on_change(desired)
        return changed, removed

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                self.reconcile_once()
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, name="pod-reconciler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
