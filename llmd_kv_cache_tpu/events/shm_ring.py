"""Same-host shared-memory event ring (docs/architecture.md
"Native data plane").

An engine colocated with its indexer shard pays ZMQ serialize → kernel →
deserialize for every event batch even though both ends share RAM. This
ring is the opt-in bypass: a file-backed mmap (``/dev/shm`` when the
host has one) carrying length-prefixed records — normally packed
:mod:`.packed` frames — from one writer to one reader with no sockets
and no copies beyond the single ``memcpy`` into the ring.

Design constraints, deliberately minimal:

- **SPSC only.** One producer, one consumer. The header keeps two u64
  cursors (absolute byte offsets, monotonically increasing); the writer
  only advances ``write_pos``, the reader only advances ``read_pos``.
  On x86/ARM64 an aligned 8-byte store is atomic, and CPython's memory
  model adds no reordering the GIL doesn't already forbid — but there is
  NO cross-process fence beyond that, which is exactly the caveat: use
  one writer process and one reader process, period.
- **Records never wrap.** A record that doesn't fit before the ring's
  end writes a skip marker (length ``0xFFFFFFFF``) and restarts at
  offset 0, so a reader always sees each record contiguous — that is
  what lets the pool hand ``np.frombuffer`` views straight into the
  index without reassembly.
- **Full ring = drop at the writer.** ``write`` returns False instead
  of blocking; the event stream is soft state and anti-entropy repairs
  holes, same policy as the pool's drop-oldest queues.
"""

from __future__ import annotations

import mmap
import os
import struct
from typing import Optional

MAGIC = b"KSHM"
VERSION = 1
HEADER_SIZE = 64
_HDR = struct.Struct("<4sIQ")  # magic, version, capacity
_U64 = struct.Struct("<Q")
_LEN = struct.Struct("<I")
_SKIP = 0xFFFFFFFF
_WRITE_POS_OFF = 16
_READ_POS_OFF = 24


def default_ring_dir() -> str:
    """``/dev/shm`` when the host mounts one (RAM-backed, the point of
    the exercise), else the system temp dir — still correct, just paged."""
    return "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"


class ShmRing:
    """One file-backed SPSC ring. ``create=True`` (writer side) sizes and
    initializes the file; the reader attaches to an existing one."""

    def __init__(self, path: str, capacity: int = 1 << 20,
                 create: bool = False):
        self.path = path
        if create:
            capacity = max(4096, int(capacity))
            with open(path, "wb") as f:
                f.truncate(HEADER_SIZE + capacity)
            self._file = open(path, "r+b")
            self._mm = mmap.mmap(self._file.fileno(),
                                 HEADER_SIZE + capacity)
            self._mm[:_HDR.size] = _HDR.pack(MAGIC, VERSION, capacity)
            self._set_u64(_WRITE_POS_OFF, 0)
            self._set_u64(_READ_POS_OFF, 0)
            self.capacity = capacity
        else:
            self._file = open(path, "r+b")
            self._mm = mmap.mmap(self._file.fileno(), 0)
            magic, version, cap = _HDR.unpack_from(self._mm, 0)
            if magic != MAGIC or version != VERSION:
                self._mm.close()
                self._file.close()
                raise ValueError(
                    f"{path} is not a v{VERSION} shm event ring"
                )
            self.capacity = int(cap)

    # -- cursor helpers ---------------------------------------------------

    def _get_u64(self, off: int) -> int:
        return _U64.unpack_from(self._mm, off)[0]

    def _set_u64(self, off: int, value: int) -> None:
        _U64.pack_into(self._mm, off, value)

    @property
    def write_pos(self) -> int:
        return self._get_u64(_WRITE_POS_OFF)

    @property
    def read_pos(self) -> int:
        return self._get_u64(_READ_POS_OFF)

    def __len__(self) -> int:
        """Unread bytes (records + framing) currently in the ring."""
        return self.write_pos - self.read_pos

    # -- writer side ------------------------------------------------------

    def write(self, record: bytes) -> bool:
        """Append one record; False when the ring lacks room (caller
        drops or falls back to the socket wire — never blocks)."""
        need = _LEN.size + len(record)
        if need > self.capacity - _LEN.size:
            return False  # can never fit, even empty
        wpos = self.write_pos
        rpos = self.read_pos
        woff = wpos % self.capacity
        # Keep records contiguous: pad to the ring start when the record
        # would straddle the end. The pad consumes ring space too.
        pad = 0
        if woff + need > self.capacity:
            pad = self.capacity - woff
        if wpos + pad + need - rpos > self.capacity:
            return False  # reader hasn't caught up
        if pad:
            if pad >= _LEN.size:
                _LEN.pack_into(self._mm, HEADER_SIZE + woff, _SKIP)
            wpos += pad
            woff = 0
        base = HEADER_SIZE + woff
        _LEN.pack_into(self._mm, base, len(record))
        self._mm[base + _LEN.size:base + need] = record
        # Publish after the payload is in place: the reader gates on
        # write_pos, so a torn record is never visible.
        self._set_u64(_WRITE_POS_OFF, wpos + need)
        return True

    # -- reader side ------------------------------------------------------

    def read(self) -> Optional[bytes]:
        """Pop one record, or None when the ring is empty. Returns a
        copy (``bytes``) so the slot can be reused immediately."""
        while True:
            rpos = self.read_pos
            if rpos >= self.write_pos:
                return None
            roff = rpos % self.capacity
            base = HEADER_SIZE + roff
            remaining = self.capacity - roff
            if remaining < _LEN.size:
                self._set_u64(_READ_POS_OFF, rpos + remaining)
                continue
            (length,) = _LEN.unpack_from(self._mm, base)
            if length == _SKIP:
                self._set_u64(_READ_POS_OFF, rpos + remaining)
                continue
            record = bytes(
                self._mm[base + _LEN.size:base + _LEN.size + length]
            )
            self._set_u64(_READ_POS_OFF, rpos + _LEN.size + length)
            return record

    def close(self) -> None:
        try:
            self._mm.close()
        finally:
            self._file.close()

    def unlink(self) -> None:
        """Remove the backing file (writer-side cleanup)."""
        try:
            os.unlink(self.path)
        except FileNotFoundError:  # lint: allow-swallow (already gone)
            pass
