"""Engine-specific event adapters."""

from .vllm import VLLMAdapter
from .sglang import SGLangAdapter


def create_adapter(engine_type: str = "vllm"):
    """Select an adapter by engine type (reference ``engineadapter/adapter.go``)."""
    engine_type = (engine_type or "vllm").lower()
    if engine_type == "vllm":
        return VLLMAdapter()
    if engine_type == "sglang":
        return SGLangAdapter()
    raise ValueError(f"unknown engine type: {engine_type}")


__all__ = ["VLLMAdapter", "SGLangAdapter", "create_adapter"]
