"""Shared adapter helpers: topic parsing and hash normalization.

Counterpart of reference ``pkg/kvevents/engineadapter/common.go``.
"""

from __future__ import annotations

from typing import Any

_MASK64 = 0xFFFFFFFFFFFFFFFF


def parse_topic(topic: str) -> tuple[str, str]:
    """Parse ``kv@<pod-id>@<model>`` → (pod_id, model).

    The model segment may itself contain ``@`` (LoRA refs etc.), so split at
    most twice (``common.go:39-45``).
    """
    parts = topic.split("@", 2)
    if len(parts) < 3:
        return (parts[1] if len(parts) > 1 else "", "")
    return parts[1], parts[2]


def hash_to_uint64(raw: Any) -> int:
    """Normalize an engine hash value to uint64.

    Engines emit block hashes as unsigned ints, signed ints (Python's hash()
    can be negative), or raw bytes (sha256-style digests, of which the last
    8 bytes big-endian are taken) — ``common.go:50-71``.
    """
    if isinstance(raw, bool):
        raise TypeError("hash value cannot be a bool")
    if isinstance(raw, int):
        return raw & _MASK64
    if isinstance(raw, (bytes, bytearray)):
        if len(raw) == 0:
            raise ValueError("empty bytes hash")
        tail = bytes(raw[-8:])
        return int.from_bytes(tail, "big")
    raise TypeError(f"unsupported hash type: {type(raw)!r}")


def to_int(raw: Any) -> int:
    if isinstance(raw, bool) or not isinstance(raw, int):
        raise TypeError(f"unsupported numeric type: {type(raw)!r}")
    return raw


def field_at(fields: list, i: int) -> Any:
    """Positional access tolerant of omitted trailing fields."""
    return fields[i] if i < len(fields) else None
