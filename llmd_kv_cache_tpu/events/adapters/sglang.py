"""SGLang engine adapter.

Counterpart of reference ``pkg/kvevents/engineadapter/sglang_adapter.go``.
SGLang emits the same positional msgpack wire format as vLLM but with a
shorter field set (no HMA group fields): BlockStored carries at most 9
fields (tag..extra_keys) and BlockRemoved at most 3 (tag, hashes, medium).
Decoding reuses the vLLM positional converters with the field lists clamped
to SGLang's schema so any future vLLM-only trailing fields are ignored.
"""

from __future__ import annotations

from typing import Any

from ..model import BlockRemovedEvent, BlockStoredEvent, GenericEvent
from .vllm import VLLMAdapter

_SGLANG_BLOCK_STORED_FIELDS = 9
_SGLANG_BLOCK_REMOVED_FIELDS = 3


class SGLangAdapter(VLLMAdapter):
    """Parses SGLang KV-event messages."""

    def _decode_event(self, raw: Any) -> GenericEvent:
        event = super()._decode_event(raw)
        if isinstance(event, BlockStoredEvent):
            # SGLang's schema ends at extra_keys; clear HMA-only fields that
            # positional decoding may have picked up from longer arrays.
            event.group_idx = None
            event.kv_cache_spec_kind = ""
            event.kv_cache_spec_sliding_window = None
        elif isinstance(event, BlockRemovedEvent):
            event.group_idx = None
        return event
