"""vLLM engine adapter.

Counterpart of reference ``pkg/kvevents/engineadapter/vllm_adapter.go``.
vLLM serializes event batches with msgspec (``array_like=True,
omit_defaults=True``): positional msgpack arrays where trailing default
fields may be absent and newer versions may append fields. Decoding is
therefore positional with length guards, never fixed-shape.

Wire shape: payload = ``[ts, [event, ...], data_parallel_rank?]``; each
event = ``[tag, ...fields]`` with tag one of BlockStored / BlockRemoved /
AllBlocksCleared.

BlockStored positions (``vllm_adapter.go:132-149``):
``[0]`` tag, ``[1]`` block_hashes, ``[2]`` parent_hash|nil, ``[3]``
token_ids, ``[4]`` block_size, ``[5]`` lora_id?, ``[6]`` medium?, ``[7]``
lora_name?, ``[8]`` extra_keys?, ``[9]`` group_idx?, ``[10]``
kv_cache_spec_kind?, ``[11]`` sliding_window?.

BlockRemoved positions (``:277-282``): ``[1]`` block_hashes, ``[2]``
medium?, ``[3]`` group_idx?.
"""

from __future__ import annotations

from typing import Any

import msgpack

from ..model import (
    AllBlocksClearedEvent,
    BlockRemovedEvent,
    BlockStoredEvent,
    EventBatch,
    GenericEvent,
    RawMessage,
)
from .common import field_at, hash_to_uint64, parse_topic, to_int


class VLLMAdapter:
    """Parses vLLM KV-event messages."""

    def sharding_key(self, msg: RawMessage) -> str:
        pod_id, _ = parse_topic(msg.topic)
        return pod_id

    def parse_message(self, msg: RawMessage) -> tuple[str, str, EventBatch]:
        pod_id, model_name = parse_topic(msg.topic)

        decoded = msgpack.unpackb(msg.payload, raw=False, strict_map_key=False)
        if not isinstance(decoded, (list, tuple)) or len(decoded) < 2:
            raise ValueError(f"malformed vLLM event batch: {type(decoded)!r}")

        ts = float(decoded[0])
        raw_events = decoded[1]
        if not isinstance(raw_events, (list, tuple)):
            raise ValueError("vLLM event batch events is not an array")

        dp_rank = None
        if len(decoded) > 2 and decoded[2] is not None:
            dp_rank = to_int(decoded[2])

        # Wire element [3]: W3C traceparent (this repo's publishers only).
        # Positional decoding with length guards keeps engines that never
        # send it — and future appended fields — parseable.
        traceparent = None
        if len(decoded) > 3 and isinstance(decoded[3], str):
            traceparent = decoded[3]

        # Wire element [4]: publisher's topology epoch (cluster.membership)
        # — 0/absent from engines that predate the epoch plane.
        epoch = 0
        if len(decoded) > 4 and decoded[4] is not None:
            try:
                epoch = int(decoded[4])
            except (TypeError, ValueError):
                epoch = 0

        events = [self._decode_event(raw) for raw in raw_events]
        return pod_id, model_name, EventBatch(
            timestamp=ts, events=events, data_parallel_rank=dp_rank,
            traceparent=traceparent, epoch=epoch,
        )

    def _decode_event(self, raw: Any) -> GenericEvent:
        # Events may arrive as nested arrays or as embedded msgpack bytes
        # (both occur depending on the publisher's serializer nesting).
        if isinstance(raw, (bytes, bytearray)):
            raw = msgpack.unpackb(raw, raw=False, strict_map_key=False)
        if not isinstance(raw, (list, tuple)) or not raw:
            raise ValueError("malformed tagged union: no tag")
        tag = raw[0]
        if not isinstance(tag, str):
            raise ValueError(f"event tag is not a string: {type(tag)!r}")
        fields = list(raw)
        if tag == "BlockStored":
            return self._convert_block_stored(fields)
        if tag == "BlockRemoved":
            return self._convert_block_removed(fields)
        if tag == "AllBlocksCleared":
            return AllBlocksClearedEvent()
        raise ValueError(f"unknown vLLM event tag: {tag}")

    def _convert_block_stored(self, fields: list) -> BlockStoredEvent:
        if len(fields) < 5:
            raise ValueError(f"BlockStored: need at least 5 fields, got {len(fields)}")

        raw_hashes = fields[1]
        if not isinstance(raw_hashes, (list, tuple)):
            raise ValueError(f"BlockStored: block_hashes is not an array: {type(fields[1])!r}")
        block_hashes = [hash_to_uint64(h) for h in raw_hashes]

        parent_hash = 0
        if fields[2] is not None:
            parent_hash = hash_to_uint64(fields[2])

        raw_tokens = fields[3]
        if not isinstance(raw_tokens, (list, tuple)):
            raise ValueError(f"BlockStored: token_ids is not an array: {type(fields[3])!r}")
        tokens = [to_int(t) & 0xFFFFFFFF for t in raw_tokens]

        block_size = to_int(fields[4])

        lora_id = None
        if (raw := field_at(fields, 5)) is not None:
            lora_id = to_int(raw)

        device_tier = ""
        if (raw := field_at(fields, 6)) is not None:
            if not isinstance(raw, str):
                raise ValueError(f"BlockStored: medium is not a string: {type(raw)!r}")
            device_tier = raw

        lora_name = None
        if (raw := field_at(fields, 7)) is not None:
            if not isinstance(raw, str):
                raise ValueError(f"BlockStored: lora_name is not a string: {type(raw)!r}")
            lora_name = raw

        extra_keys = None
        if (raw := field_at(fields, 8)) is not None:
            if not isinstance(raw, (list, tuple)):
                raise ValueError(f"BlockStored: extra_keys is not an array: {type(raw)!r}")
            extra_keys = [
                list(inner) if isinstance(inner, (list, tuple)) else inner
                for inner in raw
            ]

        group_idx = None
        if (raw := field_at(fields, 9)) is not None:
            group_idx = to_int(raw)
            if group_idx < 0:
                raise ValueError(f"BlockStored: group_idx: negative value: {group_idx}")

        spec_kind = ""
        if (raw := field_at(fields, 10)) is not None:
            if not isinstance(raw, str):
                raise ValueError(
                    f"BlockStored: kv_cache_spec_kind is not a string: {type(raw)!r}"
                )
            spec_kind = raw

        sliding_window = None
        if (raw := field_at(fields, 11)) is not None:
            sliding_window = to_int(raw)

        return BlockStoredEvent(
            block_hashes=block_hashes,
            tokens=tokens,
            parent_hash=parent_hash,
            block_size=block_size,
            device_tier=device_tier,
            lora_id=lora_id,
            lora_name=lora_name,
            extra_keys=extra_keys,
            group_idx=group_idx,
            kv_cache_spec_kind=spec_kind,
            kv_cache_spec_sliding_window=sliding_window,
        )

    def _convert_block_removed(self, fields: list) -> BlockRemovedEvent:
        if len(fields) < 2:
            raise ValueError(f"BlockRemoved: need at least 2 fields, got {len(fields)}")

        raw_hashes = fields[1]
        if not isinstance(raw_hashes, (list, tuple)):
            raise ValueError(f"BlockRemoved: block_hashes is not an array: {type(fields[1])!r}")
        block_hashes = [hash_to_uint64(h) for h in raw_hashes]

        device_tier = ""
        if (raw := field_at(fields, 2)) is not None:
            if not isinstance(raw, str):
                raise ValueError(f"BlockRemoved: medium is not a string: {type(raw)!r}")
            device_tier = raw

        group_idx = None
        if (raw := field_at(fields, 3)) is not None:
            group_idx = to_int(raw)
            if group_idx < 0:
                raise ValueError(f"BlockRemoved: group_idx: negative value: {group_idx}")

        return BlockRemovedEvent(
            block_hashes=block_hashes,
            device_tier=device_tier,
            group_idx=group_idx,
        )
