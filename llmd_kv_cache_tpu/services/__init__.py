"""Sidecar services (tokenizer/renderer over gRPC-UDS)."""
