"""Fleet telemetry collector: trace assembly, metric rollup, SLO burn rates.

The fleet-level half of the observability stack (ISSUE 10). One collector
process polls every pod's admin endpoint and turns per-process telemetry
into fleet answers:

- **Cross-process trace assembly** — pulls finished spans from each
  target's ``/debug/spans?since=seq`` (the ring exporter's cursor API),
  groups them by trace id across processes, and once a trace goes idle
  computes its **critical path**: the chain of span segments that actually
  gated the request end-to-end (score fan-out → handoff transfer →
  admission queue → prefill chunks → decode steps), with per-segment
  *self time* (span wall time not covered by on-path children). Spans are
  deduped by span id, so at-least-once pulls and shared in-process
  exporters are safe.
- **Tail-based sampling** — a trace is retained when it breached the SLO
  latency threshold, or belongs to the K-slowest reservoir, or wins the
  head-sample lottery (hash of the trace id, so the decision is stable
  across collectors). Everything else is dropped after accounting.
- **Metric rollup** — scrapes every target's ``/metrics`` and merges
  families type-correctly (``telemetry/rollup.py``), serving fleet
  TTFT/ITL/score-latency percentiles per role from ``/debug/rollup``.
- **SLO burn rates** — feeds threshold SLIs (TTFT, score latency, target
  availability) into ``telemetry/slo.py`` trackers; alert state lives at
  ``/debug/slo`` and in the ``kvtpu_slo_*`` families.

Scrapes ride the PR 1 resilience primitives: per-target
:class:`CircuitBreaker` plus a jittered :class:`RetryPolicy`, so one dead
pod degrades that target's freshness instead of stalling the round.
Stdlib-only transport (``urllib``): the collector must run on the most
degraded image available.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from prometheus_client import Counter, Gauge

from ..utils.lockdep import new_lock
from ..resilience.policy import CircuitBreaker, RetryPolicy, call_with_retry
from ..telemetry.rollup import (
    MetricFamily,
    merge_families,
    parse_exposition,
    rollup_percentiles,
)
from ..telemetry.anomaly import AnomalyRegistry, SentinelConfig
from ..telemetry.audit import AuditJoiner
from ..telemetry.incident import (
    ClockSkewEstimator,
    IncidentConfig,
    IncidentManager,
)
from ..telemetry.sampling_profiler import merge_folded, span_function_shares
from ..telemetry.slo import SLOConfig, SLORegistry
from ..telemetry.workingset import merge_workingset_windows, whatif_table
from ..telemetry.tracing import RecordedSpan, tracer
from ..utils.logging import get_logger
from .admin import AdminServer

logger = get_logger("services.telemetry_collector")

FLEET_SCRAPES = Counter(
    "kvtpu_fleet_scrapes_total",
    "Collector scrape attempts per target and outcome",
    ["target", "outcome"],  # success|failure|skipped (breaker open)
)
FLEET_SPANS_INGESTED = Counter(
    "kvtpu_fleet_spans_ingested_total",
    "Spans pulled from pod ring exporters (post-dedupe)",
)
FLEET_TRACES_ASSEMBLED = Counter(
    "kvtpu_fleet_traces_assembled_total",
    "Traces finalized by the assembler (idle-timeout reached)",
)
FLEET_TRACES_RETAINED = Counter(
    "kvtpu_fleet_traces_retained_total",
    "Finalized traces retained by the tail sampler, by reason",
    ["reason"],  # slo_breach|k_slowest|head_sample
)
FLEET_TARGETS_REACHABLE = Gauge(
    "kvtpu_fleet_targets_reachable",
    "Targets whose last scrape round succeeded",
)
FLEET_PROFILE_WINDOWS = Counter(
    "kvtpu_fleet_profile_windows_total",
    "Sampling-profiler windows pulled from pod /debug/pyprof endpoints",
)
FLEET_WORKINGSET_WINDOWS = Counter(
    "kvtpu_fleet_workingset_windows_total",
    "Working-set windows pulled from pod /debug/workingset endpoints",
)
FLEET_TYPE_CONFLICTS = Counter(
    "kvtpu_fleet_metric_type_conflicts_total",
    "Metric families skipped by the rollup because pods disagreed on TYPE",
)
FLEET_AUDIT_RECORDS = Counter(
    "kvtpu_fleet_audit_records_total",
    "Audit records (predictions + outcomes) pulled from pod /debug/audit "
    "endpoints",
)

# Fleet-level serving histograms worth rolling up, per role.
_ROLLUP_FAMILIES = (
    "kvtpu_engine_ttft_seconds",
    "kvtpu_engine_itl_seconds",
    "kvcache_score_latency_seconds",
)


@dataclass(frozen=True)
class ScrapeTarget:
    """One pod admin endpoint: ``address`` is ``host:port``."""

    name: str
    address: str
    role: str = ""  # prefill|decode|indexer-shard|router|""

    @classmethod
    def from_dict(cls, data: dict) -> "ScrapeTarget":
        return cls(
            name=str(data.get("name") or data.get("address", "")),
            address=str(data["address"]),
            role=str(data.get("role", "")),
        )


@dataclass(frozen=True)
class CollectorConfig:
    """``fleetTelemetry.collector`` config block (camelCase in files)."""

    targets: Tuple[ScrapeTarget, ...] = ()
    scrape_interval_s: float = 5.0
    admin_port: int = 0
    host: str = "127.0.0.1"
    # Trace assembly/sampling.
    trace_idle_s: float = 1.0
    max_traces: int = 256
    k_slowest: int = 8
    head_sample_rate: float = 0.01
    slo_latency_threshold_s: float = 2.0
    # SLO thresholds/objectives.
    ttft_threshold_s: float = 2.0
    ttft_objective: float = 0.99
    score_threshold_s: float = 0.1
    score_objective: float = 0.99
    restore_threshold_s: float = 0.25
    restore_objective: float = 0.99
    availability_objective: float = 0.999
    # Continuous-profiling leg: pull /debug/pyprof windows from every
    # target (404 from a pod with the sampler off is tolerated and never
    # trips that target's breaker) and keep the newest pyprof_max_windows
    # fleet-wide for merging.
    pyprof_enabled: bool = True
    pyprof_max_windows: int = 120
    # Working-set analytics leg: pull /debug/workingset windows (404 from
    # a pod without the tracker is tolerated, same as pyprof) and keep
    # the newest workingset_max_windows fleet-wide; the what-if capacity
    # table evaluates the merged MRC at these multiples of current HBM.
    workingset_enabled: bool = True
    workingset_max_windows: int = 240
    whatif_factors: Tuple[float, ...] = (0.5, 1.0, 2.0, 4.0)
    # Ground-truth audit leg: pull /debug/audit records (404 from a pod
    # without the audit ring is tolerated, same as pyprof) and join
    # predictions to realized outcomes per trace — calibration curves,
    # staleness-attributed error, and the routing-regret counterfactual.
    audit_enabled: bool = True
    # Score-time index staleness above this attributes a misprediction to
    # "stale" (event lag) rather than "fresh" (model error).
    audit_stale_threshold_s: float = 1.0
    # A losing pod's calibrated estimate must beat the chosen pod's
    # realized hit by this many blocks before a regret is charged.
    audit_regret_margin_blocks: float = 0.5
    # index_divergence SLI: fraction of divergence-audit pod-checks that
    # found the advertised index matching engine truth.
    divergence_objective: float = 0.999
    # Anomaly sentinels (telemetry/anomaly.py): robust MAD/z detectors
    # over the per-round SLI series (ingest lag, restore latency, hedge
    # spend, fence rejections, shed rate) beyond the burn-rate alerts.
    anomaly_enabled: bool = True
    anomaly_window: int = 64
    anomaly_min_samples: int = 8
    anomaly_z_threshold: float = 6.0
    anomaly_clear_threshold: float = 3.0
    anomaly_min_consecutive: int = 2
    # Incident black-box capture (telemetry/incident.py): alert/anomaly
    # fire edges (and the manual /debug/incident/open action) snapshot
    # fleet evidence into CRC-footed CBOR bundles under
    # ``incident.directory``.
    incident: IncidentConfig = IncidentConfig()
    fast_windows: Tuple[float, float] = (300.0, 3600.0)
    slow_window: float = 21600.0
    fast_threshold: float = 14.4
    slow_threshold: float = 6.0
    # Scrape resilience.
    request_timeout_s: float = 2.0
    retry_attempts: int = 2
    breaker_failures: int = 3
    breaker_reset_s: float = 10.0

    @classmethod
    def from_dict(cls, data: Optional[dict]) -> "CollectorConfig":
        if not data:
            return cls()

        def k(camel: str, snake: str, default):
            if camel in data:
                return data[camel]
            if snake in data:
                return data[snake]
            return default

        d = cls()
        fast = k("fastWindows", "fast_windows", d.fast_windows)
        return cls(
            targets=tuple(
                ScrapeTarget.from_dict(t)
                for t in k("targets", "targets", ())
            ),
            scrape_interval_s=float(
                k("scrapeIntervalS", "scrape_interval_s", d.scrape_interval_s)),
            admin_port=int(k("adminPort", "admin_port", d.admin_port)),
            host=str(k("host", "host", d.host)),
            trace_idle_s=float(k("traceIdleS", "trace_idle_s", d.trace_idle_s)),
            max_traces=int(k("maxTraces", "max_traces", d.max_traces)),
            k_slowest=int(k("kSlowest", "k_slowest", d.k_slowest)),
            head_sample_rate=float(
                k("headSampleRate", "head_sample_rate", d.head_sample_rate)),
            slo_latency_threshold_s=float(
                k("sloLatencyThresholdS", "slo_latency_threshold_s",
                  d.slo_latency_threshold_s)),
            ttft_threshold_s=float(
                k("ttftThresholdS", "ttft_threshold_s", d.ttft_threshold_s)),
            ttft_objective=float(
                k("ttftObjective", "ttft_objective", d.ttft_objective)),
            score_threshold_s=float(
                k("scoreThresholdS", "score_threshold_s", d.score_threshold_s)),
            score_objective=float(
                k("scoreObjective", "score_objective", d.score_objective)),
            restore_threshold_s=float(
                k("restoreThresholdS", "restore_threshold_s",
                  d.restore_threshold_s)),
            restore_objective=float(
                k("restoreObjective", "restore_objective",
                  d.restore_objective)),
            availability_objective=float(
                k("availabilityObjective", "availability_objective",
                  d.availability_objective)),
            pyprof_enabled=bool(
                k("pyprofEnabled", "pyprof_enabled", d.pyprof_enabled)),
            pyprof_max_windows=int(
                k("pyprofMaxWindows", "pyprof_max_windows",
                  d.pyprof_max_windows)),
            workingset_enabled=bool(
                k("workingsetEnabled", "workingset_enabled",
                  d.workingset_enabled)),
            workingset_max_windows=int(
                k("workingsetMaxWindows", "workingset_max_windows",
                  d.workingset_max_windows)),
            whatif_factors=tuple(
                float(f) for f in
                k("whatifFactors", "whatif_factors", d.whatif_factors)),
            audit_enabled=bool(
                k("auditEnabled", "audit_enabled", d.audit_enabled)),
            audit_stale_threshold_s=float(
                k("auditStaleThresholdS", "audit_stale_threshold_s",
                  d.audit_stale_threshold_s)),
            audit_regret_margin_blocks=float(
                k("auditRegretMarginBlocks", "audit_regret_margin_blocks",
                  d.audit_regret_margin_blocks)),
            divergence_objective=float(
                k("divergenceObjective", "divergence_objective",
                  d.divergence_objective)),
            anomaly_enabled=bool(
                k("anomalyEnabled", "anomaly_enabled", d.anomaly_enabled)),
            anomaly_window=int(
                k("anomalyWindow", "anomaly_window", d.anomaly_window)),
            anomaly_min_samples=int(
                k("anomalyMinSamples", "anomaly_min_samples",
                  d.anomaly_min_samples)),
            anomaly_z_threshold=float(
                k("anomalyZThreshold", "anomaly_z_threshold",
                  d.anomaly_z_threshold)),
            anomaly_clear_threshold=float(
                k("anomalyClearThreshold", "anomaly_clear_threshold",
                  d.anomaly_clear_threshold)),
            anomaly_min_consecutive=int(
                k("anomalyMinConsecutive", "anomaly_min_consecutive",
                  d.anomaly_min_consecutive)),
            incident=IncidentConfig.from_dict(
                k("incident", "incident", None)),
            fast_windows=(float(fast[0]), float(fast[1])),
            slow_window=float(k("slowWindow", "slow_window", d.slow_window)),
            fast_threshold=float(
                k("fastThreshold", "fast_threshold", d.fast_threshold)),
            slow_threshold=float(
                k("slowThreshold", "slow_threshold", d.slow_threshold)),
            request_timeout_s=float(
                k("requestTimeoutS", "request_timeout_s", d.request_timeout_s)),
            retry_attempts=int(
                k("retryAttempts", "retry_attempts", d.retry_attempts)),
            breaker_failures=int(
                k("breakerFailures", "breaker_failures", d.breaker_failures)),
            breaker_reset_s=float(
                k("breakerResetS", "breaker_reset_s", d.breaker_reset_s)),
        )


# -- critical path -----------------------------------------------------------


def critical_path(spans: List[RecordedSpan]) -> List[dict]:
    """Per-segment critical-path attribution for one assembled trace.

    Walks backward from the **latest end in the root's subtree** — not the
    root span's own end, because in the score→serve shape the root
    (``GetPodScores``) returns long before the spans it parents (handoff
    transfer, admission, prefill chunks, decode steps) finish. At each
    span, the child subtree whose end is latest (but not after the cursor)
    is the one the request was actually waiting on; the uncovered
    remainder inside the span's own lifetime is its *self time*. Wall
    time covered by no span at all (gaps between sequential children
    after their parent returned — queueing, scheduling, engine init) is
    surfaced as one synthetic ``(untracked)`` segment rather than
    mis-billed to whichever tiny span encloses the gap in the tree.
    Returns ordered segments ``{name, process, start, end,
    self_time_s}`` (earliest first), one per on-path span; the segments'
    ``self_time_s`` values tile the trace duration exactly.

    Orphan spans (parent never exported, e.g. dropped by the ring) start
    their own subtree only when nothing else claims the root; the path
    follows the earliest-starting root candidate with an end time.
    """
    by_id = {s.span_id: s for s in spans if s.end_time is not None}
    children: Dict[int, List[RecordedSpan]] = {}
    roots = []
    for s in by_id.values():
        if s.parent_span_id is not None and s.parent_span_id in by_id:
            children.setdefault(s.parent_span_id, []).append(s)
        else:
            roots.append(s)
    if not roots:
        return []
    root = min(roots, key=lambda s: s.start_time)
    segments: List[dict] = []

    subtree_ends: Dict[int, float] = {}

    def subtree_end(span: RecordedSpan) -> float:
        cached = subtree_ends.get(span.span_id)
        if cached is not None:
            return cached
        end = span.end_time
        for child in children.get(span.span_id, ()):
            end = max(end, subtree_end(child))
        subtree_ends[span.span_id] = end
        return end

    untracked = [0.0]

    def visit(span: RecordedSpan, end_cursor: float) -> None:
        cursor = end_cursor
        self_time = 0.0

        def credit(lo: float, hi: float) -> None:
            # Wall time [lo, hi) covered by no child: the portion inside
            # the span's own lifetime is its self time; the overhang
            # (children outlasting the span, inter-child gaps after it
            # returned) is untracked — real critical-path time no span
            # instruments.
            nonlocal self_time
            if hi <= lo:
                return
            own = max(0.0, min(hi, span.end_time) - max(lo, span.start_time))
            self_time += own
            untracked[0] += (hi - lo) - own

        kids = sorted(
            children.get(span.span_id, ()),
            key=subtree_end,
            reverse=True,
        )
        for child in kids:
            if child.start_time >= cursor:
                continue  # fully shadowed by a later sibling already walked
            child_end = min(subtree_end(child), cursor)
            if child_end <= child.start_time:
                continue
            credit(child_end, cursor)
            visit(child, child_end)
            cursor = min(cursor, child.start_time)
        credit(span.start_time, cursor)
        segments.append({
            "name": span.name,
            "process": str(span.attributes.get("process", "")),
            "start": span.start_time,
            "end": span.end_time,
            "self_time_s": round(self_time, 6),
        })

    end = subtree_end(root)
    visit(root, end)
    if untracked[0] > 1e-9:
        segments.append({
            "name": "(untracked)",
            "process": "",
            "start": root.start_time,
            "end": end,
            "self_time_s": round(untracked[0], 6),
        })
    segments.sort(key=lambda seg: seg["start"])
    return segments


# -- trace assembly + tail sampling ------------------------------------------


class TraceAssembler:
    """Groups pulled spans by trace id; finalizes idle traces.

    A trace is *finalized* once no new span arrived for ``idle_s`` —
    cross-process ingestion has no explicit end marker, so idleness is the
    completion signal (same trick tail-sampling OTel collectors use).
    """

    def __init__(
        self,
        idle_s: float = 1.0,
        slo_threshold_s: float = 2.0,
        k_slowest: int = 8,
        head_sample_rate: float = 0.01,
        max_traces: int = 256,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._idle_s = idle_s
        self._slo_threshold_s = slo_threshold_s
        self._k_slowest = max(0, k_slowest)
        self._head_rate = min(max(head_sample_rate, 0.0), 1.0)
        self._max_traces = max(1, max_traces)
        self._clock = clock
        self._lock = new_lock()
        # trace_id -> {"spans": {span_id: RecordedSpan}, "last": mono_ts}
        self._open: Dict[int, dict] = {}
        self._retained: Dict[int, dict] = {}
        self._retained_order: List[int] = []
        self._seen_span_ids: Dict[int, set] = {}
        self.assembled = 0
        self.sampled_out = 0

    def ingest(self, wire_spans: List[dict]) -> int:
        """Add pulled spans (wire dicts); returns newly ingested count."""
        now = self._clock()
        added = 0
        with self._lock:
            for data in wire_spans:
                try:
                    span = RecordedSpan.from_wire(data)
                except Exception:
                    continue  # one bad span must not poison the pull
                if span.trace_id == 0 or span.span_id == 0:
                    continue
                seen = self._seen_span_ids.setdefault(span.trace_id, set())
                if span.span_id in seen:
                    continue
                seen.add(span.span_id)
                entry = self._open.setdefault(
                    span.trace_id, {"spans": {}, "last": now})
                entry["spans"][span.span_id] = span
                entry["last"] = now
                added += 1
        if added:
            FLEET_SPANS_INGESTED.inc(added)
        return added

    def finalize_idle(self, force: bool = False) -> List[dict]:
        """Assemble every idle (or, with ``force``, every open) trace."""
        now = self._clock()
        done: List[Tuple[int, dict]] = []
        with self._lock:
            for tid in list(self._open):
                if force or now - self._open[tid]["last"] >= self._idle_s:
                    done.append((tid, self._open.pop(tid)))
        out = []
        for tid, entry in done:
            summary = self._assemble(tid, entry)
            FLEET_TRACES_ASSEMBLED.inc()
            self.assembled += 1
            reason = self._retention_reason(tid, summary)
            if reason is not None:
                summary["retained_reason"] = reason
                FLEET_TRACES_RETAINED.labels(reason).inc()
                self._retain(tid, summary)
            else:
                self.sampled_out += 1
                with self._lock:
                    self._seen_span_ids.pop(tid, None)
            out.append(summary)
        return out

    def _assemble(self, trace_id: int, entry: dict) -> dict:
        spans = [s for s in entry["spans"].values() if s.end_time is not None]
        spans.sort(key=lambda s: s.start_time)
        processes = sorted(
            {str(s.attributes.get("process", "")) for s in spans} - {""})
        start = min((s.start_time for s in spans), default=0.0)
        end = max((s.end_time for s in spans), default=0.0)
        path = critical_path(spans)
        return {
            "trace_id": f"{trace_id:032x}",
            "span_count": len(spans),
            "processes": processes,
            "duration_s": round(max(0.0, end - start), 6),
            "critical_path": path,
            "critical_path_processes": sorted(
                {seg["process"] for seg in path} - {""}),
        }

    def _retention_reason(self, trace_id: int, summary: dict) -> Optional[str]:
        if summary["duration_s"] >= self._slo_threshold_s:
            return "slo_breach"
        if self._k_slowest > 0:
            with self._lock:
                slowest = sorted(
                    (t["duration_s"] for t in self._retained.values()
                     if t.get("retained_reason") == "k_slowest"),
                    reverse=True,
                )
            if len(slowest) < self._k_slowest or \
                    summary["duration_s"] > slowest[min(len(slowest), self._k_slowest) - 1]:
                return "k_slowest"
        if self._head_rate > 0.0:
            digest = hashlib.sha256(summary["trace_id"].encode()).digest()
            if int.from_bytes(digest[:8], "big") / 2**64 < self._head_rate:
                return "head_sample"
        return None

    def _retain(self, trace_id: int, summary: dict) -> None:
        with self._lock:
            self._retained[trace_id] = summary
            self._retained_order.append(trace_id)
            while len(self._retained_order) > self._max_traces:
                old = self._retained_order.pop(0)
                self._retained.pop(old, None)
                self._seen_span_ids.pop(old, None)

    def retained(self) -> List[dict]:
        with self._lock:
            return [self._retained[t] for t in self._retained_order
                    if t in self._retained]

    def find_trace(self, trace_id_hex: str) -> Optional[dict]:
        try:
            tid = int(trace_id_hex, 16)
        except ValueError:
            return None
        with self._lock:
            return self._retained.get(tid)

    def debug_view(self) -> dict:
        with self._lock:
            open_count = len(self._open)
            retained = [self._retained[t] for t in self._retained_order
                        if t in self._retained]
        return {
            "open_traces": open_count,
            "assembled_total": self.assembled,
            "sampled_out_total": self.sampled_out,
            "retained": retained,
        }


# -- the collector service ---------------------------------------------------


@dataclass
class _TargetState:
    target: ScrapeTarget
    breaker: CircuitBreaker
    span_cursor: int = -1
    pyprof_cursor: int = -1
    workingset_cursor: int = -1
    audit_cursor: int = -1
    reachable: bool = False
    families: Dict[str, MetricFamily] = field(default_factory=dict)
    last_hist_counts: Dict[str, Tuple[float, float]] = field(default_factory=dict)
    # Cumulative counter values from the previous round (per family key),
    # for the anomaly sentinels' per-round rate deltas.
    last_counters: Dict[str, float] = field(default_factory=dict)
    # Per-sentinel recent sample series for this target — the evidence
    # incident bundles carry so kvdiag's first-anomalous-pod heuristic
    # can re-score each pod offline.
    sli_history: Dict[str, deque] = field(default_factory=dict)


class TelemetryCollector:
    """Scrape loop + assembler + rollup + SLO registry + admin surface."""

    def __init__(
        self,
        config: CollectorConfig,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = config
        self._clock = clock
        self._retry = RetryPolicy(
            max_attempts=max(1, config.retry_attempts),
            base_delay_s=0.02,
            max_delay_s=0.2,
            deadline_s=config.request_timeout_s,
        )
        self._targets = [
            _TargetState(
                target=t,
                breaker=CircuitBreaker(
                    target=t.name,
                    failure_threshold=config.breaker_failures,
                    reset_timeout_s=config.breaker_reset_s,
                    clock=clock,
                ),
            )
            for t in config.targets
        ]
        self.assembler = TraceAssembler(
            idle_s=config.trace_idle_s,
            slo_threshold_s=config.slo_latency_threshold_s,
            k_slowest=config.k_slowest,
            head_sample_rate=config.head_sample_rate,
            max_traces=config.max_traces,
            clock=clock,
        )
        self.slos = SLORegistry(clock=clock)
        windows = dict(
            fast_windows=config.fast_windows,
            slow_window=config.slow_window,
            fast_threshold=config.fast_threshold,
            slow_threshold=config.slow_threshold,
        )
        self.slos.add(SLOConfig(
            name="ttft",
            objective=config.ttft_objective,
            description=f"TTFT <= {config.ttft_threshold_s}s", **windows))
        self.slos.add(SLOConfig(
            name="score_latency",
            objective=config.score_objective,
            description=f"score_tokens <= {config.score_threshold_s}s",
            **windows))
        self.slos.add(SLOConfig(
            name="restore_latency",
            objective=config.restore_objective,
            description=f"KV restore <= {config.restore_threshold_s}s "
                        "(any tier)", **windows))
        self.slos.add(SLOConfig(
            name="availability",
            objective=config.availability_objective,
            description="scrape target reachable", **windows))
        self.slos.add(SLOConfig(
            name="index_divergence",
            objective=config.divergence_objective,
            description="divergence audit finds index matching engine "
                        "truth", **windows))
        # Anomaly sentinels: one robust-z detector per watched SLI series
        # (fed once per scrape round), sharing the SLO registry's edge
        # cursor contract so the controller and the incident manager
        # consume both streams identically.
        self.anomalies = AnomalyRegistry(clock=clock)
        sentinel_knobs = dict(
            window=config.anomaly_window,
            min_samples=config.anomaly_min_samples,
            z_threshold=config.anomaly_z_threshold,
            clear_threshold=config.anomaly_clear_threshold,
            min_consecutive=config.anomaly_min_consecutive,
        )
        for name, description, floor in (
                ("ingest_lag", "worst per-pod event-ingest lag (s)", 0.05),
                ("restore_latency", "worst per-pod mean KV restore (s)",
                 0.01),
                ("hedge_spend", "hedged shard RPCs issued per round", 1.0),
                ("fence_rejections", "stale-epoch rejections per round",
                 1.0),
                ("shed_rate", "requests shed per round", 1.0)):
            self.anomalies.add(SentinelConfig(
                name=name, description=description,
                absolute_floor=floor, **sentinel_knobs))
        # Clock-skew estimation + incident black-box capture.
        self.skew = ClockSkewEstimator()
        self.incidents = IncidentManager(
            config.incident,
            fetch=self._fetch,
            targets=lambda: [
                (s.target.name, s.target.address, s.breaker)
                for s in self._targets
            ],
            local_evidence=self.incident_evidence,
            skew=self.skew,
            clock=clock,
        )
        self._slo_edge_cursor = -1
        self._anomaly_edge_cursor = -1
        # Score-vs-reality join: predictions and outcomes pulled from the
        # pod audit rings land here, keyed by trace id.
        self.joiner = AuditJoiner(
            stale_threshold_s=config.audit_stale_threshold_s,
            regret_margin_blocks=config.audit_regret_margin_blocks,
        )
        self._profile_lock = new_lock()
        self._profile_windows: deque = deque(
            maxlen=max(1, config.pyprof_max_windows))
        self._workingset_windows: deque = deque(
            maxlen=max(1, config.workingset_max_windows))
        # TYPE-conflicted families already warned about (warn + count
        # once per family name, not per rollup read).
        self._warned_type_conflicts: set = set()
        self._tracer = tracer()
        self._admin: Optional[AdminServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.rounds = 0

    # -- transport ---------------------------------------------------------

    def _fetch(self, url: str) -> bytes:
        def one() -> bytes:
            with urllib.request.urlopen(
                    url, timeout=self.cfg.request_timeout_s) as resp:
                return resp.read()

        return call_with_retry(one, self._retry)

    def _scrape_target(self, state: _TargetState) -> bool:
        """One target's spans + metrics pull; returns reachability."""
        name = state.target.name
        if not state.breaker.allow():
            FLEET_SCRAPES.labels(name, "skipped").inc()
            return False
        base = f"http://{state.target.address}"
        try:
            spans_raw = self._fetch(
                f"{base}/debug/spans?since={state.span_cursor}")
            metrics_raw = self._fetch(f"{base}/metrics")
        except Exception as exc:
            state.breaker.record_failure()
            FLEET_SCRAPES.labels(name, "failure").inc()
            logger.debug("scrape of %s failed: %s", name, exc)
            return False
        state.breaker.record_success()
        FLEET_SCRAPES.labels(name, "success").inc()
        # Clock-echo leg: one tiny GET bracketed by two local clock
        # readings refreshes this pod's skew estimate every round (the
        # estimator rejects congested samples itself); failures are
        # swallowed inside update() — skew is an enrichment, never a
        # health signal.
        self.skew.update(
            name, lambda: json.loads(self._fetch(f"{base}/debug/time")))
        try:
            payload = json.loads(spans_raw)
            self.assembler.ingest(payload.get("spans", []))
            state.span_cursor = int(payload.get("next_seq", state.span_cursor))
        except Exception as exc:
            logger.debug("span payload from %s unparseable: %s", name, exc)
        try:
            state.families = parse_exposition(metrics_raw.decode("utf-8"))
        except Exception as exc:
            logger.debug("metrics from %s unparseable: %s", name, exc)
        # Profile leg: separate try so a pod without the sampler (404) or
        # with a flaky pyprof endpoint stays "reachable" and never trips
        # the breaker — profiles are an enrichment, not a health signal.
        if self.cfg.pyprof_enabled:
            try:
                prof_raw = self._fetch(
                    f"{base}/debug/pyprof?since={state.pyprof_cursor}")
                prof = json.loads(prof_raw)
                windows = prof.get("windows", [])
                with self._profile_lock:
                    for window in windows:
                        window = dict(window)
                        window.setdefault("process", "")
                        window["target"] = name
                        self._profile_windows.append(window)
                if windows:
                    FLEET_PROFILE_WINDOWS.inc(len(windows))
                state.pyprof_cursor = int(
                    prof.get("next_seq", state.pyprof_cursor))
            except Exception as exc:
                logger.debug("pyprof pull from %s skipped: %s", name, exc)
        # Working-set leg: same enrichment contract as pyprof — a 404
        # from a pod without the tracker never trips the breaker.
        if self.cfg.workingset_enabled:
            try:
                ws_raw = self._fetch(
                    f"{base}/debug/workingset?since={state.workingset_cursor}")
                ws = json.loads(ws_raw)
                windows = ws.get("windows", [])
                with self._profile_lock:
                    for window in windows:
                        window = dict(window)
                        window.setdefault("process", "")
                        window["target"] = name
                        self._workingset_windows.append(window)
                if windows:
                    FLEET_WORKINGSET_WINDOWS.inc(len(windows))
                state.workingset_cursor = int(
                    ws.get("next_seq", state.workingset_cursor))
            except Exception as exc:
                logger.debug("workingset pull from %s skipped: %s", name, exc)
        # Audit leg: same enrichment contract — a 404 from a pod without
        # the audit ring (fleetTelemetry.audit off) never trips the
        # breaker. Records feed the score-vs-reality joiner.
        if self.cfg.audit_enabled:
            try:
                audit_raw = self._fetch(
                    f"{base}/debug/audit?since={state.audit_cursor}")
                audit = json.loads(audit_raw)
                records = audit.get("records", [])
                if records:
                    self.joiner.ingest(records)
                    FLEET_AUDIT_RECORDS.inc(len(records))
                state.audit_cursor = int(
                    audit.get("next_seq", state.audit_cursor))
            except Exception as exc:
                logger.debug("audit pull from %s skipped: %s", name, exc)
        return True

    # -- SLI extraction ----------------------------------------------------

    def _feed_latency_slis(self) -> None:
        """Per-round good/bad deltas from each target's histograms.

        Good = observations at or under the SLO threshold bucket; bad =
        over it. Deltas are per-target against the previous scrape, so
        restarts (cumulative counts going backward) reset cleanly.
        """
        feeds = (
            ("ttft", "kvtpu_engine_ttft_seconds", self.cfg.ttft_threshold_s),
            ("score_latency", "kvcache_score_latency_seconds",
             self.cfg.score_threshold_s),
            ("restore_latency", "kvtpu_offload_restore_seconds",
             self.cfg.restore_threshold_s),
        )
        for slo_name, family, threshold in feeds:
            tracker = self.slos.get(slo_name)
            if tracker is None:
                continue
            for state in self._targets:
                fam = state.families.get(family)
                if fam is None or fam.type != "histogram":
                    continue
                total = 0.0
                # Cumulative buckets are per labelset (the restore family
                # carries a ``tier`` label): take the widest bucket at or
                # under the threshold *per labelset*, then sum across
                # labelsets — a plain max would undercount every labelset
                # but the busiest tier.
                under_by_labels: Dict[tuple, float] = {}
                for (suffix, labels), value in fam.samples.items():
                    if suffix == "_count":
                        total += value
                    elif suffix == "_bucket":
                        le = dict(labels).get("le", "+Inf")
                        try:
                            bound = float("inf") if le == "+Inf" else float(le)
                        except ValueError:
                            continue
                        if bound <= threshold:
                            rest = tuple(kv for kv in labels
                                         if kv[0] != "le")
                            under_by_labels[rest] = max(
                                under_by_labels.get(rest, 0.0), value)
                under = sum(under_by_labels.values())
                key = f"{state.target.name}:{family}"
                prev_total, prev_under = state.last_hist_counts.get(
                    key, (0.0, 0.0))
                if total < prev_total:  # target restarted
                    prev_total, prev_under = 0.0, 0.0
                d_total = total - prev_total
                d_under = min(under - prev_under, d_total)
                state.last_hist_counts[key] = (total, under)
                if d_total > 0:
                    tracker.record(
                        good=int(round(d_under)),
                        bad=int(round(d_total - d_under)),
                    )

    def _feed_divergence_sli(self) -> None:
        """Per-round good/bad deltas from the divergence-audit counters.

        Each pod-check the auditor runs increments
        ``kvtpu_index_divergence_checked_total{pod=...}`` and, when the
        advertised index disagreed with engine truth,
        ``..._divergent_total{pod=...}``. Good = checks that matched, bad
        = checks that diverged; deltas are per (target, pod) against the
        previous scrape so restarts reset cleanly (same bookkeeping as
        :meth:`_feed_latency_slis`).
        """
        tracker = self.slos.get("index_divergence")
        if tracker is None:
            return
        for state in self._targets:
            # prometheus_client stamps the counter TYPE line with the
            # ``_total`` suffix, so parse_exposition keys the family under
            # the suffixed name; accept the bare name too for merged or
            # hand-written expositions.
            checked_fam = (
                state.families.get("kvtpu_index_divergence_checked_total")
                or state.families.get("kvtpu_index_divergence_checked"))
            if checked_fam is None:
                continue
            divergent_fam = (
                state.families.get("kvtpu_index_divergence_divergent_total")
                or state.families.get("kvtpu_index_divergence_divergent"))
            div_by_pod: Dict[str, float] = {}
            if divergent_fam is not None:
                for (_suffix, labels), value in divergent_fam.samples.items():
                    div_by_pod[dict(labels).get("pod", "")] = value
            for (_suffix, labels), checked in checked_fam.samples.items():
                pod = dict(labels).get("pod", "")
                divergent = div_by_pod.get(pod, 0.0)
                key = f"{state.target.name}:divergence:{pod}"
                prev_checked, prev_div = state.last_hist_counts.get(
                    key, (0.0, 0.0))
                if checked < prev_checked:  # target restarted
                    prev_checked, prev_div = 0.0, 0.0
                d_checked = checked - prev_checked
                d_div = min(divergent - prev_div, d_checked)
                state.last_hist_counts[key] = (checked, divergent)
                if d_checked > 0:
                    tracker.record(
                        good=int(round(d_checked - d_div)),
                        bad=int(round(max(d_div, 0.0))),
                    )

    def _counter_sum(self, state: _TargetState, family: str,
                     label_filter: Optional[Tuple[str, str]] = None) -> Optional[float]:
        """Summed cumulative value of a counter family (both the bare and
        prometheus_client's ``_total``-suffixed TYPE name are accepted),
        optionally restricted to samples carrying ``label_filter``."""
        fam = (state.families.get(f"{family}_total")
               or state.families.get(family))
        if fam is None:
            return None
        total = 0.0
        for (_suffix, labels), value in fam.samples.items():
            if label_filter is not None \
                    and dict(labels).get(label_filter[0]) != label_filter[1]:
                continue
            total += value
        return total

    def _counter_delta(self, state: _TargetState, key: str,
                       total: Optional[float]) -> float:
        """Per-round positive delta of a cumulative counter; a backward
        step (pod restart) resets the baseline instead of going negative."""
        if total is None:
            return 0.0
        prev = state.last_counters.get(key, 0.0)
        if total < prev:
            prev = 0.0
        state.last_counters[key] = total
        return total - prev

    def _anomaly_samples(self, state: _TargetState) -> Dict[str, float]:
        """This round's per-target sentinel inputs, from the scraped
        exposition: gauges read directly, counters as per-round deltas,
        the restore histogram as the delta mean."""
        out: Dict[str, float] = {}
        # ingest lag: worst per-pod event lag gauge (absent family -> 0).
        lag = 0.0
        fam = (state.families.get("kvcache_event_pod_lag_seconds")
               or state.families.get("kvcache_index_staleness_seconds"))
        if fam is not None:
            for _key, value in fam.samples.items():
                lag = max(lag, value)
        out["ingest_lag"] = lag
        # restore latency: delta mean of the restore histogram.
        restore = 0.0
        fam = state.families.get("kvtpu_offload_restore_seconds")
        if fam is not None:
            count = sum(v for (s, _l), v in fam.samples.items()
                        if s == "_count")
            total = sum(v for (s, _l), v in fam.samples.items()
                        if s == "_sum")
            d_count = self._counter_delta(
                state, "anomaly:restore_count", count)
            d_sum = self._counter_delta(state, "anomaly:restore_sum", total)
            restore = d_sum / d_count if d_count > 0 else 0.0
        out["restore_latency"] = restore
        out["hedge_spend"] = self._counter_delta(
            state, "anomaly:hedge",
            self._counter_sum(state, "kvtpu_hedge_attempts",
                              ("outcome", "issued")))
        out["fence_rejections"] = self._counter_delta(
            state, "anomaly:fence",
            self._counter_sum(state, "kvtpu_fence_rejections"))
        out["shed_rate"] = self._counter_delta(
            state, "anomaly:shed",
            self._counter_sum(state, "kvtpu_shed_decisions",
                              ("outcome", "shed")))
        return out

    def _feed_anomaly_slis(self) -> None:
        """Per-round sentinel feeding: compute each target's SLI samples,
        stash them in the target's bounded history (incident-bundle
        evidence), and feed the fleet aggregate — worst pod for the
        latency-shaped series, fleet sum for the rate-shaped ones — to
        the sentinel registry."""
        fleet: Dict[str, float] = {}
        for state in self._targets:
            if not state.families:
                continue
            samples = self._anomaly_samples(state)
            for name, value in samples.items():
                history = state.sli_history.get(name)
                if history is None:
                    history = state.sli_history[name] = deque(
                        maxlen=max(2, self.cfg.anomaly_window))
                history.append(round(value, 6))
                if name in ("ingest_lag", "restore_latency"):
                    fleet[name] = max(fleet.get(name, 0.0), value)
                else:
                    fleet[name] = fleet.get(name, 0.0) + value
        for name, value in fleet.items():
            self.anomalies.observe(name, value)

    def _check_incident_triggers(self) -> None:
        """Open an incident for every *new* alert/anomaly fire edge.

        Both edge streams are consumed through private cursors (the same
        payloads /debug/slo?since= pullers see), so each fire triggers at
        most one capture attempt; the manager's per-trigger cooldown
        absorbs flapping alerts from there.
        """
        slo_edges = self.slos.export_edges_since(self._slo_edge_cursor)
        self._slo_edge_cursor = int(
            slo_edges.get("next_seq", self._slo_edge_cursor))
        anomaly_edges = self.anomalies.export_edges_since(
            self._anomaly_edge_cursor)
        self._anomaly_edge_cursor = int(
            anomaly_edges.get("next_seq", self._anomaly_edge_cursor))
        for edge in slo_edges.get("edges") or ():
            if edge.get("edge") == "fire":
                self.incidents.maybe_open(
                    f"slo:{edge.get('slo', '?')}", reason=dict(edge))
        for edge in anomaly_edges.get("edges") or ():
            if edge.get("edge") == "fire":
                self.incidents.maybe_open(
                    f"anomaly:{edge.get('sentinel', '?')}",
                    reason=dict(edge))

    def incident_evidence(self) -> dict:
        """Collector-side evidence embedded in every incident bundle."""
        return {
            "slo": self.slos.debug_view(),
            "anomalies": self.anomalies.debug_view(),
            "sli_history": {
                s.target.name: {
                    name: list(series)
                    for name, series in s.sli_history.items()
                }
                for s in self._targets
            },
            "traces": self.assembler.debug_view(),
            "targets": {
                s.target.name: {
                    "address": s.target.address,
                    "role": s.target.role,
                    "reachable": s.reachable,
                    "breaker": s.breaker.state,
                }
                for s in self._targets
            },
            "rounds": self.rounds,
        }

    # -- rounds ------------------------------------------------------------

    def scrape_once(self) -> dict:
        """One full collection round (also the unit-test entry point)."""
        with self._tracer.span(
            "llm_d.kv_cache.collector.scrape_round",
            targets=len(self._targets),
        ) as span:
            reachable = 0
            for state in self._targets:
                state.reachable = self._scrape_target(state)
                reachable += int(state.reachable)
            FLEET_TARGETS_REACHABLE.set(reachable)
            span.set_attribute("reachable", reachable)
            availability = self.slos.get("availability")
            if availability is not None and self._targets:
                availability.record(
                    good=reachable, bad=len(self._targets) - reachable)
            self._feed_latency_slis()
            self._feed_divergence_sli()
            if self.cfg.anomaly_enabled:
                self._feed_anomaly_slis()
            finalized = self.assembler.finalize_idle()
            slo_state = self.slos.evaluate_all()
            # Incident triggers ride *after* evaluate_all so a burn-rate
            # edge minted this round is captured this round, not next.
            self._check_incident_triggers()
            self.rounds += 1
            return {
                "reachable": reachable,
                "targets": len(self._targets),
                "finalized_traces": len(finalized),
                "slo": slo_state,
            }

    # -- read surface ------------------------------------------------------

    def rollup_view(self) -> dict:
        """Fleet percentiles per role (and overall) for the key families."""
        by_role: Dict[str, List[Dict[str, MetricFamily]]] = {"all": []}
        for state in self._targets:
            if not state.families:
                continue
            by_role["all"].append(state.families)
            if state.target.role:
                by_role.setdefault(state.target.role, []).append(state.families)
        out: dict = {}
        conflicts: List[str] = []
        for role, expositions in by_role.items():
            merged = merge_families(expositions, conflicts=conflicts)
            out[role] = {
                fam: rollup_percentiles(merged, fam)
                for fam in _ROLLUP_FAMILIES
                if rollup_percentiles(merged, fam)
            }
        for name in conflicts:
            if name not in self._warned_type_conflicts:
                self._warned_type_conflicts.add(name)
                FLEET_TYPE_CONFLICTS.inc()
                logger.warning(
                    "metric family %s skipped: pods disagree on its TYPE "
                    "line (version skew?)", name)
        if conflicts:
            out["type_conflicts"] = sorted(set(conflicts))
        out["targets"] = {
            s.target.name: {
                "address": s.target.address,
                "role": s.target.role,
                "reachable": s.reachable,
                "breaker": s.breaker.state,
                "span_cursor": s.span_cursor,
            }
            for s in self._targets
        }
        return out

    def profile_view(self) -> dict:
        """Fleet-merged continuous profile + critical-path attribution.

        Merges every pulled ``/debug/pyprof`` window into one folded
        profile, derives per-span leaf-function shares, and joins them
        against the retained traces' critical paths so each trace answers
        *dominant segment × dominant function* ("score fan-out: 41% in
        msgpack decode"). ``folded`` is ready for ``flamegraph.pl``.
        """
        with self._profile_lock:
            windows = list(self._profile_windows)
        merged = merge_folded([w.get("folded", "") for w in windows])
        spans = span_function_shares(merged)
        attribution = []
        for summary in self.assembler.retained():
            path = summary.get("critical_path") or []
            if not path:
                continue
            dominant = max(path, key=lambda seg: seg["self_time_s"])
            entry = {
                "trace_id": summary["trace_id"],
                "segment": dominant["name"],
                "process": dominant["process"],
                "self_time_s": dominant["self_time_s"],
                "dominant_function": "",
                "function_share": 0.0,
            }
            prof = spans.get(dominant["name"])
            if prof and prof["functions"]:
                fn, share = next(iter(prof["functions"].items()))
                entry["dominant_function"] = fn
                entry["function_share"] = share
            attribution.append(entry)
        return {
            "windows": len(windows),
            "targets": sorted({w.get("target", "") for w in windows} - {""}),
            "samples": sum(int(w.get("samples", 0)) for w in windows),
            "spans": spans,
            "attribution": attribution,
            "folded": "\n".join(
                f"{stack} {count}"
                for stack, count in sorted(merged.items())),
        }

    def workingset_view(self) -> dict:
        """Fleet-merged working-set analytics + the what-if table.

        Merges every pulled ``/debug/workingset`` window sample-weighted
        (``telemetry.workingset.merge_workingset_windows``) and evaluates
        the fleet MRC at ``whatif_factors`` multiples of the summed HBM
        capacity — the numbers ``kvdiag --fleet`` prints: "hit ratio at
        0.5x/1x/2x/4x current HBM", the never-read offload fraction, and
        the cross-pod duplicate share.
        """
        with self._profile_lock:
            windows = list(self._workingset_windows)
        merged = merge_workingset_windows(windows)
        merged["windows"] = len(windows)
        merged["targets"] = sorted(
            {w.get("target", "") for w in windows} - {""})
        merged["whatif"] = whatif_table(
            merged, factors=self.cfg.whatif_factors)
        # Measured (not modeled) hit ratios per scope, for sanity checks
        # against the MRC estimate at 1.0x.
        for st in merged["scopes"].values():
            st["measured_hit_ratio"] = (
                round(st["hits"] / st["accesses"], 4)
                if st.get("accesses") else 0.0)
        return merged

    def audit_view(self) -> dict:
        """Score-vs-reality audit: the joiner's calibration/regret state
        plus the fleet's current divergence picture (phantom/ghost block
        gauges per pod, straight from the targets' last expositions).

        This is what the collector serves at ``/debug/audit`` (the pods'
        same-named endpoint serves the raw record ring instead) and what
        ``kvdiag --fleet`` prints as the audit section.
        """
        out = self.joiner.view()
        divergence: Dict[str, dict] = {}
        for state in self._targets:
            for fam_name, field_name in (
                    ("kvtpu_index_divergence_phantom_blocks", "phantom"),
                    ("kvtpu_index_divergence_ghost_blocks", "ghost")):
                fam = state.families.get(fam_name)
                if fam is None:
                    continue
                for (_suffix, labels), value in fam.samples.items():
                    pod = dict(labels).get("pod", "")
                    entry = divergence.setdefault(
                        pod, {"phantom": 0.0, "ghost": 0.0})
                    entry[field_name] = max(entry[field_name], value)
        out["divergence"] = {
            pod: entry for pod, entry in sorted(divergence.items())
            if entry["phantom"] > 0 or entry["ghost"] > 0
        }
        out["divergence_pods_checked"] = len(divergence)
        return out

    def debug_view(self) -> dict:
        pyprof = self.profile_view()
        pyprof.pop("folded", None)  # bulk text lives at /debug/pyprof
        return {
            "rounds": self.rounds,
            "traces": self.assembler.debug_view(),
            "slo": self.slos.debug_view(),
            "anomaly": self.anomalies.debug_view(),
            "incident": self.incidents.debug_view(),
            "rollup": self.rollup_view(),
            "pyprof": pyprof,
            "workingset": self.workingset_view(),
            "audit": self.audit_view(),
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start the periodic scrape loop and (optionally) the admin port."""
        if self.cfg.admin_port > 0 and self._admin is None:
            self._admin = AdminServer(
                port=self.cfg.admin_port, host=self.cfg.host,
                expose_debug=True)
            self._admin.register_debug(
                "traces", self.assembler.debug_view)
            self._admin.register_debug("slo", self.slos.debug_view)
            # /debug/slo?since= additionally serves the alert edge
            # history (cursor semantics of /debug/spans); plain GETs keep
            # the level-state provider above.
            self._admin.register_slo_source(self.slos.export_edges_since)
            self._admin.register_debug("rollup", self.rollup_view)
            self._admin.register_debug("fleet", self.debug_view)
            self._admin.register_debug("pyprof", self.profile_view)
            self._admin.register_debug("workingset", self.workingset_view)
            # The collector's /debug/audit serves the *joined* view (the
            # pods' same-named endpoint serves their raw record rings —
            # AdminServer routes plain GETs to this provider and ?since=
            # pulls to a registered cursor source).
            self._admin.register_debug("audit", self.audit_view)
            self._admin.register_debug(
                "anomaly", self.anomalies.debug_view)
            self._admin.register_debug(
                "incident", self.incidents.debug_view)
            # POST /debug/incident/open — the manual black-box pull.
            # Captures inline so the response carries the bundle path;
            # ?force=1 bypasses the trigger cooldown, ?trigger=<name>
            # labels the bundle.
            self._admin.register_action(
                "incident/open", self._incident_open_action)
            self._admin.start()
        if self._thread is None and self.cfg.scrape_interval_s > 0:
            self._stop.clear()

            def loop() -> None:
                while not self._stop.wait(self.cfg.scrape_interval_s):
                    try:
                        self.scrape_once()
                    except Exception:  # the loop must survive bad rounds
                        logger.exception("collector round failed")

            self._thread = threading.Thread(
                target=loop, name="kvtpu-telemetry-collector", daemon=True)
            self._thread.start()

    def _incident_open_action(self, params) -> dict:
        trigger = str(params.get("trigger") or "manual")
        force = str(params.get("force", "")).lower() in ("1", "true", "yes")
        summary = self.incidents.maybe_open(
            f"manual:{trigger}" if not trigger.startswith("manual") else trigger,
            reason={"source": "admin", "params": dict(params)},
            force=force,
            synchronous=True,
        )
        if summary is None:
            raise ValueError(
                "incident suppressed (cooldown, capture in flight, or "
                "incident.directory unset); retry with force=1 or "
                "configure incidentConfig")
        return summary

    @property
    def admin_port(self) -> int:
        return self._admin.port if self._admin is not None else 0

    def stop(self) -> None:
        self.incidents.wait(timeout=5.0)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._admin is not None:
            self._admin.stop()
            self._admin = None
