"""Protobuf wire surface for the indexer service.

``indexer_pb2`` is generated (``hack/gen_protos.sh``) from
``api/indexerpb/indexer.proto``, which is carried verbatim from the
reference (``api/indexerpb/indexer.proto:24-43``) because the wire
contract must be byte-compatible with llm-d's Go EPP client.
"""

from . import indexer_pb2

__all__ = ["indexer_pb2"]
