"""Stdlib HTTP admin/debug endpoint: /metrics, /healthz, /debug/*.

The indexer sidecar's "open the pod and look" surface (ISSUE 3). Serves:

- ``/metrics``   — the process's Prometheus registry (text exposition)
- ``/healthz``   — liveness probe (200 + ``{"status": "ok"}``)
- ``/debug/flight-recorder`` — the in-process flight recorder ring
  (full dump; ``?since=SEQ`` switches to the cursor export used by
  ``/debug/spans`` — records newer than the puller's cursor plus
  ``next_seq``/``dropped`` — which is what the incident capture and the
  collector pull)
- ``/debug/time`` — wall + monotonic clock echo (always on with the
  debug surface): the telemetry collector brackets it between two local
  clock readings to estimate this pod's clock offset by RTT-halving
  (``telemetry/incident.py``), which is how incident bundles merge
  per-pod timelines despite skewed clocks
- ``/debug/<name>``          — registered JSON providers (``lag``,
  ``ledger``, ``engine``, …), whatever the owning service wires in
- ``/debug/vars``            — every provider + the flight recorder in
  one JSON document (what ``hack/kvdiag.py`` snapshots)
- ``/debug/profile?duration_s=N`` — on-demand ``jax.profiler`` capture
  (guarded: 404 unless the owner registered a capture callable via
  :meth:`AdminServer.register_profiler`; one capture at a time → 409)
- ``/debug/spans?since=SEQ`` — finished spans from the process's ring
  exporter, newer than the puller's cursor (404 until the owner calls
  :meth:`AdminServer.register_spans_source`). The fleet telemetry
  collector polls this to assemble cross-process traces.
- ``/debug/pyprof?since=SEQ`` — sealed folded-stack windows from the
  always-on sampling profiler, same cursor semantics as
  ``/debug/spans`` (404 until :meth:`AdminServer.register_pyprof_source`
  is called). The collector merges these fleet-wide.
- ``/debug/workingset?since=SEQ`` — sealed working-set/reuse windows
  from the process's tracker (telemetry/workingset.py), same cursor
  semantics (404 until :meth:`AdminServer.register_workingset_source`
  registers a source).
- ``/debug/pyprof/capture?seconds=N`` — on-demand burst capture on the
  sampling profiler, next to the jax ``/debug/profile`` endpoint (one at
  a time → 409; 404 until :meth:`AdminServer.register_pyprof_capture`).
- ``/debug/slo?since=SEQ`` — SLO alert fire/clear **edge history** from
  the registry, same cursor semantics as ``/debug/spans`` (404 until
  :meth:`AdminServer.register_slo_source`; without ``since`` it falls
  through to a plain registered ``slo`` level-state provider). The fleet
  controller consumes this to react to each alert transition once.
- ``/debug/audit?since=SEQ`` — ground-truth audit records (score-time
  predictions, engine-realized outcomes) from the process's
  ``telemetry.audit.AuditLog`` ring, same cursor semantics as
  ``/debug/spans`` (404 until :meth:`AdminServer.register_audit_source`;
  without ``since`` it falls through to a plain registered ``audit``
  provider — the collector's joined calibration/regret view). The fleet
  telemetry collector pulls this to join predictions to outcomes.
- ``POST /debug/<name>`` — guarded mutation endpoints (e.g. ``role``,
  ``drain``): 404 until the owner registers a handler via
  :meth:`AdminServer.register_action`; parameters ride the query string.

``/metrics?format=openmetrics`` switches the exposition to OpenMetrics,
the only text format that renders exemplars (trace-id links on
``BucketHistogram`` buckets).

Deliberately stdlib-only (``http.server``): the endpoint must work in the
most degraded pod imaginable — that is exactly when it is needed. Disabled
by default; the config knobs are ``metricsPort`` (metrics+health only) and
``adminPort`` (adds ``/debug/*``), both 0 = off. Binds localhost by
default: the debug surface exposes pod names and score internals, so
exposure beyond the pod is an operator decision (``host="0.0.0.0"``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Mapping, Optional
from urllib.parse import parse_qs

from ..telemetry import flight_recorder
from ..utils.logging import get_logger

logger = get_logger("services.admin")


class AdminServer:
    """Small threaded HTTP server for observability endpoints.

    ``port=0`` binds an ephemeral port (tests); the *disabled-by-default*
    semantics of the ``metricsPort``/``adminPort`` config knobs live in the
    wiring (IndexerService skips construction when the knob is 0).
    """

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        expose_debug: bool = True,
        health: Optional[Callable[[], dict]] = None,
    ):
        self._host = host
        self._requested_port = port
        self._expose_debug = expose_debug
        self._providers: dict[str, Callable[[], object]] = {}
        self._health = health
        self._profiler: Optional[Callable[[float], dict]] = None
        self._spans_source: Optional[Callable[[int], dict]] = None
        self._pyprof_source: Optional[Callable[[int], dict]] = None
        self._pyprof_capture: Optional[Callable[[float], dict]] = None
        self._workingset_source: Optional[Callable[[int], dict]] = None
        self._slo_source: Optional[Callable[[int], dict]] = None
        self._audit_source: Optional[Callable[[int], dict]] = None
        self._actions: dict[str, Callable[[Mapping[str, str]], dict]] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def register_debug(self, name: str, provider: Callable[[], object]) -> None:
        """Expose ``provider()`` (a JSON-serializable callable) as
        ``/debug/<name>`` and inside ``/debug/vars``."""
        self._providers[name] = provider

    def register_profiler(self, capture: Callable[[float], dict]) -> None:
        """Enable ``/debug/profile``: ``capture(duration_s)`` runs a
        blocking profiler capture and returns a JSON-serializable summary
        (``telemetry.engine_telemetry.ProfilerCapture.capture``). The
        endpoint stays 404 until this is called — an unconfigured pod must
        not let arbitrary HTTP clients spin up the profiler."""
        self._profiler = capture

    def register_spans_source(self, source: Callable[[int], dict]) -> None:
        """Enable ``/debug/spans``: ``source(since_seq)`` returns the
        ring exporter's ``export_since`` payload (spans + cursor + drops).
        Typically ``InMemorySpanExporter.export_since``. 404 until set —
        span export is opt-in per pod (``fleetTelemetry.spanExport``)."""
        self._spans_source = source

    def register_pyprof_source(self, source: Callable[[int], dict]) -> None:
        """Enable ``/debug/pyprof``: ``source(since_seq)`` returns the
        sampling profiler's ``export_since`` payload (sealed folded-stack
        windows + cursor + drops). 404 until set — continuous profiling
        is opt-in per pod (``fleetTelemetry.pyprof``)."""
        self._pyprof_source = source

    def register_workingset_source(
            self, source: Callable[[int], dict]) -> None:
        """Enable ``/debug/workingset``: ``source(since_seq)`` returns the
        working-set tracker's sealed reuse windows with the same cursor
        semantics as ``/debug/spans`` / ``/debug/pyprof``. 404 until
        registered — workingset is opt-in per pod
        (``fleetTelemetry.workingset``)."""
        self._workingset_source = source

    def register_slo_source(self, source: Callable[[int], dict]) -> None:
        """Enable ``/debug/slo?since=``: ``source(since_seq)`` returns the
        SLO registry's ``export_edges_since`` payload (alert fire/clear
        edges + cursor + drops), same cursor semantics as
        ``/debug/spans``. Without a query string the endpoint still falls
        through to a registered plain ``slo`` provider (level state), so
        existing consumers keep working."""
        self._slo_source = source

    def register_audit_source(self, source: Callable[[int], dict]) -> None:
        """Enable ``/debug/audit?since=``: ``source(since_seq)`` returns the
        audit ring's ``export_since`` payload (prediction/outcome records
        + cursor + drops), same cursor semantics as ``/debug/spans``.
        Typically ``telemetry.audit.AuditLog.export_since``. 404 until
        set — the audit plane is opt-in per pod
        (``fleetTelemetry.audit``). Without ``since`` the endpoint falls
        through to a plain registered ``audit`` provider (the collector's
        joined calibration view), mirroring ``/debug/slo``."""
        self._audit_source = source

    def register_action(
            self, name: str,
            handler: Callable[[Mapping[str, str]], dict]) -> None:
        """Enable ``POST /debug/<name>``: ``handler(params)`` receives the
        flattened query parameters and returns a JSON-serializable result.
        POST endpoints are guarded the same way as the profiler: 404 until
        the owning service explicitly registers a handler, so an
        unconfigured pod cannot be mutated over HTTP. ``ValueError`` from
        the handler maps to 400 (bad request), anything else to 500."""
        self._actions[name] = handler

    def register_pyprof_capture(self, capture: Callable[[float], dict]) -> None:
        """Enable ``/debug/pyprof/capture``: ``capture(seconds)`` runs a
        blocking burst capture on the sampling profiler and returns the
        folded profile. 404 until set."""
        self._pyprof_capture = capture

    def set_health_provider(self, provider: Callable[[], dict]) -> None:
        """Make ``/healthz`` report ``provider()`` instead of the static
        ok. A payload whose ``status`` is not ``"ok"`` is served with 503
        so readiness probes gate traffic (e.g. ``warming`` after a warm
        restart, recovery.manager)."""
        self._health = provider

    @property
    def port(self) -> int:
        """The bound port (0 until started)."""
        return self._httpd.server_port if self._httpd is not None else 0

    # -- request handling --------------------------------------------------

    def _metrics_payload(self, fmt: str = "") -> tuple[bytes, str]:
        if fmt == "openmetrics":
            from prometheus_client import REGISTRY
            from prometheus_client.openmetrics.exposition import (
                CONTENT_TYPE_LATEST as OPENMETRICS_CONTENT_TYPE,
                generate_latest as generate_openmetrics,
            )

            return generate_openmetrics(REGISTRY), OPENMETRICS_CONTENT_TYPE
        from prometheus_client import CONTENT_TYPE_LATEST, generate_latest

        return generate_latest(), CONTENT_TYPE_LATEST

    def _handle_spans(self, query: Mapping[str, list]) -> tuple[int, bytes, str]:
        if self._spans_source is None:
            return (404, b'{"error": "span export not configured"}',
                    "application/json")
        raw = query.get("since", ["-1"])[-1]
        try:
            since = int(raw)
        except ValueError:
            return (400, json.dumps(
                {"error": f"bad since: {raw!r}"}).encode(), "application/json")
        try:
            payload = self._spans_source(since)
        except Exception as exc:
            return 500, json.dumps({"error": str(exc)}).encode(), "application/json"
        return (200, json.dumps(payload, default=repr).encode(),
                "application/json")

    def _handle_pyprof(self, query: Mapping[str, list]) -> tuple[int, bytes, str]:
        if self._pyprof_source is None:
            return (404, b'{"error": "sampling profiler not configured"}',
                    "application/json")
        raw = query.get("since", ["-1"])[-1]
        try:
            since = int(raw)
        except ValueError:
            return (400, json.dumps(
                {"error": f"bad since: {raw!r}"}).encode(), "application/json")
        try:
            payload = self._pyprof_source(since)
        except Exception as exc:
            return 500, json.dumps({"error": str(exc)}).encode(), "application/json"
        return (200, json.dumps(payload, default=repr).encode(),
                "application/json")

    def _handle_workingset(
            self, query: Mapping[str, list]) -> tuple[int, bytes, str]:
        if self._workingset_source is None:
            return (404, b'{"error": "workingset tracking not configured"}',
                    "application/json")
        raw = query.get("since", ["-1"])[-1]
        try:
            since = int(raw)
        except ValueError:
            return (400, json.dumps(
                {"error": f"bad since: {raw!r}"}).encode(), "application/json")
        try:
            payload = self._workingset_source(since)
        except Exception as exc:
            return 500, json.dumps({"error": str(exc)}).encode(), "application/json"
        return (200, json.dumps(payload, default=repr).encode(),
                "application/json")

    def _handle_slo(self, query: Mapping[str, list]) -> tuple[int, bytes, str]:
        if self._slo_source is None:
            return (404, b'{"error": "slo edge export not configured"}',
                    "application/json")
        raw = query.get("since", ["-1"])[-1]
        try:
            since = int(raw)
        except ValueError:
            return (400, json.dumps(
                {"error": f"bad since: {raw!r}"}).encode(), "application/json")
        try:
            payload = self._slo_source(since)
        except Exception as exc:
            return 500, json.dumps({"error": str(exc)}).encode(), "application/json"
        return (200, json.dumps(payload, default=repr).encode(),
                "application/json")

    def _handle_audit(self, query: Mapping[str, list]) -> tuple[int, bytes, str]:
        if self._audit_source is None:
            return (404, b'{"error": "audit export not configured"}',
                    "application/json")
        raw = query.get("since", ["-1"])[-1]
        try:
            since = int(raw)
        except ValueError:
            return (400, json.dumps(
                {"error": f"bad since: {raw!r}"}).encode(), "application/json")
        try:
            payload = self._audit_source(since)
        except Exception as exc:
            return 500, json.dumps({"error": str(exc)}).encode(), "application/json"
        return (200, json.dumps(payload, default=repr).encode(),
                "application/json")

    def _handle_pyprof_capture(
            self, query: Mapping[str, list]) -> tuple[int, bytes, str]:
        if self._pyprof_capture is None:
            return (404, b'{"error": "sampling profiler not configured"}',
                    "application/json")
        raw = query.get("seconds", ["1.0"])[-1]
        try:
            seconds = float(raw)
        except ValueError:
            return (400, json.dumps(
                {"error": f"bad seconds: {raw!r}"}).encode(),
                "application/json")
        try:
            summary = self._pyprof_capture(seconds)
        except ValueError as exc:
            return 400, json.dumps({"error": str(exc)}).encode(), "application/json"
        except Exception as exc:
            # CaptureInProgress (a RuntimeError subclass) → 409, matching
            # the jax profiler endpoint; anything else → 500.
            from ..telemetry.sampling_profiler import CaptureInProgress

            status = 409 if isinstance(exc, CaptureInProgress) else 500
            return status, json.dumps({"error": str(exc)}).encode(), "application/json"
        return (200, json.dumps(summary, indent=2, default=repr).encode(),
                "application/json")

    def _debug_vars(self) -> dict:
        payload: dict = {
            "flight_recorder": flight_recorder().snapshot(),
        }
        for name, provider in self._providers.items():
            try:
                payload[name] = provider()
            except Exception as exc:
                payload[name] = {"error": str(exc)}
        return payload

    def _handle_profile(self, query: Mapping[str, list]) -> tuple[int, bytes, str]:
        if self._profiler is None:
            return (404, b'{"error": "profiler not configured"}',
                    "application/json")
        raw = query.get("duration_s", ["1.0"])[-1]
        try:
            duration_s = float(raw)
        except ValueError:
            return (400, json.dumps(
                {"error": f"bad duration_s: {raw!r}"}).encode(),
                "application/json")
        try:
            summary = self._profiler(duration_s)
        except ValueError as exc:
            return 400, json.dumps({"error": str(exc)}).encode(), "application/json"
        except Exception as exc:
            # ProfileInProgress (a RuntimeError subclass) → 409; any other
            # capture failure (unsupported platform, profiler error) → 500.
            from ..telemetry.engine_telemetry import ProfileInProgress

            status = 409 if isinstance(exc, ProfileInProgress) else 500
            return status, json.dumps({"error": str(exc)}).encode(), "application/json"
        return (200, json.dumps(summary, indent=2, default=repr).encode(),
                "application/json")

    def _handle(self, path: str,
                query: Optional[Mapping[str, list]] = None) -> tuple[int, bytes, str]:
        """Route one GET; returns (status, body, content_type)."""
        if path == "/healthz":
            if self._health is None:
                return 200, b'{"status": "ok"}', "application/json"
            try:
                payload = self._health()
            except Exception as exc:  # health must answer even when broken
                return (
                    500,
                    json.dumps({"status": "error", "error": str(exc)}).encode(),
                    "application/json",
                )
            status = 200 if payload.get("status") == "ok" else 503
            return status, json.dumps(payload, default=repr).encode(), "application/json"
        if path == "/metrics":
            fmt = (query or {}).get("format", [""])[-1]
            body, ctype = self._metrics_payload(fmt)
            return 200, body, ctype
        if self._expose_debug:
            if path == "/debug/profile":
                return self._handle_profile(query or {})
            if path == "/debug/spans":
                return self._handle_spans(query or {})
            # No local sampler but a registered "pyprof" provider (the
            # collector's fleet-merged view): fall through to the generic
            # /debug/<name> dispatch below instead of 404ing.
            if path == "/debug/pyprof" and (
                    self._pyprof_source is not None
                    or "pyprof" not in self._providers):
                return self._handle_pyprof(query or {})
            if path == "/debug/pyprof/capture":
                return self._handle_pyprof_capture(query or {})
            # Same provider fall-through as pyprof: the collector exposes
            # its *merged* fleet view as a "workingset" debug provider.
            if path == "/debug/workingset" and (
                    self._workingset_source is not None
                    or "workingset" not in self._providers):
                return self._handle_workingset(query or {})
            # /debug/slo serves two shapes: with ?since= (or with no plain
            # "slo" provider) the edge-history cursor payload; otherwise it
            # falls through to the registered level-state provider, so
            # pre-cursor consumers keep working.
            if path == "/debug/slo" and self._slo_source is not None and (
                    "since" in (query or {}) or "slo" not in self._providers):
                return self._handle_slo(query or {})
            # Same dual shape as /debug/slo: with ?since= (or no plain
            # "audit" provider) the cursor record export answers; else the
            # registered provider (the collector's joined view) does. An
            # unconfigured pod 404s either way (collector pulls tolerate).
            if path == "/debug/audit" and (
                    self._audit_source is not None
                    and ("since" in (query or {})
                         or "audit" not in self._providers)
                    or self._audit_source is None
                    and "audit" not in self._providers):
                return self._handle_audit(query or {})
            if path == "/debug/time":
                # Deliberately unguarded (no registration): the echo
                # carries no pod internals and must answer even on a pod
                # nothing else was wired on — skew estimation is most
                # valuable exactly when a pod is misbehaving.
                body = json.dumps({
                    "wall": time.time(),
                    "mono": time.monotonic(),
                    "pid": os.getpid(),
                }).encode("utf-8")
                return 200, body, "application/json"
            if path == "/debug/flight-recorder":
                if "since" in (query or {}):
                    raw = (query or {}).get("since", ["-1"])[-1]
                    try:
                        since = int(raw)
                    except ValueError:
                        return (400, json.dumps(
                            {"error": f"bad since: {raw!r}"}).encode(),
                            "application/json")
                    payload = flight_recorder().export_since(since)
                    return (200, json.dumps(payload, default=repr).encode(),
                            "application/json")
                body = flight_recorder().dump_json(indent=2).encode("utf-8")
                return 200, body, "application/json"
            if path == "/debug/vars":
                body = json.dumps(self._debug_vars(), indent=2, default=repr)
                return 200, body.encode("utf-8"), "application/json"
            if path.startswith("/debug/"):
                name = path[len("/debug/"):]
                provider = self._providers.get(name)
                if provider is not None:
                    try:
                        body = json.dumps(provider(), indent=2, default=repr)
                    except Exception as exc:
                        return 500, json.dumps({"error": str(exc)}).encode(), "application/json"
                    return 200, body.encode("utf-8"), "application/json"
        return 404, b'{"error": "not found"}', "application/json"

    def _handle_post(self, path: str,
                     query: Optional[Mapping[str, list]] = None) -> tuple[int, bytes, str]:
        """Route one POST; only registered /debug/<name> actions exist."""
        if self._expose_debug and path.startswith("/debug/"):
            handler = self._actions.get(path[len("/debug/"):])
            if handler is not None:
                params = {k: v[-1] for k, v in (query or {}).items()}
                try:
                    payload = handler(params)
                except ValueError as exc:
                    return (400, json.dumps({"error": str(exc)}).encode(),
                            "application/json")
                except Exception as exc:
                    return (500, json.dumps({"error": str(exc)}).encode(),
                            "application/json")
                return (200, json.dumps(payload, default=repr).encode(),
                        "application/json")
        return 404, b'{"error": "not found"}', "application/json"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        """Bind + serve in a daemon thread; returns the bound port."""
        if self._httpd is not None:
            return self.port
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                try:
                    path, _, raw_query = self.path.partition("?")
                    status, body, ctype = outer._handle(
                        path, parse_qs(raw_query))
                except Exception as exc:  # a broken provider must not kill the server
                    status = 500
                    body = json.dumps({"error": str(exc)}).encode("utf-8")
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler API
                try:
                    # Drain any request body so keep-alive stays coherent;
                    # action parameters travel in the query string.
                    length = int(self.headers.get("Content-Length") or 0)
                    if length > 0:
                        self.rfile.read(length)
                    path, _, raw_query = self.path.partition("?")
                    status, body, ctype = outer._handle_post(
                        path, parse_qs(raw_query))
                except Exception as exc:  # a broken handler must not kill the server
                    status = 500
                    body = json.dumps({"error": str(exc)}).encode("utf-8")
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # route to our logger, DEBUG
                logger.debug("admin http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self._host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"kvtpu-admin-{self.port}",
            daemon=True,
        )
        self._thread.start()
        logger.info(
            "admin endpoint on http://%s:%d (debug=%s)",
            self._host, self.port, self._expose_debug,
        )
        return self.port

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd = None


def start_observability_servers(
    metrics_port: int,
    admin_port: int,
    host: str = "127.0.0.1",
    providers: Optional[dict[str, Callable[[], object]]] = None,
    health: Optional[Callable[[], dict]] = None,
) -> list[AdminServer]:
    """Start the configured endpoint(s); 0 = disabled (the default).

    When both knobs name the same port (or only ``admin_port`` is set),
    one server does both jobs; distinct ports get a metrics-only server
    plus a full admin server. ``health`` (optional) backs ``/healthz`` on
    every started server — non-ok payloads serve as 503 for readiness
    probes.
    """
    servers: list[AdminServer] = []
    if admin_port > 0:
        admin = AdminServer(port=admin_port, host=host, expose_debug=True,
                            health=health)
        for name, provider in (providers or {}).items():
            admin.register_debug(name, provider)
        admin.start()
        servers.append(admin)
    if metrics_port > 0 and metrics_port != admin_port:
        metrics = AdminServer(port=metrics_port, host=host, expose_debug=False,
                              health=health)
        metrics.start()
        servers.append(metrics)
    return servers
