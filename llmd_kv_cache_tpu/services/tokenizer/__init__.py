"""Tokenizer/renderer sidecar: gRPC over a Unix domain socket.

Counterpart of reference ``services/uds_tokenizer`` + ``pkg/tokenization``:
the indexer needs exact token ids (and multimodal hashes/placeholders) to
content-address prompts the same way the engines do, so tokenization and
chat-template rendering run in a Python sidecar sharing the engines'
tokenizer stack, reached over a local socket.

Wire: gRPC generic handlers with msgpack-encoded messages (the reference
uses protobuf; the RPC surface — Tokenize / InitializeTokenizer /
RenderChatCompletion / RenderCompletion — is the same, and msgpack keeps
this image free of protoc codegen).
"""

from .messages import (
    ChatMessage,
    RenderChatRequest,
    RenderChatResponse,
    TokenizeRequest,
    TokenizeResponse,
)
from .service import TokenizerService, serve_uds
from .client import UdsTokenizerClient

__all__ = [
    "ChatMessage",
    "RenderChatRequest",
    "RenderChatResponse",
    "TokenizeRequest",
    "TokenizeResponse",
    "TokenizerService",
    "serve_uds",
    "UdsTokenizerClient",
]
