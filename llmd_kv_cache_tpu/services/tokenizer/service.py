"""gRPC tokenizer service over a Unix domain socket.

Counterpart of reference ``services/uds_tokenizer`` (asyncio gRPC server on
a unix socket, ``run_grpc_server.py``) and its servicer
(``tokenizer_grpc_service.py``). RPCs are registered through generic
method handlers with msgpack serializers — no codegen.

RPC surface (service ``kvtpu.tokenizer.TokenizationService``):
  InitializeTokenizer  — eager per-model load (clients call once, with
                         retries, before serving traffic)
  Tokenize             — text → token ids (+ byte offsets)
  RenderCompletion     — completion prompt → token ids
  RenderChatCompletion — chat messages (+ tools, template kwargs,
                         multimodal parts) → token ids + MM hashes and
                         placeholder ranges for extra-key computation
"""

from __future__ import annotations

import hashlib
import uuid
from concurrent import futures
from typing import Optional

import grpc

from ...telemetry import tracer
from ...utils.logging import get_logger
from ...utils.net import grpc_target
from .backends import TokenizerRegistry
from .messages import (
    InitializeTokenizerRequest,
    InitializeTokenizerResponse,
    RenderChatRequest,
    RenderChatResponse,
    RenderCompletionRequest,
    TokenizeRequest,
    TokenizeResponse,
)

logger = get_logger("services.tokenizer")

SERVICE_NAME = "kvtpu.tokenizer.TokenizationService"
MAX_MESSAGE_BYTES = 100 * 1024 * 1024  # match reference caps (uds_tokenizer.go:109-122)


class TokenizerService:
    """RPC implementations (transport-independent)."""

    def __init__(self, registry: Optional[TokenizerRegistry] = None):
        self.registry = registry or TokenizerRegistry()

    # -- RPCs --

    def initialize_tokenizer(
        self, req: InitializeTokenizerRequest
    ) -> InitializeTokenizerResponse:
        try:
            self.registry.get(req.model_name)
            return InitializeTokenizerResponse(success=True)
        except Exception as e:
            logger.exception("tokenizer init failed for %s", req.model_name)
            return InitializeTokenizerResponse(success=False, error=str(e))

    def tokenize(self, req: TokenizeRequest) -> TokenizeResponse:
        try:
            tok = self.registry.get(req.model_name)
            if req.return_offsets:
                ids, offsets = tok.encode_with_offsets(
                    req.text, add_special_tokens=req.add_special_tokens
                )
                return TokenizeResponse(token_ids=ids, offsets=offsets)
            ids = tok.encode(req.text, add_special_tokens=req.add_special_tokens)
            return TokenizeResponse(token_ids=ids)
        except Exception as e:
            logger.exception("tokenize failed")
            return TokenizeResponse(error=str(e))

    def render_completion(self, req: RenderCompletionRequest) -> TokenizeResponse:
        return self.tokenize(
            TokenizeRequest(
                model_name=req.model_name,
                text=req.prompt,
                add_special_tokens=req.add_special_tokens,
            )
        )

    def render_chat_completion(self, req: RenderChatRequest) -> RenderChatResponse:
        try:
            tok = self.registry.get(req.model_name)
            messages = []
            for m in req.messages:
                d = {"role": m.role, "content": m.content}
                if m.tool_calls:
                    d["tool_calls"] = m.tool_calls
                messages.append(d)

            # Multimodal parts are replaced by per-item UNIQUE sentinels
            # before template rendering. Uniqueness (uuid per item) makes
            # placeholder location collision-proof: user text can never
            # contain the sentinel, and each occurrence maps 1:1 to its
            # item in document order. Content hashes feed block extra-keys.
            mm_items: list[tuple[str, str, str]] = []  # (sentinel, modality, hash)
            for m in messages:
                if not isinstance(m["content"], list):
                    continue
                new_parts = []
                for part in m["content"]:
                    modality = _part_modality(part) if isinstance(part, dict) else None
                    if modality is None:
                        new_parts.append(part)
                        continue
                    payload = _part_payload(part)
                    identifier = hashlib.sha256(payload).hexdigest()
                    sentinel = f"<|mm-{uuid.uuid4().hex[:12]}|>"
                    mm_items.append((sentinel, modality, identifier))
                    new_parts.append({"type": "text", "text": sentinel})
                m["content"] = new_parts

            rendered = tok.apply_chat_template(
                messages,
                add_generation_prompt=req.add_generation_prompt,
                chat_template=req.chat_template,
                tools=req.tools,
                **req.template_kwargs,
            )

            if not mm_items:
                ids = tok.encode(rendered, add_special_tokens=True)
                return RenderChatResponse(token_ids=ids, rendered_text=rendered)

            # Build the token stream segment-by-segment so every placeholder
            # offset is known exactly (no token-subsequence guessing, which
            # breaks when BPE merges markers with their neighbors): text
            # segments are tokenized independently with the placeholder
            # marker tokens spliced between them.
            ids: list[int] = []
            mm_hashes: dict[str, list[str]] = {}
            mm_placeholders: dict[str, list[tuple[int, int]]] = {}
            rest = rendered
            display_text = rendered
            for sentinel, modality, identifier in mm_items:
                before, sep, rest = rest.partition(sentinel)
                display_text = display_text.replace(sentinel, f"<|{modality}|>", 1)
                if not sep:
                    # Template dropped the part (e.g. text-only template):
                    # no placeholder, and no hash — the item is absent from
                    # the token stream, so it must not taint blocks. Restore
                    # the unconsumed text for the remaining sentinels.
                    rest = before
                    continue
                # Specials (BOS) go on the first *encoded* segment, wherever
                # that falls — templates may drop earlier items.
                seg_ids = tok.encode(before, add_special_tokens=not ids)
                ids.extend(seg_ids)
                marker_ids = tok.encode(f"<|{modality}|>", add_special_tokens=False)
                mm_hashes.setdefault(modality, []).append(identifier)
                mm_placeholders.setdefault(modality, []).append(
                    (len(ids), len(marker_ids))
                )
                ids.extend(marker_ids)
            if rest:
                ids.extend(tok.encode(rest, add_special_tokens=not ids))

            return RenderChatResponse(
                token_ids=ids,
                rendered_text=display_text,
                mm_hashes=mm_hashes,
                mm_placeholders=mm_placeholders,
            )
        except Exception as e:
            logger.exception("render chat failed")
            return RenderChatResponse(error=str(e))


def _part_modality(part: dict) -> Optional[str]:
    t = part.get("type", "")
    if t in ("image", "image_url", "input_image"):
        return "image"
    if t in ("audio", "input_audio"):
        return "audio"
    if t == "video":
        return "video"
    return None


def _part_payload(part: dict) -> bytes:
    for key in ("data", "image_url", "url", "audio", "video"):
        v = part.get(key)
        if isinstance(v, dict):
            v = v.get("url", "")
        if v:
            return str(v).encode("utf-8")
    return repr(sorted(part.items())).encode("utf-8")


def _make_grpc_handler(service: TokenizerService):
    """Register RPCs as generic unary-unary handlers with msgpack codecs."""
    rpcs = {
        "InitializeTokenizer": (
            service.initialize_tokenizer,
            InitializeTokenizerRequest.from_bytes,
            lambda resp: resp.to_bytes(),
        ),
        "Tokenize": (
            service.tokenize,
            TokenizeRequest.from_bytes,
            lambda resp: resp.to_bytes(),
        ),
        "RenderCompletion": (
            service.render_completion,
            RenderCompletionRequest.from_bytes,
            lambda resp: resp.to_bytes(),
        ),
        "RenderChatCompletion": (
            service.render_chat_completion,
            RenderChatRequest.from_bytes,
            lambda resp: resp.to_bytes(),
        ),
    }

    method_handlers = {}
    for name, (fn, deserialize, serialize) in rpcs.items():
        def make(fn=fn, name=name):
            def handler(request, context):
                # Server-side half of the W3C hop: parent this span under
                # the caller's traceparent metadata when present, so one
                # trace covers client call + server work.
                with tracer().span(
                    f"llm_d.kv_cache.tokenizer.{name}",
                    parent_traceparent=extract_traceparent(context),
                ):
                    return fn(request)
            return handler

        method_handlers[name] = grpc.unary_unary_rpc_method_handler(
            make(),
            request_deserializer=deserialize,
            response_serializer=serialize,
        )
    return grpc.method_handlers_generic_handler(SERVICE_NAME, method_handlers)


def extract_traceparent(context) -> Optional[str]:
    """Pull the W3C ``traceparent`` from gRPC invocation metadata (None
    when absent or the context does not expose metadata)."""
    if context is None:
        return None
    try:
        metadata = context.invocation_metadata()
    except Exception:  # pragma: no cover - non-grpc test doubles  # lint: allow-swallow
        return None
    if not metadata:
        return None
    for key, value in metadata:
        if key == "traceparent" and isinstance(value, str):
            return value
    return None


def serve_uds(
    socket_path: str,
    service: Optional[TokenizerService] = None,
    max_workers: int = 8,
) -> grpc.Server:
    """Start the tokenizer gRPC server bound to ``unix:<socket_path>``.

    Returns the started server (caller stops it). Pass a plain filesystem
    path (``unix:`` is prepended) or a full gRPC address like
    ``127.0.0.1:0`` for TCP tests.
    """
    from .pb_service import make_pb_handler

    service = service or TokenizerService()
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
            ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
        ],
    )
    # Two wires, one server: the native msgpack convention and the
    # reference's protobuf contract (what llm-d's Go client speaks).
    server.add_generic_rpc_handlers(
        (_make_grpc_handler(service), make_pb_handler(service))
    )
    address = grpc_target(socket_path)
    server.add_insecure_port(address)
    server.start()
    logger.info("tokenizer service on %s", address)
    return server


def main() -> None:  # pragma: no cover - deployment entry point
    import argparse

    from ...utils.logging import configure_from_env

    configure_from_env()
    parser = argparse.ArgumentParser(description="kvtpu tokenizer sidecar")
    parser.add_argument("--socket", default="/tmp/kvtpu-tokenizer.sock")
    parser.add_argument("--max-workers", type=int, default=8)
    args = parser.parse_args()
    server = serve_uds(args.socket, max_workers=args.max_workers)
    server.wait_for_termination()


if __name__ == "__main__":  # pragma: no cover
    main()
