"""Tokenizer backends: HuggingFace loading with cache + a hermetic fallback.

Counterpart of reference ``tokenizer_service/tokenizer.py``: per-model
tokenizer instances loaded once and cached. Two backends:

- ``hf:`` / plain names → ``transformers.AutoTokenizer`` (local files or
  hub cache; this image has zero egress, so hub names must already be
  cached or be local paths)
- ``simple:`` → a deterministic hermetic tokenizer (hash-bucketed word
  ids) used by tests and smoke deployments; supports a minimal chat
  template so render paths are exercisable without model downloads
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Protocol

from ...utils.lockdep import new_lock


class Tokenizer(Protocol):
    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]: ...

    def encode_with_offsets(
        self, text: str, add_special_tokens: bool = True
    ) -> tuple[list[int], list[tuple[int, int]]]: ...

    def apply_chat_template(
        self, messages: list[dict], add_generation_prompt: bool = True,
        chat_template: Optional[str] = None, tools: Optional[list] = None,
        **kwargs,
    ) -> str: ...


class SimpleTokenizer:
    """Hermetic whitespace tokenizer: token id = stable hash of the word.

    Deterministic across processes (sha1-based, not PYTHONHASHSEED), so
    indexer and engine sides agree on ids — which is all the cache layer
    needs from a tokenizer.
    """

    VOCAB = 32000
    BOS = 1

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        ids, _ = self.encode_with_offsets(text, add_special_tokens)
        return ids

    def encode_with_offsets(self, text, add_special_tokens=True):
        ids: list[int] = []
        offsets: list[tuple[int, int]] = []
        if add_special_tokens:
            ids.append(self.BOS)
            offsets.append((0, 0))
        pos = 0
        for word in text.split():
            start = text.index(word, pos)
            end = start + len(word)
            pos = end
            digest = hashlib.sha1(word.encode("utf-8")).digest()
            ids.append(2 + int.from_bytes(digest[:4], "big") % (self.VOCAB - 2))
            offsets.append((start, end))
        return ids, offsets

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            chat_template=None, tools=None, **kwargs):
        parts = []
        for m in messages:
            content = m["content"]
            if isinstance(content, list):  # structured parts: join text parts
                content = " ".join(
                    p.get("text", "") for p in content if isinstance(p, dict)
                )
            line = f"<|{m['role']}|> {content}"
            if m.get("tool_calls"):
                names = ",".join(
                    str(tc.get("function", {}).get("name", tc.get("name", "?")))
                    if isinstance(tc, dict) else str(tc)
                    for tc in m["tool_calls"]
                )
                line += f" <|tool_calls|> {names}"
            parts.append(line)
        if tools:
            parts.insert(0, f"<|tools|> {len(tools)}")
        if kwargs.get("documents"):
            parts.insert(0, f"<|documents|> {len(kwargs['documents'])}")
        if add_generation_prompt:
            parts.append("<|assistant|>")
        return "\n".join(parts)


class HFTokenizer:
    """transformers-backed tokenizer adapter."""

    def __init__(self, model_name: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(model_name)

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens)

    def encode_with_offsets(self, text, add_special_tokens=True):
        enc = self._tok(
            text,
            add_special_tokens=add_special_tokens,
            return_offsets_mapping=True,
        )
        return list(enc["input_ids"]), [tuple(o) for o in enc["offset_mapping"]]

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            chat_template=None, tools=None, **kwargs):
        return self._tok.apply_chat_template(
            messages,
            tokenize=False,
            add_generation_prompt=add_generation_prompt,
            chat_template=chat_template,
            tools=tools,
            **kwargs,
        )


class TokenizerRegistry:
    """Thread-safe per-model tokenizer cache with eager initialization.

    Loading happens under a per-model lock so a slow HF load for one model
    never stalls requests for already-loaded models.
    """

    def __init__(self) -> None:
        self._lock = new_lock()
        self._tokenizers: dict[str, Tokenizer] = {}
        self._model_locks: dict[str, threading.Lock] = {}

    def get(self, model_name: str) -> Tokenizer:
        with self._lock:
            tok = self._tokenizers.get(model_name)
            if tok is not None:
                return tok
            model_lock = self._model_locks.setdefault(model_name, new_lock())
        with model_lock:
            with self._lock:
                tok = self._tokenizers.get(model_name)
                if tok is not None:
                    return tok
            tok = self._load(model_name)
            with self._lock:
                self._tokenizers[model_name] = tok
            return tok

    @staticmethod
    def _load(model_name: str) -> Tokenizer:
        if model_name.startswith("simple:") or model_name == "simple":
            return SimpleTokenizer()
        if model_name.startswith("hf:"):
            model_name = model_name[len("hf:"):]
        return HFTokenizer(model_name)
