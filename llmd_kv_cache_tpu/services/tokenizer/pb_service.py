"""Protobuf adapter for the tokenizer service.

Serves the reference's ``tokenization.TokenizationService`` contract
(``api/tokenizerpb/tokenizer.proto:188-210``, spoken by the Go EPP's
``uds_tokenizer.go`` client) on the same gRPC server as the native
msgpack surface, by translating protobuf messages to the
transport-independent :class:`TokenizerService` calls.

Error model matches the reference servicer: failures are reported in the
response's ``success``/``error_message`` fields, not as gRPC status codes.
"""

from __future__ import annotations

import json
import uuid

import grpc

from ...utils.logging import get_logger
from ..tokenizerpb import tokenizer_pb2 as pb
from .messages import (
    ChatMessage,
    InitializeTokenizerRequest,
    RenderChatRequest,
    TokenizeRequest,
)
from .service import TokenizerService

logger = get_logger("services.tokenizer.pb")

PROTO_SERVICE_NAME = "tokenization.TokenizationService"


def _value_to_py(v: pb.Value):
    kind = v.WhichOneof("value")
    if kind == "string_value":
        return v.string_value
    if kind == "number_value":
        return v.number_value
    if kind == "bool_value":
        return v.bool_value
    if kind == "list_value":
        return [_value_to_py(x) for x in v.list_value.values]
    if kind == "struct_value":
        return {k: _value_to_py(x) for k, x in v.struct_value.fields.items()}
    return None


def _message_to_internal(m: pb.ChatMessage) -> ChatMessage:
    if m.HasField("content"):
        content = m.content
    elif m.content_parts:
        parts = []
        for part in m.content_parts:
            if part.type == "image_url" and part.HasField("image_url"):
                parts.append(
                    {"type": "image_url", "image_url": {"url": part.image_url.url}}
                )
            else:
                parts.append({"type": "text",
                              "text": part.text if part.HasField("text") else ""})
        content = parts
    else:
        content = ""
    msg = ChatMessage(role=m.role, content=content)
    if m.HasField("tool_calls_json") and m.tool_calls_json:
        try:
            msg.tool_calls = json.loads(m.tool_calls_json)
        except json.JSONDecodeError:
            logger.warning("unparseable tool_calls_json; ignoring")
    return msg


class TokenizerPbServicer:
    """Protobuf-facing RPC implementations delegating to TokenizerService."""

    def __init__(self, service: TokenizerService):
        self.service = service

    def tokenize(self, req: pb.TokenizeRequest, _ctx) -> pb.TokenizeResponse:
        resp = self.service.tokenize(
            TokenizeRequest(
                model_name=req.model_name,
                text=req.input,
                add_special_tokens=req.add_special_tokens,
                return_offsets=True,
            )
        )
        if resp.error:
            return pb.TokenizeResponse(success=False, error_message=resp.error)
        flat = [x for pair in resp.offsets for x in pair]
        return pb.TokenizeResponse(
            input_ids=resp.token_ids, success=True, offset_pairs=flat
        )

    def initialize_tokenizer(
        self, req: pb.InitializeTokenizerRequest, _ctx
    ) -> pb.InitializeTokenizerResponse:
        # enable_thinking / add_generation_prompt are per-render options in
        # this implementation (applied at RenderChatCompletion time), not
        # load-time state; accepted here for wire compatibility.
        resp = self.service.initialize_tokenizer(
            InitializeTokenizerRequest(model_name=req.model_name)
        )
        return pb.InitializeTokenizerResponse(
            success=resp.success, error_message=resp.error
        )

    def render_chat_template(
        self, req: pb.ChatTemplateRequest, _ctx
    ) -> pb.ChatTemplateResponse:
        """Deprecated RPC: render-only (no tokenization)."""
        try:
            tok = self.service.registry.get(req.model_name)
            messages = []
            for turn in req.conversation_turns:
                for m in turn.messages:
                    im = _message_to_internal(m)
                    d = {"role": im.role, "content": im.content}
                    if im.tool_calls:
                        d["tool_calls"] = im.tool_calls
                    messages.append(d)
            kwargs = {k: _value_to_py(v)
                      for k, v in req.chat_template_kwargs.items()}
            if req.continue_final_message:
                kwargs["continue_final_message"] = True
            tools = [
                {k: _value_to_py(v) for k, v in t.tool.items()}
                for t in req.tools
            ]
            documents = [
                {k: _value_to_py(v) for k, v in doc.document.items()}
                for doc in req.documents
            ]
            if documents:
                kwargs["documents"] = documents
            rendered = tok.apply_chat_template(
                messages,
                add_generation_prompt=req.add_generation_prompt,
                chat_template=req.chat_template or None,
                tools=tools or None,
                **kwargs,
            )
            return pb.ChatTemplateResponse(rendered_prompt=rendered, success=True)
        except Exception as e:
            logger.exception("RenderChatTemplate failed")
            return pb.ChatTemplateResponse(success=False, error_message=str(e))

    def render_completion(
        self, req: pb.RenderCompletionRequest, _ctx
    ) -> pb.RenderCompletionResponse:
        resp = self.service.tokenize(
            TokenizeRequest(model_name=req.model_name, text=req.prompt)
        )
        if resp.error:
            return pb.RenderCompletionResponse(
                success=False, error_message=resp.error
            )
        return pb.RenderCompletionResponse(
            request_id=f"rndr-{uuid.uuid4().hex}",
            token_ids=resp.token_ids,
            success=True,
        )

    def render_chat_completion(
        self, req: pb.RenderChatCompletionRequest, _ctx
    ) -> pb.RenderChatCompletionResponse:
        tools = None
        if req.HasField("tools_json") and req.tools_json:
            try:
                tools = json.loads(req.tools_json)
            except json.JSONDecodeError as e:
                return pb.RenderChatCompletionResponse(
                    success=False, error_message=f"bad tools_json: {e}"
                )
        kwargs = {}
        if req.HasField("chat_template_kwargs") and req.chat_template_kwargs:
            try:
                kwargs = json.loads(req.chat_template_kwargs)
            except json.JSONDecodeError as e:
                return pb.RenderChatCompletionResponse(
                    success=False, error_message=f"bad chat_template_kwargs: {e}"
                )
        if req.continue_final_message:
            kwargs["continue_final_message"] = True
        add_gen = (
            req.add_generation_prompt
            if req.HasField("add_generation_prompt")
            else True
        )
        resp = self.service.render_chat_completion(
            RenderChatRequest(
                model_name=req.model_name,
                messages=[_message_to_internal(m) for m in req.messages],
                chat_template=req.chat_template or None,
                add_generation_prompt=add_gen,
                tools=tools,
                template_kwargs=kwargs,
            )
        )
        if resp.error:
            return pb.RenderChatCompletionResponse(
                success=False, error_message=resp.error
            )
        features = pb.MultiModalFeatures()
        for modality, hashes in resp.mm_hashes.items():
            features.mm_hashes[modality].values.extend(hashes)
        for modality, ranges in resp.mm_placeholders.items():
            features.mm_placeholders[modality].ranges.extend(
                pb.PlaceholderRange(offset=o, length=n) for o, n in ranges
            )
        return pb.RenderChatCompletionResponse(
            request_id=f"chat-{uuid.uuid4().hex}",
            token_ids=resp.token_ids,
            features=features,
            success=True,
        )


def make_pb_handler(service: TokenizerService) -> grpc.GenericRpcHandler:
    """Generic handler serving the protobuf contract; add alongside the
    msgpack handler on one server."""
    servicer = TokenizerPbServicer(service)
    rpcs = {
        "Tokenize": (servicer.tokenize,
                     pb.TokenizeRequest, pb.TokenizeResponse),
        "RenderChatTemplate": (servicer.render_chat_template,
                               pb.ChatTemplateRequest, pb.ChatTemplateResponse),
        "InitializeTokenizer": (servicer.initialize_tokenizer,
                                pb.InitializeTokenizerRequest,
                                pb.InitializeTokenizerResponse),
        "RenderChatCompletion": (servicer.render_chat_completion,
                                 pb.RenderChatCompletionRequest,
                                 pb.RenderChatCompletionResponse),
        "RenderCompletion": (servicer.render_completion,
                             pb.RenderCompletionRequest,
                             pb.RenderCompletionResponse),
    }
    method_handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
        for name, (fn, req_cls, resp_cls) in rpcs.items()
    }
    return grpc.method_handlers_generic_handler(PROTO_SERVICE_NAME, method_handlers)
