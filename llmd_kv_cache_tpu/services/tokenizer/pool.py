"""Tokenization worker pool + prompt-based scoring path.

Counterpart of reference ``pkg/tokenization/pool.go`` (worker pool over a
rate-limited queue with blocking ``Tokenize`` and bounded retries) and the
deprecated ``Indexer.GetPodScores(prompt)`` path (``indexer.go:202-229``):
schedulers that only have the raw prompt/chat go through here; schedulers
that already have token ids call ``Indexer.score_tokens`` directly.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Optional, Sequence

from ...core.extra_keys import BlockExtraFeatures
from ...metrics.collector import TOKENIZATION_LATENCY
from ...scoring.indexer import Indexer
from ...utils.logging import get_logger
from .client import UdsTokenizerClient
from .messages import ChatMessage

logger = get_logger("services.tokenizer.pool")

_MAX_ATTEMPTS = 3  # reference pool drops a task after 3 failures


@dataclass
class TokenizationPoolConfig:
    workers: int = 5
    queue_size: int = 1024
    request_timeout_s: float = 30.0


class _Task:
    __slots__ = ("model_name", "prompt", "messages", "block_size", "result",
                 "done", "error")

    def __init__(self, model_name, prompt, messages, block_size):
        self.model_name = model_name
        self.prompt = prompt
        self.messages = messages
        self.block_size = block_size
        self.result = None
        self.error: Optional[str] = None
        self.done = threading.Event()


class TokenizationPool:
    """Bounded worker pool around the UDS tokenizer client."""

    def __init__(self, client: UdsTokenizerClient,
                 cfg: Optional[TokenizationPoolConfig] = None,
                 block_size: int = 16):
        self.client = client
        self.cfg = cfg or TokenizationPoolConfig()
        self.block_size = block_size
        self._queue: queue.Queue = queue.Queue(maxsize=self.cfg.queue_size)
        self._threads: list[threading.Thread] = []
        self._stop = object()
        self._started = False

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for i in range(self.cfg.workers):
            t = threading.Thread(target=self._worker, name=f"tok-pool-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        if not self._started:
            return
        for _ in self._threads:
            self._queue.put(self._stop)
        for t in self._threads:
            t.join()
        self._threads.clear()
        self._started = False

    def _worker(self) -> None:
        while True:
            task = self._queue.get()  # lint: allow-no-deadline (worker parks for work; shutdown via sentinel)
            try:
                if task is self._stop:
                    return
                self._run_task(task)
            finally:
                self._queue.task_done()

    def _run_task(self, task: _Task) -> None:
        import grpc

        start = time.perf_counter()
        for attempt in range(_MAX_ATTEMPTS):
            try:
                if task.messages is not None:
                    task.result = self.client.score_path_features(
                        task.model_name, task.messages, task.block_size
                    )
                else:
                    resp = self.client.encode(task.model_name, task.prompt)
                    task.result = (resp.token_ids, None)
                TOKENIZATION_LATENCY.observe(time.perf_counter() - start)
                task.done.set()
                return
            except grpc.RpcError as e:
                # Transport failures are retryable, with backoff so a
                # briefly-overloaded sidecar isn't hammered.
                task.error = str(e)
                logger.warning("tokenize attempt %d/%d failed: %s",
                               attempt + 1, _MAX_ATTEMPTS, e)
                if attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(0.1 * (attempt + 1))
            except Exception as e:
                # Application-level failures (bad model, render error) are
                # deterministic: fail immediately.
                task.error = str(e)
                break
        task.done.set()  # dropped

    def tokenize(
        self,
        model_name: str,
        prompt: Optional[str] = None,
        messages: Optional[Sequence[ChatMessage]] = None,
        block_size: Optional[int] = None,
    ) -> tuple[list[int], Optional[list[Optional[BlockExtraFeatures]]]]:
        """Blocking tokenize/render through the pool.

        One overall deadline (``request_timeout_s``) covers queueing and
        execution.
        """
        if (prompt is None) == (messages is None):
            raise ValueError("provide exactly one of prompt or messages")
        if messages is not None and not messages:
            raise ValueError("messages must be non-empty")
        task = _Task(model_name, prompt,
                     list(messages) if messages is not None else None,
                     block_size if block_size is not None else self.block_size)
        deadline = time.monotonic() + self.cfg.request_timeout_s
        try:
            self._queue.put(task, timeout=self.cfg.request_timeout_s)
        except queue.Full:
            raise TimeoutError("tokenization queue full") from None
        if not task.done.wait(max(deadline - time.monotonic(), 0.0)):
            raise TimeoutError("tokenization timed out")
        if task.result is None:
            raise RuntimeError(f"tokenization failed: {task.error}")
        return task.result


class PromptScorer:
    """``GetPodScores(prompt)``: render + score in one call."""

    def __init__(self, indexer: Indexer, pool: TokenizationPool):
        self.indexer = indexer
        self.pool = pool

    def get_pod_scores(
        self,
        model_name: str,
        prompt: Optional[str] = None,
        messages: Optional[Sequence[ChatMessage]] = None,
        pod_identifiers: Optional[set[str]] = None,
    ) -> dict[str, float]:
        # Block size comes from the indexer's own processor so multimodal
        # features are computed at exactly the scoring granularity.
        tokens, features = self.pool.tokenize(
            model_name, prompt, messages,
            block_size=self.indexer.token_processor.block_size,
        )
        return self.indexer.score_tokens(
            tokens, model_name, pod_identifiers, features
        )
