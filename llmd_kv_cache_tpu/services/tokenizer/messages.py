"""Tokenizer RPC message types with msgpack wire encoding.

Role parity with reference ``api/tokenizerpb/tokenizer.proto``: the same
five-call surface and field sets, carried as msgpack maps (string keys,
forward-compatible: unknown keys are ignored on decode).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import msgpack


def _pack(d: dict) -> bytes:
    return msgpack.packb(d, use_bin_type=True)


def _unpack(b: bytes) -> dict:
    return msgpack.unpackb(b, raw=False)


@dataclass
class InitializeTokenizerRequest:
    model_name: str

    def to_bytes(self) -> bytes:
        return _pack({"model_name": self.model_name})

    @classmethod
    def from_bytes(cls, b: bytes) -> "InitializeTokenizerRequest":
        d = _unpack(b)
        return cls(model_name=d.get("model_name", ""))


@dataclass
class InitializeTokenizerResponse:
    success: bool = True
    error: str = ""

    def to_bytes(self) -> bytes:
        return _pack({"success": self.success, "error": self.error})

    @classmethod
    def from_bytes(cls, b: bytes) -> "InitializeTokenizerResponse":
        d = _unpack(b)
        return cls(success=d.get("success", False), error=d.get("error", ""))


@dataclass
class TokenizeRequest:
    model_name: str
    text: str
    add_special_tokens: bool = True
    return_offsets: bool = False

    def to_bytes(self) -> bytes:
        return _pack(
            {
                "model_name": self.model_name,
                "text": self.text,
                "add_special_tokens": self.add_special_tokens,
                "return_offsets": self.return_offsets,
            }
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "TokenizeRequest":
        d = _unpack(b)
        return cls(
            model_name=d.get("model_name", ""),
            text=d.get("text", ""),
            add_special_tokens=d.get("add_special_tokens", True),
            return_offsets=d.get("return_offsets", False),
        )


@dataclass
class TokenizeResponse:
    token_ids: list[int] = field(default_factory=list)
    offsets: list[tuple[int, int]] = field(default_factory=list)
    error: str = ""

    def to_bytes(self) -> bytes:
        return _pack(
            {
                "token_ids": self.token_ids,
                "offsets": [list(o) for o in self.offsets],
                "error": self.error,
            }
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "TokenizeResponse":
        d = _unpack(b)
        return cls(
            token_ids=list(d.get("token_ids", [])),
            offsets=[tuple(o) for o in d.get("offsets", [])],
            error=d.get("error", ""),
        )


@dataclass
class ChatMessage:
    role: str
    content: Any  # str or structured content parts (list of dicts)
    # Assistant tool calls (list of dicts), passed through to the chat
    # template when present.
    tool_calls: Optional[list] = None


@dataclass
class RenderChatRequest:
    model_name: str
    messages: list[ChatMessage] = field(default_factory=list)
    chat_template: Optional[str] = None
    add_generation_prompt: bool = True
    tools: Optional[list[dict]] = None
    template_kwargs: dict = field(default_factory=dict)

    def to_bytes(self) -> bytes:
        return _pack(
            {
                "model_name": self.model_name,
                "messages": [
                    {"role": m.role, "content": m.content,
                     "tool_calls": m.tool_calls}
                    for m in self.messages
                ],
                "chat_template": self.chat_template,
                "add_generation_prompt": self.add_generation_prompt,
                "tools": self.tools,
                "template_kwargs": self.template_kwargs,
            }
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "RenderChatRequest":
        d = _unpack(b)
        return cls(
            model_name=d.get("model_name", ""),
            messages=[
                ChatMessage(role=m.get("role", ""), content=m.get("content"),
                            tool_calls=m.get("tool_calls"))
                for m in d.get("messages", [])
            ],
            chat_template=d.get("chat_template"),
            add_generation_prompt=d.get("add_generation_prompt", True),
            tools=d.get("tools"),
            template_kwargs=d.get("template_kwargs", {}) or {},
        )


@dataclass
class RenderChatResponse:
    token_ids: list[int] = field(default_factory=list)
    rendered_text: str = ""
    # modality → content-hash identifiers, aligned with placeholders
    mm_hashes: dict[str, list[str]] = field(default_factory=dict)
    # modality → [(offset, length)] placeholder token ranges
    mm_placeholders: dict[str, list[tuple[int, int]]] = field(default_factory=dict)
    error: str = ""

    def to_bytes(self) -> bytes:
        return _pack(
            {
                "token_ids": self.token_ids,
                "rendered_text": self.rendered_text,
                "mm_hashes": self.mm_hashes,
                "mm_placeholders": {
                    k: [list(p) for p in v] for k, v in self.mm_placeholders.items()
                },
                "error": self.error,
            }
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "RenderChatResponse":
        d = _unpack(b)
        return cls(
            token_ids=list(d.get("token_ids", [])),
            rendered_text=d.get("rendered_text", ""),
            mm_hashes={k: list(v) for k, v in (d.get("mm_hashes") or {}).items()},
            mm_placeholders={
                k: [tuple(p) for p in v]
                for k, v in (d.get("mm_placeholders") or {}).items()
            },
            error=d.get("error", ""),
        )


@dataclass
class RenderCompletionRequest:
    model_name: str
    prompt: str
    add_special_tokens: bool = True

    def to_bytes(self) -> bytes:
        return _pack(
            {
                "model_name": self.model_name,
                "prompt": self.prompt,
                "add_special_tokens": self.add_special_tokens,
            }
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "RenderCompletionRequest":
        d = _unpack(b)
        return cls(
            model_name=d.get("model_name", ""),
            prompt=d.get("prompt", ""),
            add_special_tokens=d.get("add_special_tokens", True),
        )
