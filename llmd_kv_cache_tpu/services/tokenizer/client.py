"""Tokenizer service client.

Counterpart of reference ``pkg/tokenization/uds_tokenizer.go``: gRPC client
over ``unix://`` (TCP for tests) with large message caps, keepalive,
per-model initialization with bounded retry/backoff, and the Encode /
Render / RenderChat calls the indexer's prompt path needs. Also provides
``score_path_features``: rendered chat → (token_ids, extra_features) ready
for ``Indexer.score_tokens``.
"""

from __future__ import annotations

from typing import Optional

import grpc

from ...core.extra_keys import BlockExtraFeatures, PlaceholderRange, compute_block_extra_features
from ...resilience.failpoints import FaultInjected, failpoints
from ...resilience.policy import RetryPolicy, RetryExhausted, call_with_retry
from ...telemetry import current_traceparent, tracer
from ...utils.logging import get_logger
from ...utils.net import grpc_target
from .messages import (
    ChatMessage,
    InitializeTokenizerRequest,
    InitializeTokenizerResponse,
    RenderChatRequest,
    RenderChatResponse,
    RenderCompletionRequest,
    TokenizeRequest,
    TokenizeResponse,
)
from .service import MAX_MESSAGE_BYTES, SERVICE_NAME

logger = get_logger("services.tokenizer.client")

_INIT_RETRIES = 5
_INIT_BACKOFF_S = 0.5

# Error-mode fires at the entry of every outgoing RPC (chaos: flaky
# tokenizer sidecar). Injected faults are retried like transport errors.
FP_TOKENIZER_RPC = "services.tokenizer.rpc"

# Data-path RPCs ride the request hot path, so the budget is tight: one
# fast retry absorbs a transient blip, anything longer surfaces to the
# caller. Init gets its own longer policy (server may still be starting).
DEFAULT_RPC_RETRY_POLICY = RetryPolicy(
    max_attempts=2, base_delay_s=0.05, max_delay_s=0.5, deadline_s=5.0
)
_INIT_RETRY_POLICY = RetryPolicy(
    max_attempts=_INIT_RETRIES, base_delay_s=_INIT_BACKOFF_S, max_delay_s=5.0
)


class _InitFailed(Exception):
    """Application-level init failure (bad model name etc.): deterministic,
    retrying cannot help."""


_RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
})


def _retryable(exc: BaseException) -> bool:
    """Transient transport failures only; deterministic status codes
    surface to the caller untouched."""
    if isinstance(exc, FaultInjected):
        return True
    if isinstance(exc, grpc.RpcError):
        code = exc.code() if callable(getattr(exc, "code", None)) else None
        return code in _RETRYABLE_CODES
    return False


class UdsTokenizerClient:
    """Blocking client for the tokenizer sidecar."""

    def __init__(self, address: str, timeout_s: float = 30.0,
                 retry_policy: Optional[RetryPolicy] = None):
        self._channel = grpc.insecure_channel(
            grpc_target(address),
            options=[
                ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
                ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
                ("grpc.keepalive_time_ms", 30_000),
            ],
        )
        self._timeout = timeout_s
        self.retry_policy = retry_policy or DEFAULT_RPC_RETRY_POLICY
        self._initialized_models: set[str] = set()

        def unary(method, req_serializer, resp_deserializer):
            return self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=req_serializer,
                response_deserializer=resp_deserializer,
            )

        self._init = unary(
            "InitializeTokenizer",
            lambda r: r.to_bytes(),
            InitializeTokenizerResponse.from_bytes,
        )
        self._tokenize = unary(
            "Tokenize", lambda r: r.to_bytes(), TokenizeResponse.from_bytes
        )
        self._render_completion = unary(
            "RenderCompletion", lambda r: r.to_bytes(), TokenizeResponse.from_bytes
        )
        self._render_chat = unary(
            "RenderChatCompletion", lambda r: r.to_bytes(), RenderChatResponse.from_bytes
        )

    def _call(self, rpc, request, method: str = ""):
        """Issue one unary RPC under the retry policy; transient transport
        errors and injected faults are retried. On exhaustion the last
        underlying error is re-raised so callers keep the grpc.RpcError
        contract.

        The ambient W3C trace context rides as ``traceparent`` gRPC
        metadata (injected per attempt), so the server-side span parents
        into the caller's trace across the UDS hop. The ambient request
        deadline rides as ``kvtpu-deadline-ms`` metadata the same way and
        caps the transport timeout — an already-expired budget fails the
        call before any wire traffic.
        """
        from ...resilience.deadline import (
            current_deadline,
            deadline_metadata,
            effective_timeout,
        )

        with tracer().span("llm_d.kv_cache.tokenizer.rpc", method=method):
            tp = current_traceparent()
            md = (("traceparent", tp),) if tp else ()
            md = md + tuple(deadline_metadata())
            metadata = md or None
            dl = current_deadline()
            timeout = effective_timeout(self._timeout)

            def attempt():
                if dl is not None:
                    dl.check("services.tokenizer.rpc")
                failpoints.hit(FP_TOKENIZER_RPC)
                return rpc(request, timeout=timeout, metadata=metadata)

            try:
                return call_with_retry(
                    attempt, self.retry_policy, retryable=_retryable
                )
            except RetryExhausted as e:
                raise e.__cause__

    def initialize(self, model_name: str) -> None:
        """Eager per-model init with bounded retry/backoff
        (``uds_tokenizer.go:162-193``). Transport failures (server still
        starting) retry; application-level failures are deterministic and
        fail fast."""
        if model_name in self._initialized_models:
            return

        def attempt():
            failpoints.hit(FP_TOKENIZER_RPC)
            resp = self._init(
                InitializeTokenizerRequest(model_name), timeout=self._timeout
            )
            if not resp.success:
                raise _InitFailed(resp.error)
            return resp

        try:
            call_with_retry(attempt, _INIT_RETRY_POLICY, retryable=_retryable)
        except (_InitFailed, RetryExhausted, grpc.RpcError) as e:
            cause = e.__cause__ if isinstance(e, RetryExhausted) else e
            raise RuntimeError(
                f"tokenizer init failed for {model_name}: {cause}"
            ) from e
        self._initialized_models.add(model_name)

    def encode(
        self,
        model_name: str,
        text: str,
        add_special_tokens: bool = True,
        return_offsets: bool = False,
    ) -> TokenizeResponse:
        resp = self._call(
            self._tokenize,
            method="Tokenize",
            request=TokenizeRequest(
                model_name=model_name,
                text=text,
                add_special_tokens=add_special_tokens,
                return_offsets=return_offsets,
            ),
        )
        if resp.error:
            raise RuntimeError(f"tokenize failed: {resp.error}")
        return resp

    def render(self, model_name: str, prompt: str,
               add_special_tokens: bool = True) -> list[int]:
        resp = self._call(
            self._render_completion,
            method="RenderCompletion",
            request=RenderCompletionRequest(
                model_name=model_name, prompt=prompt,
                add_special_tokens=add_special_tokens,
            ),
        )
        if resp.error:
            raise RuntimeError(f"render failed: {resp.error}")
        return resp.token_ids

    def render_chat(
        self,
        model_name: str,
        messages: list[ChatMessage],
        chat_template: Optional[str] = None,
        add_generation_prompt: bool = True,
        tools: Optional[list[dict]] = None,
        **template_kwargs,
    ) -> RenderChatResponse:
        resp = self._call(
            self._render_chat,
            method="RenderChatCompletion",
            request=RenderChatRequest(
                model_name=model_name,
                messages=messages,
                chat_template=chat_template,
                add_generation_prompt=add_generation_prompt,
                tools=tools,
                template_kwargs=template_kwargs,
            ),
        )
        if resp.error:
            raise RuntimeError(f"render chat failed: {resp.error}")
        return resp

    def score_path_features(
        self,
        model_name: str,
        messages: list[ChatMessage],
        block_size: int,
        **render_kwargs,
    ) -> tuple[list[int], Optional[list[Optional[BlockExtraFeatures]]]]:
        """Render a chat and produce (token_ids, extra_features) for
        ``Indexer.score_tokens`` — the deprecated in-process prompt path of
        the reference (``indexer.go:202-229``) as a client-side helper."""
        resp = self.render_chat(model_name, messages, **render_kwargs)
        placeholders = {
            modality: [PlaceholderRange(offset=o, length=n) for o, n in spans]
            for modality, spans in resp.mm_placeholders.items()
        }
        features = compute_block_extra_features(
            resp.mm_hashes, placeholders, block_size, len(resp.token_ids)
        )
        return resp.token_ids, features

    def close(self) -> None:
        self._channel.close()
