"""Tokenizer service client.

Counterpart of reference ``pkg/tokenization/uds_tokenizer.go``: gRPC client
over ``unix://`` (TCP for tests) with large message caps, keepalive,
per-model initialization with bounded retry/backoff, and the Encode /
Render / RenderChat calls the indexer's prompt path needs. Also provides
``score_path_features``: rendered chat → (token_ids, extra_features) ready
for ``Indexer.score_tokens``.
"""

from __future__ import annotations

import time
from typing import Optional

import grpc

from ...core.extra_keys import BlockExtraFeatures, PlaceholderRange, compute_block_extra_features
from ...utils.logging import get_logger
from ...utils.net import grpc_target
from .messages import (
    ChatMessage,
    InitializeTokenizerRequest,
    InitializeTokenizerResponse,
    RenderChatRequest,
    RenderChatResponse,
    RenderCompletionRequest,
    TokenizeRequest,
    TokenizeResponse,
)
from .service import MAX_MESSAGE_BYTES, SERVICE_NAME

logger = get_logger("services.tokenizer.client")

_INIT_RETRIES = 5
_INIT_BACKOFF_S = 0.5


class UdsTokenizerClient:
    """Blocking client for the tokenizer sidecar."""

    def __init__(self, address: str, timeout_s: float = 30.0):
        self._channel = grpc.insecure_channel(
            grpc_target(address),
            options=[
                ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
                ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
                ("grpc.keepalive_time_ms", 30_000),
            ],
        )
        self._timeout = timeout_s
        self._initialized_models: set[str] = set()

        def unary(method, req_serializer, resp_deserializer):
            return self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=req_serializer,
                response_deserializer=resp_deserializer,
            )

        self._init = unary(
            "InitializeTokenizer",
            lambda r: r.to_bytes(),
            InitializeTokenizerResponse.from_bytes,
        )
        self._tokenize = unary(
            "Tokenize", lambda r: r.to_bytes(), TokenizeResponse.from_bytes
        )
        self._render_completion = unary(
            "RenderCompletion", lambda r: r.to_bytes(), TokenizeResponse.from_bytes
        )
        self._render_chat = unary(
            "RenderChatCompletion", lambda r: r.to_bytes(), RenderChatResponse.from_bytes
        )

    def initialize(self, model_name: str) -> None:
        """Eager per-model init with bounded retry/backoff
        (``uds_tokenizer.go:162-193``)."""
        if model_name in self._initialized_models:
            return
        last_error = None
        for attempt in range(_INIT_RETRIES):
            try:
                resp = self._init(
                    InitializeTokenizerRequest(model_name), timeout=self._timeout
                )
                if resp.success:
                    self._initialized_models.add(model_name)
                    return
                # Application-level failure (bad model name etc.) is
                # deterministic: retrying cannot help.
                last_error = resp.error
                break
            except grpc.RpcError as e:
                # Transport failures (server still starting) are retryable.
                last_error = str(e)
                if attempt < _INIT_RETRIES - 1:
                    time.sleep(_INIT_BACKOFF_S * (attempt + 1))
        raise RuntimeError(
            f"tokenizer init failed for {model_name}: {last_error}"
        )

    def encode(
        self,
        model_name: str,
        text: str,
        add_special_tokens: bool = True,
        return_offsets: bool = False,
    ) -> TokenizeResponse:
        resp = self._tokenize(
            TokenizeRequest(
                model_name=model_name,
                text=text,
                add_special_tokens=add_special_tokens,
                return_offsets=return_offsets,
            ),
            timeout=self._timeout,
        )
        if resp.error:
            raise RuntimeError(f"tokenize failed: {resp.error}")
        return resp

    def render(self, model_name: str, prompt: str,
               add_special_tokens: bool = True) -> list[int]:
        resp = self._render_completion(
            RenderCompletionRequest(
                model_name=model_name, prompt=prompt,
                add_special_tokens=add_special_tokens,
            ),
            timeout=self._timeout,
        )
        if resp.error:
            raise RuntimeError(f"render failed: {resp.error}")
        return resp.token_ids

    def render_chat(
        self,
        model_name: str,
        messages: list[ChatMessage],
        chat_template: Optional[str] = None,
        add_generation_prompt: bool = True,
        tools: Optional[list[dict]] = None,
        **template_kwargs,
    ) -> RenderChatResponse:
        resp = self._render_chat(
            RenderChatRequest(
                model_name=model_name,
                messages=messages,
                chat_template=chat_template,
                add_generation_prompt=add_generation_prompt,
                tools=tools,
                template_kwargs=template_kwargs,
            ),
            timeout=self._timeout,
        )
        if resp.error:
            raise RuntimeError(f"render chat failed: {resp.error}")
        return resp

    def score_path_features(
        self,
        model_name: str,
        messages: list[ChatMessage],
        block_size: int,
        **render_kwargs,
    ) -> tuple[list[int], Optional[list[Optional[BlockExtraFeatures]]]]:
        """Render a chat and produce (token_ids, extra_features) for
        ``Indexer.score_tokens`` — the deprecated in-process prompt path of
        the reference (``indexer.go:202-229``) as a client-side helper."""
        resp = self.render_chat(model_name, messages, **render_kwargs)
        placeholders = {
            modality: [PlaceholderRange(offset=o, length=n) for o, n in spans]
            for modality, spans in resp.mm_placeholders.items()
        }
        features = compute_block_extra_features(
            resp.mm_hashes, placeholders, block_size, len(resp.token_ids)
        )
        return resp.token_ids, features

    def close(self) -> None:
        self._channel.close()
