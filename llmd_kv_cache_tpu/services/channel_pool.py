"""Process-wide shared gRPC channel pool.

Every client used to open its own ``grpc.insecure_channel`` per
construction — harmless for one scheduler talking to one indexer, but
the sharded scatter-gather path constructs a client per shard (and
benches/tests construct many), so per-construction channels meant
per-construction TCP+HTTP/2 setup on the hot path. Channels are safe to
share across threads and multiplex RPCs, so the pool hands out one
refcounted channel per normalized target.

``acquire`` / ``release`` pair with client construction / ``close()``;
the underlying channel closes when its last user releases it.
"""

from __future__ import annotations


import grpc

from ..utils.lockdep import new_lock
from ..utils.logging import get_logger
from ..utils.net import grpc_target

logger = get_logger("services.channel_pool")

_lock = new_lock()
_channels: dict[str, tuple[grpc.Channel, int]] = {}


def acquire(address: str) -> grpc.Channel:
    """Shared insecure channel for ``address`` (refcount +1)."""
    target = grpc_target(address)
    with _lock:
        entry = _channels.get(target)
        if entry is not None:
            channel, refs = entry
            _channels[target] = (channel, refs + 1)
            return channel
        channel = grpc.insecure_channel(target)
        _channels[target] = (channel, 1)
        return channel


def release(address: str) -> None:
    """Refcount -1; closes the channel when the last user releases.

    Releasing an unknown target is a no-op (idempotent ``close()``)."""
    target = grpc_target(address)
    with _lock:
        entry = _channels.get(target)
        if entry is None:
            return
        channel, refs = entry
        if refs > 1:
            _channels[target] = (channel, refs - 1)
            return
        del _channels[target]
    channel.close()


def stats() -> dict:
    """{target: refcount} snapshot (debug surface, tests)."""
    with _lock:
        return {t: refs for t, (_, refs) in _channels.items()}
