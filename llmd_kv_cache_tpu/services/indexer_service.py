"""Standalone indexer service: scoring over gRPC + event-plane wiring.

Counterpart of reference ``examples/kv_cache_index_service`` (gRPC
``IndexerService.GetPodScores``, ``api/indexerpb/indexer.proto:24-43``) and
the assembled indexer deployment: one process that runs the event pool,
ZMQ subscribers, and serves scoring RPCs to schedulers that aren't
in-process (the embedded-library path remains ``scoring.Indexer``).

Two wire surfaces on one server:

- ``indexer.v1.IndexerService/GetPodScores`` — the reference's protobuf
  contract, byte-compatible with llm-d's Go EPP (prompt in, tokenized
  server-side; ``api/indexerpb/indexer.proto:24-43``).
- ``kvtpu.indexer.IndexerService/GetPodScores`` — the native
  msgpack-over-gRPC convention (token IDs in, no tokenizer needed; same
  convention as the tokenizer sidecar).
"""

from __future__ import annotations

import time
from concurrent import futures
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import grpc
import msgpack

# The protobuf stubs (and their google.protobuf dependency) are imported
# lazily by the pb surface only, so msgpack-only consumers keep the
# grpc+msgpack dependency set.

from ..events.pool import Pool, PoolConfig
from ..events.subscriber_manager import SubscriberManager
from ..events.zmq_subscriber import ZMQSubscriber
from ..recovery.drain import DrainCoordinator
from ..recovery.manager import RecoveryManager
from ..recovery.reconcile import (
    AntiEntropyReconciler,
    DigestSource,
    DivergenceAuditor,
    IndexDigestSource,
    digest_from_blocks,
    pod_blocks_from_state,
)
from ..resilience.deadline import (
    Deadline,
    DeadlineExceeded,
    current_deadline,
    deadline_metadata,
    deadline_scope,
    effective_timeout,
    extract_deadline,
)
from ..resilience.failpoints import FaultInjected, failpoints
from ..resilience.policy import RetryExhausted, RetryPolicy, call_with_retry
from ..resilience.shedding import (
    BROWNOUT,
    PRIORITY_NORMAL,
    SHED,
    CoDelShedder,
)
from ..scoring.indexer import Indexer, IndexerConfig
from ..telemetry import attach_failpoint_listener, current_traceparent, tracer
from ..telemetry.flight_recorder import KIND_SHED, record as record_event
from ..utils.logging import get_logger
from ..utils.net import grpc_target
from . import channel_pool
from .admin import AdminServer, start_observability_servers
from .tokenizer.service import extract_traceparent

logger = get_logger("services.indexer")

SERVICE_NAME = "kvtpu.indexer.IndexerService"
PROTO_SERVICE_NAME = "indexer.v1.IndexerService"

# Error-mode fires at the entry of every outgoing scoring RPC (chaos:
# flaky indexer deployment). Injected faults retry like transport errors.
FP_INDEXER_RPC = "services.indexer.rpc"

# Server-side lookup hook (chaos: gray failures). Delay-mode arms a
# slow-not-dead shard: ``hit()`` fires both the generic name and a
# ``<name>.<shard_id>`` variant, so one shard of an in-process fleet can
# be slowed while its peers stay healthy.
FP_SHARD_LOOKUP = "services.indexer.lookup"

# Scoring sits on the scheduler hot path: one fast retry, then give up
# and let the picker fall back to round-robin.
DEFAULT_RPC_RETRY_POLICY = RetryPolicy(
    max_attempts=2, base_delay_s=0.05, max_delay_s=0.5, deadline_s=5.0
)


_RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
})


def _retryable(exc: BaseException) -> bool:
    """Transient transport failures only; application-level status codes
    (FAILED_PRECONDITION, INVALID_ARGUMENT, …) are deterministic and must
    surface to the caller untouched."""
    if isinstance(exc, FaultInjected):
        return True
    if isinstance(exc, grpc.RpcError):
        code = exc.code() if callable(getattr(exc, "code", None)) else None
        return code in _RETRYABLE_CODES
    return False


def _call_rpc(rpc, request, timeout: float, policy: RetryPolicy):
    """One unary scoring RPC under the retry policy. On exhaustion the
    last underlying error is re-raised so callers keep the grpc.RpcError
    contract (status code inspection, etc.). Ambient W3C trace context
    rides as ``traceparent`` metadata so the server span joins the
    caller's trace; the ambient request deadline rides the same way
    (``kvtpu-deadline-ms``) and caps the transport timeout — an expired
    deadline fails the call before any wire traffic."""
    tp = current_traceparent()
    md = (("traceparent", tp),) if tp else ()
    md = md + tuple(deadline_metadata())
    metadata = md or None
    dl = current_deadline()
    timeout = effective_timeout(timeout)

    def attempt():
        if dl is not None:
            dl.check("services.rpc")
        failpoints.hit(FP_INDEXER_RPC)
        return rpc(request, timeout=timeout, metadata=metadata)

    try:
        return call_with_retry(attempt, policy, retryable=_retryable)
    except RetryExhausted as e:
        raise e.__cause__


def _pack_dict(d: dict) -> bytes:
    return msgpack.packb(d, use_bin_type=True)


def _unpack_dict(b: bytes) -> dict:
    return msgpack.unpackb(b, raw=False, strict_map_key=False)


def _row_from_entry(e) -> list:
    """PodEntry → snapshot wire row ``[pod, tier, flags, group_idx]``
    (the dump_state/journal layout; cluster.remote.entry_from_row is the
    inverse)."""
    return [
        e.pod_identifier,
        e.device_tier,
        (1 if e.speculative else 0) | (2 if e.has_group else 0),
        e.group_idx,
    ]


@dataclass
class ScoreRequest:
    tokens: list[int]
    model_name: str
    pod_identifiers: list[str] = field(default_factory=list)
    # Shard metadata (cluster/): the sender's intended owner shard id for
    # a shard-targeted request, "" for an unsharded call. Tolerant like
    # ``traceparent``: old peers omit it, old servers ignore it.
    shard: str = ""
    # Target pod role for disaggregated serving (offload/handoff): ""
    # (role-agnostic, the legacy behavior), "prefill", or "decode".
    # "decode" requests additionally earn transferred-prefix residency
    # bonuses when the serving indexer tracks handoffs. Same tolerance
    # pattern as ``shard``.
    role: str = ""
    # End-to-end deadline: milliseconds of budget remaining at send time
    # (resilience.deadline — relative, so clock skew cannot bend it).
    # 0/absent = no deadline; old servers ignore it.
    deadline_ms: int = 0
    # Shedding priority (resilience.shedding.PRIORITY_*): 0 low, 1 normal
    # (the default — also what an old peer's absent field decodes to),
    # 2 critical (never shed).
    priority: int = 1
    # Sender's topology epoch (cluster.membership). 0/absent = an
    # unstamped (pre-epoch) peer, never fenced; a stamp older than the
    # server's epoch is rejected or flagged per ``fenceMode``, and a
    # newer stamp teaches the server the fleet moved on (piggyback
    # gossip). Same tolerance pattern as ``deadline_ms``.
    epoch: int = 0

    def to_bytes(self) -> bytes:
        return msgpack.packb(
            {
                "tokens": self.tokens,
                "model_name": self.model_name,
                "pod_identifiers": self.pod_identifiers,
                "shard": self.shard,
                "role": self.role,
                "deadline_ms": self.deadline_ms,
                "priority": self.priority,
                "epoch": self.epoch,
            },
            use_bin_type=True,
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "ScoreRequest":
        d = msgpack.unpackb(b, raw=False)
        try:
            deadline_ms = int(d.get("deadline_ms", 0) or 0)
        except (TypeError, ValueError):
            deadline_ms = 0
        try:
            priority = int(d.get("priority", 1))
        except (TypeError, ValueError):
            priority = 1
        try:
            epoch = int(d.get("epoch", 0) or 0)
        except (TypeError, ValueError):
            epoch = 0
        return cls(
            tokens=list(d.get("tokens", [])),
            model_name=d.get("model_name", ""),
            pod_identifiers=list(d.get("pod_identifiers", [])),
            shard=d.get("shard", "") or "",
            role=d.get("role", "") or "",
            deadline_ms=deadline_ms,
            priority=priority,
            epoch=epoch,
        )


@dataclass
class ScoreResponse:
    scores: dict[str, float] = field(default_factory=dict)
    error: str = ""
    # True while the serving index is still warming after a restart
    # (recovery.manager): scores are best-effort (snapshot + partial
    # replay) and routers should widen their fallback. Absent on the wire
    # from older servers, so decoding defaults to False.
    degraded: bool = False
    # W3C traceparent of the GetPodScores span that produced these scores.
    # A scheduler that routes on them hands it to the chosen engine's
    # ``enqueue(..., traceparent=...)`` so admission/prefill/decode spans
    # join the scorer's trace — one trace covers score→serve. Empty when
    # tracing is off; absent on the wire from older servers.
    traceparent: str = ""
    # Shard metadata (cluster/): the answering replica's shard id ("" for
    # an unsharded indexer) and the shards a router could not reach while
    # assembling these scores (scores are a lower bound when non-empty).
    # Both follow the ``traceparent`` tolerance pattern — absent on the
    # wire from older peers, ignored by them on receive.
    shard: str = ""
    degraded_shards: list[str] = field(default_factory=list)
    # Per-pod transferred-prefix residency bonus already folded into
    # ``scores`` — surfaced separately so a handoff coordinator can see
    # how much of a decode pod's score is in-flight/landed transfer state
    # vs indexed cache. Empty for role-agnostic requests and on the wire
    # from older servers (same tolerance pattern as ``shard``).
    residency: dict[str, float] = field(default_factory=dict)
    # Why ``degraded`` is set, when the server knows: "" (not degraded, or
    # an older server), "warmup", "brownout" (overload — residency fold-in
    # skipped), "shed" (overload — not scored), "deadline" (the request's
    # budget expired in-flight), "fenced" (the request carried a stale
    # topology epoch and ``fenceMode: reject`` refused it). Same
    # tolerance pattern as ``shard``.
    degraded_reason: str = ""
    # The answering server's topology epoch (cluster.membership) — the
    # piggyback half of epoch gossip: a caller seeing a higher epoch than
    # it pinned learns the fleet moved on without any new RPC surface.
    # 0/absent = a pre-epoch server. Same tolerance pattern as ``shard``.
    epoch: int = 0

    def to_bytes(self) -> bytes:
        return msgpack.packb(
            {"scores": self.scores, "error": self.error,
             "degraded": self.degraded, "traceparent": self.traceparent,
             "shard": self.shard, "degraded_shards": self.degraded_shards,
             "residency": self.residency,
             "degraded_reason": self.degraded_reason,
             "epoch": self.epoch},
            use_bin_type=True,
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "ScoreResponse":
        d = msgpack.unpackb(b, raw=False)
        try:
            epoch = int(d.get("epoch", 0) or 0)
        except (TypeError, ValueError):
            epoch = 0
        return cls(
            scores=dict(d.get("scores", {})),
            error=d.get("error", ""),
            degraded=bool(d.get("degraded", False)),
            traceparent=d.get("traceparent", "") or "",
            shard=d.get("shard", "") or "",
            degraded_shards=[str(s) for s in d.get("degraded_shards", [])],
            residency=dict(d.get("residency", {})),
            degraded_reason=d.get("degraded_reason", "") or "",
            epoch=epoch,
        )


@dataclass
class ScoreFeedback:
    """The prediction a request was routed on, carried to the engine.

    A scheduler that routes on a :class:`ScoreResponse` builds one of
    these (:meth:`from_response`) and hands it to the chosen engine's
    ``enqueue(..., feedback=...)``; the engine attaches it to the
    realized prefix outcome it records at prefill finish
    (telemetry/audit.py), closing the score→serve loop. Every field
    follows the ``ScoreResponse.residency`` tolerance pattern — absent
    on the wire from older peers, ignored by them on receive — so a
    mixed-version fleet degrades to "no calibration for that hop", never
    a decode error.
    """

    # W3C traceparent of the scoring span — the join key the collector
    # matches predictions to outcomes on.
    traceparent: str = ""
    # The pod the scheduler actually chose (not necessarily the top
    # score — affinity/load tie-breaks are the scheduler's business).
    chosen_pod: str = ""
    # The chosen pod's predicted prefix score, in block units
    # (tier-weighted, so fractional).
    predicted_blocks: float = 0.0
    # Prompt length in canonical blocks at score time.
    total_blocks: int = 0
    # The full per-pod score map — the routing-regret counterfactual
    # needs the losing pods' predictions too.
    scores: dict[str, float] = field(default_factory=dict)
    # Per-pod transferred-prefix residency bonus (ScoreResponse.residency).
    residency: dict[str, float] = field(default_factory=dict)
    # Index staleness (event lag) at score time, for staleness-attributed
    # calibration error.
    staleness_s: float = 0.0

    @classmethod
    def from_response(cls, resp: "ScoreResponse", chosen_pod: str,
                      total_blocks: int = 0,
                      staleness_s: float = 0.0) -> "ScoreFeedback":
        """Build feedback from the response a scheduler routed on."""
        return cls(
            traceparent=resp.traceparent,
            chosen_pod=chosen_pod,
            predicted_blocks=float(resp.scores.get(chosen_pod, 0.0)),
            total_blocks=total_blocks,
            scores=dict(resp.scores),
            residency=dict(resp.residency),
            staleness_s=staleness_s,
        )

    def to_bytes(self) -> bytes:
        return msgpack.packb(
            {"traceparent": self.traceparent,
             "chosen_pod": self.chosen_pod,
             "predicted_blocks": self.predicted_blocks,
             "total_blocks": self.total_blocks,
             "scores": self.scores,
             "residency": self.residency,
             "staleness_s": self.staleness_s},
            use_bin_type=True,
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "ScoreFeedback":
        d = msgpack.unpackb(b, raw=False)
        try:
            predicted = float(d.get("predicted_blocks", 0.0) or 0.0)
        except (TypeError, ValueError):
            predicted = 0.0
        try:
            staleness = float(d.get("staleness_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            staleness = 0.0
        return cls(
            traceparent=d.get("traceparent", "") or "",
            chosen_pod=d.get("chosen_pod", "") or "",
            predicted_blocks=predicted,
            total_blocks=int(d.get("total_blocks", 0) or 0),
            scores=dict(d.get("scores", {})),
            residency=dict(d.get("residency", {})),
            staleness_s=staleness,
        )


class IndexerService:
    """Assembles indexer + event pool + subscribers; serves GetPodScores."""

    def __init__(
        self,
        indexer_config: Optional[IndexerConfig] = None,
        pool_config: Optional[PoolConfig] = None,
        tokenize: Optional[Callable[[str, str], Sequence[int]]] = None,
    ):
        """``tokenize(prompt, model_name) -> token_ids`` backs the protobuf
        prompt-scoring surface (the reference tokenizes via its UDS
        tokenizer pool; wire ``TokenizationPool.tokenize`` here)."""
        self.indexer = Indexer(indexer_config)
        self.tokenize = tokenize
        self.pool_config = pool_config or PoolConfig()
        # Sharded control plane (cluster/): when this replica has a shard
        # identity, ingestion goes through a ShardFilterIndex so the full
        # broadcast event stream is filtered to the keys this shard owns.
        # Scoring/lookup still read the inner index directly (the filter
        # only gates writes), and snapshots/journal/recovery see only
        # owned state, so a restart rebuilds exactly this shard's range.
        self.shard_index = None
        cc = self.indexer.config.cluster_config
        if cc is not None and cc.enabled and cc.shard_id:
            from ..cluster.sharded_index import ShardFilterIndex

            self.shard_index = ShardFilterIndex(
                self.indexer.kv_block_index,
                cc.build_ring(),
                cc.shard_id,
                replication_factor=cc.replication_factor,
            )
        # Epoch-fenced membership (cluster.membership): the pod's view of
        # the fleet topology epoch plus its own lease. Score/lookup
        # requests are fenced against it and the event pool consults it
        # before accepting writes; fenceMode decides reject vs flag.
        self.membership = None
        if cc is not None and cc.enabled:
            from ..cluster.membership import MembershipTable

            self.membership = MembershipTable.from_cluster_config(cc)
        self.pool = Pool(
            self.pool_config,
            self.shard_index or self.indexer.kv_block_index,
            self.indexer.token_processor,
        )
        if self.membership is not None:
            self.pool.attach_membership(self.membership)
        self.subscriber_manager = SubscriberManager(
            self.pool.add_task, topic_filter=self.pool_config.topic_filter
        )
        self._central_subscriber: Optional[ZMQSubscriber] = None
        self._observability_servers: list[AdminServer] = []
        # Hit/miss/evict attribution flows from the scorer into the same
        # ledger the event pool feeds store/evict events, giving one
        # per-pod cache-efficiency view (/debug/ledger).
        self.pool.ledger = self.indexer.ledger
        # Hybrid-aware scoring reads the pool's learned group catalog
        # (no-op for the default longest-prefix strategy).
        self.indexer.attach_group_catalog(self.pool.group_catalog)
        # Degraded-mode scoring: pods whose event stream went silent are
        # demoted, then dropped (resilience.liveness). None when the pool's
        # liveness knobs are disabled.
        if self.pool.liveness is not None:
            self.indexer.attach_liveness(self.pool.liveness)
        # Crash-tolerant state (recovery/): snapshots + journaled warm
        # restart + readiness gate, enabled by recoveryConfig.snapshotDir.
        self.recovery: Optional[RecoveryManager] = None
        rc = self.indexer.config.recovery_config
        if rc is not None and rc.enabled:
            self.recovery = RecoveryManager(
                rc, self.indexer.kv_block_index, self.pool
            )
        self._reconciler: Optional[AntiEntropyReconciler] = None
        # Always-on sampled divergence audit (recovery.reconcile.
        # DivergenceAuditor) — shares the reconciler's digest source but
        # never repairs, only measures (kvtpu_index_divergence_*).
        self._divergence_auditor: Optional[DivergenceAuditor] = None
        # Ground-truth audit ring (telemetry/audit.py): score-time
        # predictions recorded by the Indexer, exported at /debug/audit.
        # Created in start() when fleetTelemetry.audit is set.
        self.audit_log = None
        self._drain_coordinator: Optional[DrainCoordinator] = None
        # Adaptive overload shedding (resilience.shedding): serving delay
        # feeds a CoDel controller; under sustained overload low-priority
        # scoring sheds and normal-priority scoring browns out (residency
        # fold-in skipped, response flagged degraded). Disabled unless
        # shedTargetDelayS > 0.
        self.shedder: Optional[CoDelShedder] = None
        if self.indexer.config.shed_target_delay_s > 0:
            self.shedder = CoDelShedder(
                "indexer.score",
                target_delay_s=self.indexer.config.shed_target_delay_s,
                interval_s=self.indexer.config.shed_interval_s,
            )

    @property
    def shard_id(self) -> str:
        """This replica's shard identity; "" for an unsharded indexer."""
        cc = self.indexer.config.cluster_config
        return cc.shard_id if cc is not None else ""

    def _data_plane_debug(self) -> dict:
        """Native data-plane counters (``/debug/data_plane``, kvdiag):
        zero-copy ingest batches + shm-ring messages from the pool and
        the chunked native-scoring call/early-exit counters from the
        indexer, one flat view."""
        view = dict(self.pool.data_plane_debug())
        view.update(self.indexer.data_plane_debug())
        return view

    @property
    def process_name(self) -> str:
        """Span attribution identity: an explicitly configured fleet
        process identity wins over the shard id, so an unsharded pod
        launched with --process-identity groups consistently in the
        collector's critical-path view."""
        ft = self.indexer.config.fleet_telemetry
        if ft is not None and ft.process_identity:
            return ft.process_identity
        return self.shard_id or "indexer"

    def attach_peer_digest_source(self) -> None:
        """Cross-replica anti-entropy: reconcile the locally-owned key
        range against the union of the other replicas' advertised views
        (cluster.remote.RemoteShardDigestSource). A restarted shard calls
        this after snapshot bootstrap so residual event loss converges."""
        cc = self.indexer.config.cluster_config
        if cc is None or not cc.enabled or not cc.shard_id:
            raise RuntimeError(
                "peer reconciliation needs clusterConfig.shardId"
            )
        from ..cluster.remote import RemoteShardDigestSource, ShardClient

        peers = [
            ShardClient(cc.address_of(sid), timeout_s=cc.fanout_timeout_s)
            for sid in cc.membership()
            if sid != cc.shard_id
        ]
        self.attach_digest_source(
            RemoteShardDigestSource(
                peers,
                cc.build_ring(),
                cc.shard_id,
                replication_factor=cc.replication_factor,
            )
        )

    def attach_digest_source(self, source: DigestSource) -> None:
        """Enable anti-entropy reconciliation against ``source`` (a pod's
        advertised truth, or a reference index via IndexDigestSource).
        Runs on the recoveryConfig.reconcileIntervalS cadence once the
        service starts; 0 keeps it manual (``reconcile_now``)."""
        rc = self.indexer.config.recovery_config
        interval = rc.reconcile_interval_s if rc is not None else 0.0
        self._reconciler = AntiEntropyReconciler(
            self.indexer.kv_block_index, source, interval_s=interval
        )
        # The continuous divergence auditor shares the same digest source
        # but is repair-free: it measures phantom/ghost block counts and
        # divergence age so the index_divergence SLI sees drift the
        # reconciler hasn't (or can't) repair yet.
        self._divergence_auditor = DivergenceAuditor(
            self.indexer.kv_block_index,
            source,
            interval_s=(rc.divergence_audit_interval_s
                        if rc is not None else 0.0),
            sample=(rc.divergence_audit_sample if rc is not None else 1.0),
        )

    def reconcile_now(self) -> dict:
        """One manual anti-entropy round (admin/testing aid)."""
        if self._reconciler is None:
            raise RuntimeError("no digest source attached (attach_digest_source)")
        return self._reconciler.reconcile_once()

    def audit_now(self) -> dict:
        """One manual divergence-audit round (admin/testing aid) —
        digest compare without repair, emitting the
        kvtpu_index_divergence_* families."""
        if self._divergence_auditor is None:
            raise RuntimeError("no digest source attached (attach_digest_source)")
        return self._divergence_auditor.audit_once()

    def start(self) -> None:
        """Start the event plane: workers plus, in centralized mode, a
        bound subscriber every engine connects to."""
        # Warm restart strictly precedes live intake so replayed journal
        # records are ordered ahead of (and never re-journaled with) live
        # traffic; the readiness gate then holds scores degraded until the
        # staleness estimate clears warmupStalenessBoundS.
        if self.recovery is not None:
            self.recovery.warm_restart()
        self.pool.start()
        if self.pool_config.zmq_endpoint:
            self._central_subscriber = ZMQSubscriber(
                self.pool_config.zmq_endpoint,
                self.pool_config.topic_filter,
                self.pool.add_task,
                bind=True,
            )
            self._central_subscriber.start()
        # Failpoint trips land in the flight recorder so chaos runs leave
        # a reconstructable decision trail.
        attach_failpoint_listener()
        providers = {
            "lag": self.pool.lag_stats,
            "ledger": self.indexer.ledger.snapshot,
            "data_plane": self._data_plane_debug,
        }
        # Ledger counters double as kvtpu_cache_ledger_* families on
        # /metrics (scrape-time snapshot — nothing added to hot paths).
        try:
            from ..metrics.collector import register_cache_ledger

            register_cache_ledger(self.indexer.ledger.snapshot)
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
        if self.shard_index is not None:
            providers["shard"] = self.shard_index.debug_view
        if self.membership is not None:
            providers["membership"] = self.membership.debug_view
        if self.shedder is not None:
            providers["shed"] = self.shedder.stats
        health = None
        if self.recovery is not None:
            self.recovery.start()
            providers["recovery"] = self.recovery.health
            health = self.recovery.health
        if self._reconciler is not None and self._reconciler.interval_s > 0:
            self._reconciler.start()
        if (self._divergence_auditor is not None
                and self._divergence_auditor.interval_s > 0):
            self._divergence_auditor.start()
        if self._divergence_auditor is not None:
            providers["divergence_audit"] = self._divergence_auditor.debug_view
        self._observability_servers = start_observability_servers(
            self.indexer.config.metrics_port,
            self.indexer.config.admin_port,
            host=self.indexer.config.admin_host,
            providers=providers,
            health=health,
        )
        # Fleet span export: /debug/spans on every admin server, backed by
        # the (shared) recording ring exporter. The collector pulls from
        # here to assemble cross-process traces.
        ft = self.indexer.config.fleet_telemetry
        if ft is not None:
            from ..telemetry.fleet import enable_pyprof, enable_span_export

            source = enable_span_export(
                ft, default_identity=self.process_name)
            if source is not None:
                for server in self._observability_servers:
                    server.register_spans_source(source)
            # Continuous profiling: /debug/pyprof (windowed pull) and
            # /debug/pyprof/capture (burst) on the same admin servers.
            pyprof = enable_pyprof(ft, default_identity=self.process_name)
            if pyprof is not None:
                prof_source, prof_capture = pyprof
                for server in self._observability_servers:
                    server.register_pyprof_source(prof_source)
                    server.register_pyprof_capture(prof_capture)
            # Working-set analytics: the tracker taps the score path and
            # exports reuse windows at /debug/workingset (same cursor
            # contract) for the collector's what-if capacity table.
            from ..telemetry.fleet import enable_workingset

            tracker = enable_workingset(
                ft, default_identity=self.process_name)
            if tracker is not None:
                self.indexer.attach_workingset(tracker)
                for server in self._observability_servers:
                    server.register_workingset_source(tracker.export_since)
                    server.register_debug("workingset_state",
                                          tracker.debug_view)
            # Ground-truth audit: the Indexer records every score decision
            # (prediction + staleness at score time) into a ring exported
            # at /debug/audit; the collector joins these against engine
            # outcomes for score-vs-reality calibration.
            if ft.audit:
                from ..telemetry.audit import AuditLog

                self.audit_log = AuditLog(
                    capacity=ft.audit_max_records,
                    staleness_fn=self.pool.index_staleness_s,
                )
                self.indexer.attach_audit(self.audit_log)
                for server in self._observability_servers:
                    server.register_audit_source(self.audit_log.export_since)
                    server.register_debug("audit_state",
                                          self.audit_log.debug_view)

    def stop(self) -> None:
        for server in self._observability_servers:
            server.stop()
        self._observability_servers = []
        if self._central_subscriber is not None:
            self._central_subscriber.stop()
        if self._reconciler is not None:
            self._reconciler.stop()
        if self._divergence_auditor is not None:
            self._divergence_auditor.stop()
        self.subscriber_manager.shutdown()
        if self.recovery is not None:
            # Final snapshot happens before the pool stops so lag_stats
            # still reflects the fully-ingested watermarks.
            self.pool.join()
            self.recovery.stop(final_snapshot=True)
        self.pool.shutdown()

    # -- graceful drain ---------------------------------------------------

    def drain(self, offload=None, on_complete: Optional[Callable[[], None]] = None) -> dict:
        """Run the deadline-bounded graceful drain (recovery.drain):
        stop intake, drain queues, flush ``offload`` (an OffloadHandlers,
        optional), final snapshot. Returns the step report."""
        rc = self.indexer.config.recovery_config
        deadline = rc.drain_deadline_s if rc is not None else 10.0
        coordinator = self._drain_coordinator
        if coordinator is None:
            stoppers = [self.subscriber_manager.shutdown]
            if self._central_subscriber is not None:
                stoppers.append(self._central_subscriber.stop)
            if self._reconciler is not None:
                stoppers.append(self._reconciler.stop)
            if self._divergence_auditor is not None:
                stoppers.append(self._divergence_auditor.stop)
            coordinator = self._drain_coordinator = DrainCoordinator(
                deadline_s=deadline,
                intake_stoppers=stoppers,
                pool=self.pool,
                offload=offload,
                manager=self.recovery,
                on_complete=on_complete,
            )
        return coordinator.drain()

    def install_drain_handler(self, offload=None,
                              on_complete: Optional[Callable[[], None]] = None) -> DrainCoordinator:
        """Install a SIGTERM handler running :meth:`drain`. Call from the
        main thread before serving."""
        rc = self.indexer.config.recovery_config
        deadline = rc.drain_deadline_s if rc is not None else 10.0
        stoppers = [self.subscriber_manager.shutdown]
        if self._central_subscriber is not None:
            stoppers.append(self._central_subscriber.stop)
        if self._reconciler is not None:
            stoppers.append(self._reconciler.stop)
        self._drain_coordinator = DrainCoordinator(
            deadline_s=deadline,
            intake_stoppers=stoppers,
            pool=self.pool,
            offload=offload,
            manager=self.recovery,
            on_complete=on_complete,
        )
        self._drain_coordinator.install()
        return self._drain_coordinator

    # -- RPC --

    def _epoch_stamp(self) -> int:
        """This pod's topology epoch for response piggybacking (0 when
        the membership plane is off — absent-field tolerant)."""
        return int(self.membership.epoch) if self.membership is not None else 0

    def _shed_response(self, reason: str, error: str = "") -> ScoreResponse:
        return ScoreResponse(
            error=error, degraded=True, degraded_reason=reason,
            traceparent=current_traceparent() or "", shard=self.shard_id,
            epoch=self._epoch_stamp(),
        )

    def _record_shed(self, site: str, outcome: str, priority: int) -> None:
        try:
            from ..metrics.collector import record_shed

            record_shed(site, outcome)
        except Exception:  # pragma: no cover - metrics must never break serving  # lint: allow-swallow
            pass
        record_event(KIND_SHED, {
            "site": site, "outcome": outcome, "priority": priority,
        })

    def get_pod_scores(self, req: ScoreRequest, context=None) -> ScoreResponse:
        # Server-side half of the W3C hop: parent under the scheduler's
        # traceparent metadata when present (ambient trace context then
        # flows into the score_tokens child span). The request deadline
        # (wire field first, gRPC metadata as fallback) becomes ambient
        # the same way, so every blocking site below consumes it.
        deadline = (Deadline.from_wire_ms(req.deadline_ms)
                    or extract_deadline(context))
        served_at = time.monotonic()
        with tracer().span(
            "llm_d.kv_cache.indexer.GetPodScores",
            parent_traceparent=extract_traceparent(context),
            model=req.model_name,
            tokens=len(req.tokens),
            role=req.role,
            process=self.process_name,
        ), deadline_scope(deadline) as dl:
            try:
                if dl is not None and dl.expired():
                    # Expired before any work: shed, never serve late.
                    self._record_shed("indexer.score", "deadline", req.priority)
                    return self._shed_response(
                        "deadline", error="deadline expired before scoring"
                    )
                if self.membership is not None:
                    # Epoch fence: learn a newer stamp, reject (or flag,
                    # per fenceMode) a stale one — a router still scoring
                    # against a retired ring plan must re-learn topology,
                    # not route on answers sliced for the old placement.
                    fence = self.membership.check_request(req.epoch, "score")
                    if not fence.allowed:
                        return self._shed_response(
                            "fenced",
                            error=f"stale topology epoch {req.epoch} "
                                  f"(fleet at {fence.epoch})",
                        )
                role = req.role
                brownout = False
                if self.shedder is not None:
                    decision = self.shedder.admit(req.priority)
                    if decision == SHED:
                        self._record_shed("indexer.score", SHED, req.priority)
                        return self._shed_response(
                            "shed", error="overload shed"
                        )
                    if decision == BROWNOUT:
                        # Brownout: serve the cheap role-agnostic score —
                        # residency fold-in skipped — flagged degraded.
                        self._record_shed("indexer.score", BROWNOUT, req.priority)
                        brownout = True
                        role = ""
                detail: dict = {}
                scores = self.indexer.score_tokens(
                    req.tokens,
                    req.model_name,
                    set(req.pod_identifiers) if req.pod_identifiers else None,
                    role=role,
                    detail=detail,
                )
                # During post-restart warmup, serve best-effort scores but
                # flag them so routers widen their fallback (the wire field
                # decodes to False against older peers).
                degraded = self.recovery is not None and not self.recovery.ready
                reason = "warmup" if degraded else ""
                if brownout:
                    degraded, reason = True, "brownout"
                if dl is not None and dl.expired():
                    # Finished past the budget: still answer (the work is
                    # done), but flagged — callers see it was late.
                    degraded, reason = True, "deadline"
                    self._record_shed("indexer.score", "late", req.priority)
                # Score→serve trace continuity: hand the scheduler this
                # span's traceparent so the chosen engine's spans join the
                # trace ("" when no tracer is active).
                return ScoreResponse(scores=scores, degraded=degraded,
                                     traceparent=current_traceparent() or "",
                                     shard=self.shard_id,
                                     residency=detail.get("residency", {}),
                                     degraded_reason=reason,
                                     epoch=self._epoch_stamp())
            except DeadlineExceeded as e:
                self._record_shed("indexer.score", "deadline", req.priority)
                return self._shed_response("deadline", error=str(e))
            except Exception as e:
                logger.exception("GetPodScores failed")
                return ScoreResponse(error=str(e))
            finally:
                if self.shedder is not None:
                    self.shedder.observe_delay(time.monotonic() - served_at)

    # -- shard surface (cluster/) --
    #
    # Raw dict-in/dict-out msgpack RPCs the scatter-gather router and
    # replica peers speak. Lookup answers from the local index only (the
    # caller owns routing/merging); the repair trio exposes the same
    # digest-first views IndexDigestSource derives from ``dump_state``.

    def lookup_blocks_rpc(self, req: dict, context=None) -> dict:
        # Gray-failure injection site: a delay-mode failpoint here turns
        # this replica into a slow-not-dead shard. The shard-suffixed
        # variant slows ONE replica of an in-process fleet.
        failpoints.hit(FP_SHARD_LOOKUP)
        if self.shard_id:
            failpoints.hit(f"{FP_SHARD_LOOKUP}.{self.shard_id}")
        keys = [int(k) for k in req.get("keys", [])]
        pods = req.get("pods") or []
        deadline = Deadline.from_wire_ms(req.get("deadline_ms"))
        with tracer().span(
            "llm_d.kv_cache.indexer.LookupBlocks",
            parent_traceparent=extract_traceparent(context),
            keys=len(keys),
            process=self.process_name,
        ):
            if deadline is not None and deadline.expired():
                # The budget died in flight (or in the queue): answer
                # empty-but-flagged instead of doing work nobody can use.
                self._record_shed("indexer.lookup", "deadline",
                                  PRIORITY_NORMAL)
                return {"hits": [], "degraded": True,
                        "shard": self.shard_id,
                        "degraded_reason": "deadline",
                        "epoch": self._epoch_stamp()}
            if self.membership is not None:
                fence = self.membership.check_request(
                    int(req.get("epoch", 0) or 0), "shard.lookup")
                if not fence.allowed:
                    # Epoch-fenced: empty-but-flagged, carrying our newer
                    # epoch so the stale caller learns and re-plans.
                    return {"hits": [], "degraded": True,
                            "shard": self.shard_id,
                            "degraded_reason": "fenced",
                            "epoch": self._epoch_stamp()}
            hits: list = []
            if keys:
                found = self.indexer.kv_block_index.lookup(
                    keys, set(pods) if pods else None
                )
                hits = [
                    [int(k), [_row_from_entry(e) for e in entries]]
                    for k, entries in found.items()
                ]
            degraded = self.recovery is not None and not self.recovery.ready
            return {"hits": hits, "degraded": degraded,
                    "shard": self.shard_id, "epoch": self._epoch_stamp()}

    def lookup_blocks_batch_rpc(self, req: dict, context=None) -> dict:
        """Framed multi-chunk lookup: the batched fan-out data plane.

        ``{"chunks": [[keys...], ...], "pods": [...], "deadline_ms": int,
        "hedge": bool}`` in; ``{"chunks": [hits_list, ...], "cont":
        [0|1, ...], "degraded": bool, "shard": str}`` out, where
        ``chunks[i]`` is chunk *i*'s hit list in the LookupBlocks row
        layout and ``cont[i]`` says every requested key of chunk *i* was
        found on this shard. Chunks are answered in order and the scan
        stops at the first incomplete one — a key missing on its owning
        shard is a global miss, so later chunks cannot extend any
        consecutive-from-0 prefix (the server-side half of the router's
        early exit). Tolerant both directions: a flat ``keys`` frame from
        an older peer is treated as one chunk, and newer response fields
        are ignored by older clients.
        """
        failpoints.hit(FP_SHARD_LOOKUP)
        if self.shard_id:
            failpoints.hit(f"{FP_SHARD_LOOKUP}.{self.shard_id}")
        raw_chunks = req.get("chunks") or []
        if not raw_chunks and req.get("keys"):
            raw_chunks = [req.get("keys")]
        pods = req.get("pods") or []
        deadline = Deadline.from_wire_ms(req.get("deadline_ms"))
        with tracer().span(
            "llm_d.kv_cache.indexer.LookupBlocksBatch",
            parent_traceparent=extract_traceparent(context),
            chunks=len(raw_chunks),
            process=self.process_name,
        ):
            if deadline is not None and deadline.expired():
                self._record_shed("indexer.lookup", "deadline",
                                  PRIORITY_NORMAL)
                return {"chunks": [], "cont": [], "degraded": True,
                        "shard": self.shard_id,
                        "degraded_reason": "deadline",
                        "epoch": self._epoch_stamp()}
            if self.membership is not None:
                fence = self.membership.check_request(
                    int(req.get("epoch", 0) or 0), "shard.lookup")
                if not fence.allowed:
                    return {"chunks": [], "cont": [], "degraded": True,
                            "shard": self.shard_id,
                            "degraded_reason": "fenced",
                            "epoch": self._epoch_stamp()}
            podset = set(pods) if pods else None
            out_chunks: list = []
            cont: list = []
            for ckeys in raw_chunks:
                keys = [int(k) for k in ckeys]
                found = (self.indexer.kv_block_index.lookup(keys, podset)
                         if keys else {})
                out_chunks.append([
                    [int(k), [_row_from_entry(e) for e in entries]]
                    for k, entries in found.items()
                ])
                complete = len(found) == len(keys)
                cont.append(1 if complete else 0)
                if not complete:
                    break
            degraded = self.recovery is not None and not self.recovery.ready
            return {"chunks": out_chunks, "cont": cont,
                    "degraded": degraded, "shard": self.shard_id,
                    "epoch": self._epoch_stamp()}

    def list_pods_rpc(self, req: dict, context=None) -> dict:
        return {
            "pods": IndexDigestSource(self.indexer.kv_block_index).pods(),
            "shard": self.shard_id,
        }

    def pod_digest_rpc(self, req: dict, context=None) -> dict:
        state = self.indexer.kv_block_index.dump_state()
        d = digest_from_blocks(pod_blocks_from_state(state, req.get("pod", "")))
        d["shard"] = self.shard_id
        return d

    def pod_blocks_rpc(self, req: dict, context=None) -> dict:
        state = self.indexer.kv_block_index.dump_state()
        blocks = pod_blocks_from_state(state, req.get("pod", ""))
        return {
            "blocks": [
                [int(k), [list(r) for r in sorted(rows)]]
                for k, rows in blocks.items()
            ],
            "shard": self.shard_id,
        }

    def get_pod_scores_pb(self, req, ctx):
        """Protobuf surface: prompt in, tokenize server-side, score.

        Mirrors the reference's service wrapper
        (``examples/kv_cache_index_service/server/server.go:42-65``): errors
        surface as gRPC status codes — the proto response has no error
        field. Scores are emitted highest-first for deterministic wires.
        """
        from .indexerpb import indexer_pb2
        if self.tokenize is None:
            ctx.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "prompt scoring needs a tokenizer; configure "
                "IndexerService(tokenize=...) or use the token-ID surface "
                f"({SERVICE_NAME})",
            )
        try:
            with tracer().span(
                "llm_d.kv_cache.indexer.GetPodScores",
                parent_traceparent=extract_traceparent(ctx),
                model=req.model_name,
                wire="protobuf",
                process=self.process_name,
            ):
                tokens = list(self.tokenize(req.prompt, req.model_name))
                scores = self.indexer.score_tokens(
                    tokens,
                    req.model_name,
                    set(req.pod_identifiers) if req.pod_identifiers else None,
                )
        except Exception as e:
            logger.exception("GetPodScores (pb) failed")
            ctx.abort(grpc.StatusCode.INTERNAL, str(e))
        resp = indexer_pb2.GetPodScoresResponse()
        for pod, score in sorted(scores.items(), key=lambda kv: (-kv[1], kv[0])):
            resp.scores.add(pod=pod, score=score)
        return resp


def serve(
    address: str,
    service: IndexerService,
    max_workers: int = 16,
) -> grpc.Server:
    """Serve GetPodScores on ``address`` (host:port or unix:path), on both
    the msgpack (token IDs) and protobuf (prompt) wires."""
    def _dict_handler(method):
        return grpc.unary_unary_rpc_method_handler(
            method,
            request_deserializer=_unpack_dict,
            response_serializer=_pack_dict,
        )

    handler = grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            "GetPodScores": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: service.get_pod_scores(req, ctx),
                request_deserializer=ScoreRequest.from_bytes,
                response_serializer=lambda r: r.to_bytes(),
            ),
            # Shard surface (cluster/): scatter-gather lookup + the
            # anti-entropy repair trio, all raw msgpack dicts.
            "LookupBlocks": _dict_handler(
                lambda req, ctx: service.lookup_blocks_rpc(req, ctx)
            ),
            "LookupBlocksBatch": _dict_handler(
                lambda req, ctx: service.lookup_blocks_batch_rpc(req, ctx)
            ),
            "ListPods": _dict_handler(
                lambda req, ctx: service.list_pods_rpc(req, ctx)
            ),
            "GetPodDigest": _dict_handler(
                lambda req, ctx: service.pod_digest_rpc(req, ctx)
            ),
            "GetPodBlocks": _dict_handler(
                lambda req, ctx: service.pod_blocks_rpc(req, ctx)
            ),
        },
    )
    from .indexerpb import indexer_pb2

    pb_handler = grpc.method_handlers_generic_handler(
        PROTO_SERVICE_NAME,
        {
            "GetPodScores": grpc.unary_unary_rpc_method_handler(
                service.get_pod_scores_pb,
                request_deserializer=indexer_pb2.GetPodScoresRequest.FromString,
                response_serializer=indexer_pb2.GetPodScoresResponse.SerializeToString,
            )
        },
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((handler, pb_handler))
    server.add_insecure_port(grpc_target(address))
    server.start()
    logger.info("indexer service on %s", address)
    return server


class IndexerServiceClient:
    """Scheduler-side client for GetPodScores."""

    def __init__(self, address: str, timeout_s: float = 5.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 membership=None):
        # Shared refcounted channel (services.channel_pool): constructing
        # many clients against the same indexer no longer pays per-client
        # TCP+HTTP/2 setup.
        self.address = address
        self._channel = channel_pool.acquire(address)
        self._timeout = timeout_s
        self.retry_policy = retry_policy or DEFAULT_RPC_RETRY_POLICY
        # Optional cluster.membership.MembershipTable: requests get
        # stamped with the caller's topology epoch and a newer epoch on
        # the response is learned (piggyback gossip).
        self.membership = membership
        self._get_pod_scores = self._channel.unary_unary(
            f"/{SERVICE_NAME}/GetPodScores",
            request_serializer=lambda r: r.to_bytes(),
            response_deserializer=ScoreResponse.from_bytes,
        )

    def get_pod_scores(
        self,
        tokens: list[int],
        model_name: str,
        pod_identifiers: Optional[list[str]] = None,
    ) -> dict[str, float]:
        return self.score(tokens, model_name, pod_identifiers).scores

    def score(
        self,
        tokens: list[int],
        model_name: str,
        pod_identifiers: Optional[list[str]] = None,
        role: str = "",
        priority: int = PRIORITY_NORMAL,
    ) -> ScoreResponse:
        """Full-response variant of :meth:`get_pod_scores`: carries the
        ``degraded`` flag and the scorer's ``traceparent`` (hand the
        latter to the chosen engine's ``enqueue`` for score→serve trace
        continuity). ``role`` targets disaggregated scoring ("decode"
        adds transferred-prefix residency bonuses on the server). The
        ambient deadline (resilience.deadline.deadline_scope) rides the
        request as ``deadline_ms``; ``priority`` feeds the server's
        overload shedder."""
        dl = current_deadline()
        resp = _call_rpc(
            self._get_pod_scores,
            ScoreRequest(
                tokens=list(tokens),
                model_name=model_name,
                pod_identifiers=list(pod_identifiers or []),
                role=role,
                deadline_ms=dl.to_wire_ms() if dl is not None else 0,
                priority=priority,
                epoch=(int(self.membership.epoch)
                       if self.membership is not None else 0),
            ),
            self._timeout,
            self.retry_policy,
        )
        if self.membership is not None and resp.epoch:
            self.membership.observe_epoch(resp.epoch,
                                          source=f"score:{self.address}")
        if resp.error:
            raise RuntimeError(f"GetPodScores failed: {resp.error}")
        return resp

    def close(self) -> None:
        channel_pool.release(self.address)


class IndexerPbClient:
    """Client for the reference protobuf wire (what a Go EPP speaks).

    Exercises the exact method path ``/indexer.v1.IndexerService/
    GetPodScores`` with protobuf-serialized messages, so a round trip here
    proves wire compatibility with clients generated from
    ``api/indexerpb/indexer.proto``.
    """

    def __init__(self, address: str, timeout_s: float = 5.0,
                 retry_policy: Optional[RetryPolicy] = None):
        from .indexerpb import indexer_pb2

        self._pb = indexer_pb2
        self.address = address
        self._channel = channel_pool.acquire(address)
        self._timeout = timeout_s
        self.retry_policy = retry_policy or DEFAULT_RPC_RETRY_POLICY
        self._get_pod_scores = self._channel.unary_unary(
            f"/{PROTO_SERVICE_NAME}/GetPodScores",
            request_serializer=indexer_pb2.GetPodScoresRequest.SerializeToString,
            response_deserializer=indexer_pb2.GetPodScoresResponse.FromString,
        )

    def get_pod_scores(
        self,
        prompt: str,
        model_name: str,
        pod_identifiers: Optional[list[str]] = None,
    ) -> dict[str, float]:
        resp = _call_rpc(
            self._get_pod_scores,
            self._pb.GetPodScoresRequest(
                prompt=prompt,
                model_name=model_name,
                pod_identifiers=list(pod_identifiers or []),
            ),
            self._timeout,
            self.retry_policy,
        )
        return {s.pod: s.score for s in resp.scores}

    def close(self) -> None:
        channel_pool.release(self.address)
