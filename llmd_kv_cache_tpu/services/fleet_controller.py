"""Fleet controller service: the control loop as a deployable sidecar.

Wires the ``control/`` subsystem to *remote* planes over HTTP:

- **senses** — :class:`RemoteSignalSource` polls the telemetry
  collector's admin endpoints (``/debug/slo`` level + ``?since=`` edge
  cursor, ``/debug/traces`` critical paths, ``/debug/workingset``
  what-if table) and each engine pod's ``/debug/role``.
- **hands** — :class:`~..control.actions.AdminPlaneActuator` POSTs
  ``/debug/role?set=`` and ``/debug/drain`` to pod admin planes; shard
  membership changes go through injected deployment hooks (the ring is
  rebuilt from the membership list, PR 6).
- **its own admin plane** — ``/debug/controller`` (the controller's
  debug view: last actions with causing signals, cooldown state, dry-run
  would-have-acted records) for ``kvdiag --fleet``.

Every remote read degrades to an empty signal rather than killing the
round: a controller that cannot see must hold still, not crash — the
cooldowns and hysteresis make "no signal" a safe no-op.
"""

from __future__ import annotations

import json
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..control.actions import AdminPlaneActuator
from ..control.config import ControllerConfig
from ..control.controller import FleetController
from ..control.signals import FleetSignals
from ..utils.logging import get_logger
from .admin import AdminServer

logger = get_logger("services.fleet_controller")


@dataclass(frozen=True)
class FleetControllerServiceConfig:
    """Service-level knobs around the ``controllerConfig`` policy block."""

    # host:port of the telemetry collector's admin plane.
    collector_address: str = ""
    # pod id -> host:port of that pod's admin plane (role reads + POSTs).
    pod_admin: Dict[str, str] = field(default_factory=dict)
    # This service's own admin endpoint (0 = off).
    admin_port: int = 0
    host: str = "127.0.0.1"
    http_timeout_s: float = 5.0
    controller: ControllerConfig = field(default_factory=ControllerConfig)

    @classmethod
    def from_dict(cls, data: Optional[dict]) -> "FleetControllerServiceConfig":
        if not data:
            return cls()

        def k(camel: str, snake: str, default):
            if camel in data:
                return data[camel]
            if snake in data:
                return data[snake]
            return default

        d = cls()
        return cls(
            collector_address=str(
                k("collectorAddress", "collector_address",
                  d.collector_address)),
            pod_admin=dict(k("podAdmin", "pod_admin", {})),
            admin_port=int(k("adminPort", "admin_port", d.admin_port)),
            host=str(k("host", "host", d.host)),
            http_timeout_s=float(
                k("httpTimeoutS", "http_timeout_s", d.http_timeout_s)),
            controller=ControllerConfig.from_dict(
                k("controllerConfig", "controller", None)),
        )


def _get_json(address: str, path: str, timeout_s: float) -> Optional[dict]:
    try:
        with urllib.request.urlopen(
                f"http://{address}{path}", timeout=timeout_s) as resp:
            payload = json.loads(resp.read() or b"{}")
        return payload if isinstance(payload, dict) else None
    except Exception as exc:  # degraded sense, not a crash  # lint: allow-swallow
        logger.debug("fleet controller: GET %s%s failed: %r",
                     address, path, exc)
        return None


class RemoteSignalSource:
    """HTTP counterpart of :class:`~..control.signals.CollectorSignalSource`."""

    def __init__(
        self,
        collector_address: str,
        pod_admin: Optional[Dict[str, str]] = None,
        shards: Optional[Callable[[], List[str]]] = None,
        timeout_s: float = 5.0,
        clock: Callable[[], float] = time.time,
    ):
        self.collector_address = collector_address
        self.pod_admin = dict(pod_admin or {})
        self._shards = shards or (lambda: [])
        self.timeout_s = timeout_s
        self._clock = clock
        self._edge_cursor = -1
        self.fetch_errors = 0

    def _get(self, address: str, path: str) -> Optional[dict]:
        payload = _get_json(address, path, self.timeout_s)
        if payload is None:
            self.fetch_errors += 1
        return payload

    def poll(self) -> FleetSignals:
        slo_state: Dict[str, dict] = {}
        edges: tuple = ()
        dominant: dict = {}
        whatif: tuple = ()
        audit: dict = {}
        if self.collector_address:
            level = self._get(self.collector_address, "/debug/slo") or {}
            for name, st in level.items():
                if not isinstance(st, dict):
                    continue
                burns = st.get("burn_rates") or {}
                # Insertion order is short, confirm, slow (slo.debug_view).
                slow = list(burns.values())[-1] if burns else 0.0
                slo_state[name] = {
                    "severity": (st.get("alert") or {}).get("severity"),
                    "burn_slow": float(slow),
                }
            edge_payload = self._get(
                self.collector_address,
                f"/debug/slo?since={self._edge_cursor}") or {}
            edges = tuple(edge_payload.get("edges") or ())
            self._edge_cursor = int(
                edge_payload.get("next_seq", self._edge_cursor))
            traces = self._get(self.collector_address, "/debug/traces") or {}
            best = 0.0
            for summary in traces.get("retained") or ():
                for seg in summary.get("critical_path") or ():
                    if float(seg.get("self_time_s", 0.0)) > best:
                        best = float(seg["self_time_s"])
                        dominant = {
                            "name": seg.get("name"),
                            "process": seg.get("process"),
                            "self_time_s": seg.get("self_time_s"),
                            "trace_id": summary.get("trace_id"),
                        }
            ws = self._get(self.collector_address, "/debug/workingset") or {}
            whatif = tuple(ws.get("whatif") or ())
            # The collector's /debug/audit is the joined score-vs-reality
            # view (pods serve their raw rings under the same path); an
            # older collector without it degrades to no audit signal.
            audit = self._get(self.collector_address, "/debug/audit") or {}
        roles: Dict[str, str] = {}
        handoff: dict = {}
        epoch = 0
        for pod, address in self.pod_admin.items():
            # Per-pod membership view: the max committed epoch across the
            # fleet is the controller's fence source — a warm-restarted
            # controller learns where topology actually is before acting.
            mem = self._get(address, "/debug/membership")
            if mem:
                try:
                    epoch = max(epoch, int(mem.get("epoch", 0) or 0))
                except (TypeError, ValueError):  # lint: allow-swallow (malformed epoch from one pod degrades to unstamped, not a dead poll)
                    pass
            view = self._get(address, "/debug/role")
            if not view:
                continue
            roles[pod] = str(view.get("role", ""))
            starve = view.get("starvation")
            if isinstance(starve, dict):
                # Merge per-pod mixes sample-weighted into one fleet EMA.
                mix = starve.get("mix") or {}
                frac, n = mix.get("prefill_fraction"), int(
                    mix.get("samples") or 0)
                if frac is not None and n > 0:
                    agg = handoff.setdefault(
                        "mix", {"prefill_fraction": 0.0, "samples": 0})
                    total = agg["samples"] + n
                    agg["prefill_fraction"] = (
                        agg["prefill_fraction"] * agg["samples"]
                        + float(frac) * n) / total
                    agg["samples"] = total
                for key in ("transfer_queue_depth", "in_flight_jobs"):
                    handoff[key] = handoff.get(key, 0) + int(
                        starve.get(key) or 0)
                if starve.get("starved_side"):
                    handoff["starved_side"] = starve["starved_side"]
        return FleetSignals(
            ts=self._clock(),
            slo=slo_state,
            alert_edges=edges,
            dominant_segment=dominant,
            handoff=handoff,
            whatif=whatif,
            audit=audit,
            shards=tuple(self._shards()),
            roles=roles,
            epoch=epoch,
        )


class FleetControllerService:
    """The deployable bundle: remote source + actuator + loop + admin."""

    def __init__(
        self,
        cfg: FleetControllerServiceConfig,
        shards: Optional[Callable[[], List[str]]] = None,
        add_shard: Optional[Callable[[str], object]] = None,
        remove_shard: Optional[Callable[[str], object]] = None,
        clock: Callable[[], float] = time.time,
        membership=None,
    ):
        self.cfg = cfg
        self.source = RemoteSignalSource(
            collector_address=cfg.collector_address,
            pod_admin=cfg.pod_admin,
            shards=shards,
            timeout_s=cfg.http_timeout_s,
            clock=clock,
        )
        self.actuator = AdminPlaneActuator(
            pod_addresses=cfg.pod_admin,
            add_shard=add_shard,
            remove_shard=remove_shard,
            timeout_s=cfg.http_timeout_s,
        )
        self.controller = FleetController(
            self.source, self.actuator, config=cfg.controller, clock=clock,
            membership=membership)
        self._admin: Optional[AdminServer] = None

    def start(self) -> None:
        if self.cfg.admin_port > 0 and self._admin is None:
            self._admin = AdminServer(
                port=self.cfg.admin_port, host=self.cfg.host,
                expose_debug=True)
            self._admin.register_debug(
                "controller", self.controller.debug_view)
            self._admin.start()
        self.controller.start()

    def stop(self) -> None:
        self.controller.stop()
        if self._admin is not None:
            self._admin.stop()
            self._admin = None

    @property
    def admin_port(self) -> int:
        return self._admin.port if self._admin is not None else 0
