"""Protobuf wire surface for the tokenizer sidecar.

``tokenizer_pb2`` is generated (``hack/gen_protos.sh``) from
``api/tokenizerpb/tokenizer.proto``, carried verbatim from the reference
(``api/tokenizerpb/tokenizer.proto:188-210``): the Go EPP's UDS
tokenization client is generated from this exact file, so interop
requires a byte-identical descriptor.
"""

from . import tokenizer_pb2

__all__ = ["tokenizer_pb2"]
