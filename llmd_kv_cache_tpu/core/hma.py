"""Hybrid-model-attention (HMA) group catalog.

Counterpart of reference ``pkg/kvcache/kvblock/hma.go``. Engines with hybrid
attention (sliding-window + full, Mamba mixers, MLA, ...) maintain several KV
cache groups with distinct block semantics; BlockStored events carry the
group index plus its spec. The catalog records what each pod's groups mean so
scoring can become group-aware.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..utils.lockdep import new_lock

# KV cache spec kinds as emitted by vLLM (reference pkg/kvevents/events.go:32-43).
SPEC_FULL_ATTENTION = "full_attention"
SPEC_MLA = "mla_attention"
SPEC_SLIDING_WINDOW = "sliding_window"
SPEC_SLIDING_WINDOW_MLA = "sliding_window_mla"
SPEC_MAMBA = "mamba"
SPEC_CHUNKED_LOCAL = "chunked_local_attention"
SPEC_SINK_FULL = "sink_full_attention"
SPEC_ENCODER_ONLY = "encoder_only_attention"
SPEC_CROSS = "cross_attention"
SPEC_UNKNOWN = "unknown"


@dataclass(frozen=True)
class GroupMetadata:
    """Per-group KV cache spec learned from BlockStored events."""

    kind: str
    block_size: int
    sliding_window_size: Optional[int] = None


class GroupCatalog:
    """Thread-safe per-pod catalog of KV-cache group metadata."""

    def __init__(self) -> None:
        self._lock = new_lock()
        self._entries: dict[str, dict[int, GroupMetadata]] = {}

    def learn(self, pod_id: str, group_idx: int, meta: GroupMetadata) -> None:
        with self._lock:
            self._entries.setdefault(pod_id, {})[group_idx] = meta

    def get(self, pod_id: str, group_idx: int) -> Optional[GroupMetadata]:
        with self._lock:
            groups = self._entries.get(pod_id)
            if groups is None:
                return None
            return groups.get(group_idx)

    def pods(self) -> list[str]:
        with self._lock:
            return list(self._entries.keys())

    def groups(self, pod_id: str) -> dict[int, GroupMetadata]:
        """All known groups for a pod (empty dict if none learned)."""
        with self._lock:
            return dict(self._entries.get(pod_id, {}))
