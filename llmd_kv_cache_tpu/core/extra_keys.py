"""Multimodal extra-keys: parsing and read-side recomputation.

Counterpart of reference ``pkg/kvcache/kvblock/extra_keys.go``. Multimodal
content taints block hashes: each block overlapped by an image/audio
placeholder range carries the item's content-hash identifier, so two prompts
with identical token ids but different attachments get different block keys.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


@dataclass
class BlockExtraFeatures:
    """Per-block extra data that taints the block hash.

    ``None`` (rather than an instance) means pure text / no taint.
    ``mm_hashes`` holds multimodal content-hash identifier strings
    (reference ``extra_keys.go:26-34`` wraps them in an ``MMHash`` struct
    with a single ``Hash`` field; we keep plain strings and reconstruct the
    wire shape at hash time).
    """

    mm_hashes: list[str] = field(default_factory=list)


@dataclass(frozen=True)
class PlaceholderRange:
    """A contiguous run of placeholder tokens for one multimodal item."""

    offset: int
    length: int


def parse_raw_extra_keys(
    raw: Optional[Sequence[Optional[Sequence[Any]]]],
) -> Optional[list[Optional[BlockExtraFeatures]]]:
    """Convert the wire-format ``extra_keys`` into typed per-block features.

    Mirrors reference ``extra_keys.go:49-85``. Each inner element is either
    a bare identifier string (vLLM v0.18.0+) or a legacy ``[hash, offset]``
    pair (offset ignored). Unknown entry types (LoRA ids, cache salts) are
    skipped. ``None`` inner entries produce ``None`` (text-only block).
    """
    if raw is None:
        return None

    result: list[Optional[BlockExtraFeatures]] = [None] * len(raw)
    for block_idx, block_keys in enumerate(raw):
        if block_keys is None:
            continue
        hashes: list[str] = []
        for entry in block_keys:
            if isinstance(entry, str):
                hashes.append(entry)
            elif isinstance(entry, (list, tuple)) and entry and isinstance(entry[0], str):
                hashes.append(entry[0])
            # anything else: skip
        if hashes:
            result[block_idx] = BlockExtraFeatures(mm_hashes=hashes)
    return result


def compute_block_extra_features(
    mm_hashes: dict[str, list[str]],
    mm_placeholders: dict[str, list[PlaceholderRange]],
    block_size: int,
    num_tokens: int,
) -> Optional[list[Optional[BlockExtraFeatures]]]:
    """Recompute per-block MM taint from tokenizer metadata.

    Read-side mirror of vLLM's ``_gen_mm_extra_hash_keys``: for each full
    block, emit the identifiers of every multimodal item whose placeholder
    range overlaps the block (reference ``extra_keys.go:100-163``).
    """
    if not mm_hashes or block_size <= 0 or num_tokens <= 0:
        return None

    items: list[tuple[int, int, str]] = []  # (start, end, hash)
    for modality, hashes in mm_hashes.items():
        ranges = mm_placeholders.get(modality)
        if ranges is None:
            continue
        for h, r in zip(hashes, ranges):
            items.append((r.offset, r.offset + r.length, h))

    if not items:
        return None

    items.sort(key=lambda it: it[0])

    num_blocks = num_tokens // block_size
    result: list[Optional[BlockExtraFeatures]] = [None] * num_blocks

    for block_idx in range(num_blocks):
        block_start = block_idx * block_size
        block_end = block_start + block_size
        hashes: list[str] = []
        for start, end, h in items:
            if end <= block_start:
                continue
            if start >= block_end:
                break  # items sorted by start: no further overlaps
            hashes.append(h)
        if hashes:
            result[block_idx] = BlockExtraFeatures(mm_hashes=hashes)

    return result
