"""Block-hash and pod-entry value types.

Counterparts of ``pkg/kvcache/kvblock/index.go:157-205`` in the reference.
Block hashes are plain Python ints constrained to uint64; ``0`` is the
empty/error value (``EmptyBlockHash``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

# BlockHash is represented as a plain int (uint64 range). 0 is the sentinel
# "empty" value, matching reference index.go:172-174.
BlockHash = int
EMPTY_BLOCK_HASH: BlockHash = 0

# First-class device tiers for a TPU fleet. The reference's default event
# tier is "gpu" (pkg/kvevents/pool.go:32); ours is TPU HBM. "gpu" remains a
# legal tier string for interop with GPU-emitting engines.
TIER_TPU_HBM = "tpu-hbm"
TIER_CPU = "cpu"
TIER_SHARED_STORAGE = "shared_storage"
TIER_OBJECT_STORE = "object_store"


class KeyType(enum.Enum):
    """Whether a key passed to ``Index.evict`` is engine- or request-keyed.

    Mirrors reference ``index.go:157-167``: engine keys require resolution
    through the engine→request mapping; request keys are used directly
    (speculative entries added without engine keys).
    """

    ENGINE = "engine"
    REQUEST = "request"


@dataclass(frozen=True)
class PodEntry:
    """A pod locality record for one block (reference ``index.go:181-193``).

    Frozen/hashable so it can key the per-block pod LRU. ``speculative``
    marks entries added predictively before a KV event confirmed them;
    ``group_idx`` (with ``has_group``) identifies the engine's hybrid-
    attention KV-cache group.
    """

    pod_identifier: str
    device_tier: str
    speculative: bool = False
    has_group: bool = False
    group_idx: int = 0

    def __str__(self) -> str:
        suffix = "[speculative]" if self.speculative else ""
        if self.has_group:
            suffix += f"[group={self.group_idx}]"
        return f"{self.pod_identifier}@{self.device_tier}{suffix}"
