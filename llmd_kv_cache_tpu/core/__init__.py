"""Block-key core: content-addressed KV-block hashing and per-block metadata.

Counterpart of the reference's ``pkg/kvcache/kvblock/`` block-key layer.
"""

from .keys import EMPTY_BLOCK_HASH, KeyType, PodEntry
from .token_processor import ChunkedTokenDatabase, TokenProcessorConfig
from .extra_keys import (
    BlockExtraFeatures,
    PlaceholderRange,
    compute_block_extra_features,
    parse_raw_extra_keys,
)
from .hma import GroupCatalog, GroupMetadata

__all__ = [
    "EMPTY_BLOCK_HASH",
    "KeyType",
    "PodEntry",
    "ChunkedTokenDatabase",
    "TokenProcessorConfig",
    "BlockExtraFeatures",
    "PlaceholderRange",
    "compute_block_extra_features",
    "parse_raw_extra_keys",
    "GroupCatalog",
    "GroupMetadata",
]
