"""Token → block-key hash chain.

Counterpart of reference ``pkg/kvcache/kvblock/token_processor.go``. This is
the content-addressing scheme the whole indexer rests on; it must stay
byte-compatible with the engines' own block hashing:

- tokens are chunked into fixed-size blocks (default 16); a trailing
  partial block is dropped (``token_processor.go:184-197``)
- each block's key is ``FNV-64a(canonical-CBOR([parent, chunk, extra]))``
  chained on the previous block's key (``:146-158,160-176``)
- the chain seed is ``FNV-64a(hash_seed)`` mixed with the model name via
  one extra hash step ``hash(init, None, model_name)`` (``:114-118,131-134``)
- ``hash_seed`` must align with the engines' ``PYTHONHASHSEED``-equivalent
  (``:43-47``)
- per-block multimodal extras taint the hash: ``extra`` is the block's list
  of MM identifier entries encoded as ``[{"Hash": h}, ...]`` maps, matching
  the reference's Go-struct CBOR encoding of ``[]MMHash`` (``:167-173``
  with ``extra_keys.go:26-28``); text-only blocks hash ``extra = null``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ..utils.cbor import canonical_cbor_encode
from ..utils.fnv import fnv1a_64
from .extra_keys import BlockExtraFeatures
from .keys import EMPTY_BLOCK_HASH, BlockHash

DEFAULT_BLOCK_SIZE = 16  # vLLM's default tokens-per-block


@dataclass
class TokenProcessorConfig:
    """Configuration for the token processor.

    ``block_size_tokens``: tokens per canonical block (0 → default 16).
    ``hash_seed``: seeds the chain like vLLM's NONE_HASH; deployers must
    align it across engines and indexer.
    """

    block_size_tokens: int = DEFAULT_BLOCK_SIZE
    hash_seed: str = ""

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TokenProcessorConfig":
        if not d:
            return cls()
        block_size = d.get("blockSizeTokens", d.get("block_size_tokens", 0)) or 0
        if block_size == 0:
            # deprecated alias accepted for config compatibility
            block_size = d.get("blockSize", d.get("block_size", 0)) or 0
        if block_size == 0:
            block_size = DEFAULT_BLOCK_SIZE
        return cls(
            block_size_tokens=block_size,
            hash_seed=d.get("hashSeed", d.get("hash_seed", "")) or "",
        )


class ChunkedTokenDatabase:
    """Concrete token processor implementing the chained block-hash scheme.

    Text-only blocks take a native (C++) fast path when ``csrc/kvindex``
    builds; multimodal-tainted blocks always use the Python encoder. Both
    produce identical hashes (covered by equivalence tests).
    """

    def __init__(self, config: Optional[TokenProcessorConfig] = None,
                 use_native: bool = True):
        cfg = config or TokenProcessorConfig()
        block_size = cfg.block_size_tokens or DEFAULT_BLOCK_SIZE
        if block_size <= 0:
            raise ValueError(
                f"block_size_tokens must be greater than 0, got {cfg.block_size_tokens}"
            )
        self._block_size = block_size
        self._hash_seed = cfg.hash_seed
        self._init_hash = fnv1a_64(self._hash_seed.encode("utf-8"))
        # Per-model seed cache: the init step hashes the model name into the
        # chain once; memoize since model cardinality is tiny.
        self._model_seed_cache: dict[str, int] = {}
        self._native = None
        if use_native:
            try:
                from ..index import native as _native_mod

                if _native_mod.native_available():
                    self._native = _native_mod
            except Exception:  # pragma: no cover - toolchain-less envs
                self._native = None

    @property
    def block_size(self) -> int:
        return self._block_size

    def _hash(self, parent: int, tokens: Optional[Sequence[int]], extra) -> int:
        payload = [parent, list(tokens) if tokens is not None else None, extra]
        return fnv1a_64(canonical_cbor_encode(payload))

    def _get_init_hash(self, model_name: str) -> int:
        cached = self._model_seed_cache.get(model_name)
        if cached is None:
            cached = self._hash(self._init_hash, None, model_name)
            self._model_seed_cache[model_name] = cached
        return cached

    def _chunk_tokens(self, tokens: Sequence[int]) -> list[Sequence[int]]:
        bs = self._block_size
        n_full = len(tokens) // bs
        return [tokens[i * bs:(i + 1) * bs] for i in range(n_full)]

    def tokens_to_kv_block_keys(
        self,
        parent_key: BlockHash,
        tokens: Sequence[int],
        model_name: str,
        extra_features: Optional[Sequence[Optional[BlockExtraFeatures]]] = None,
    ) -> list[BlockHash]:
        """Convert tokens into chained block keys.

        ``parent_key`` continues an existing chain (``EMPTY_BLOCK_HASH`` to
        start fresh from the model-seeded init hash). ``extra_features``, if
        given, must have exactly one entry per full token chunk.
        """
        parent = parent_key if parent_key != EMPTY_BLOCK_HASH else self._get_init_hash(model_name)

        n_chunks = len(tokens) // self._block_size
        if n_chunks == 0:
            return []

        # Native fast path: text-only chains hash in C++ (GIL-free).
        if self._native is not None and (
            extra_features is None or all(f is None for f in extra_features)
        ):
            if extra_features is not None and len(extra_features) != n_chunks:
                raise ValueError(
                    f"extra_features length {len(extra_features)} does not match "
                    f"token chunk count {n_chunks} (block_size_tokens="
                    f"{self._block_size}, tokens={len(tokens)})"
                )
            return self._native.hash_chain(parent, tokens, self._block_size)

        chunks = self._chunk_tokens(tokens)
        if not chunks:
            return []

        if extra_features is None:
            extra_features = [None] * len(chunks)
        elif len(extra_features) != len(chunks):
            raise ValueError(
                f"extra_features length {len(extra_features)} does not match token "
                f"chunk count {len(chunks)} (block_size_tokens={self._block_size}, "
                f"tokens={len(tokens)})"
            )

        keys: list[BlockHash] = []
        prefix = parent
        for chunk, features in zip(chunks, extra_features):
            extra = None
            if features is not None:
                extra = [{"Hash": h} for h in features.mm_hashes]
            prefix = self._hash(prefix, chunk, extra)
            keys.append(prefix)
        return keys


# Backwards-friendly alias matching the reference interface name.
TokenProcessor = ChunkedTokenDatabase
