"""Token → block-key hash chain.

Counterpart of reference ``pkg/kvcache/kvblock/token_processor.go``. This is
the content-addressing scheme the whole indexer rests on; it must stay
byte-compatible with the engines' own block hashing:

- tokens are chunked into fixed-size blocks (default 16); a trailing
  partial block is dropped (``token_processor.go:184-197``)
- each block's key is ``FNV-64a(canonical-CBOR([parent, chunk, extra]))``
  chained on the previous block's key (``:146-158,160-176``)
- the chain seed is ``FNV-64a(hash_seed)`` mixed with the model name via
  one extra hash step ``hash(init, None, model_name)`` (``:114-118,131-134``)
- ``hash_seed`` must align with the engines' ``PYTHONHASHSEED``-equivalent
  (``:43-47``)
- per-block multimodal extras taint the hash: ``extra`` is the block's list
  of MM identifier entries encoded as ``[{"Hash": h}, ...]`` maps, matching
  the reference's Go-struct CBOR encoding of ``[]MMHash`` (``:167-173``
  with ``extra_keys.go:26-28``); text-only blocks hash ``extra = null``.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional, Sequence

try:  # numpy backs the cached-key arrays for the native fused score path
    import numpy as _np
except Exception:  # pragma: no cover - numpy-less envs degrade gracefully
    _np = None

from ..utils.lockdep import new_lock
from ..utils.cbor import canonical_cbor_encode
from ..utils.fnv import fnv1a_64
from .extra_keys import BlockExtraFeatures
from .keys import EMPTY_BLOCK_HASH, BlockHash

DEFAULT_BLOCK_SIZE = 16  # vLLM's default tokens-per-block
# Prefix-key cache budget in *tokens* (not entries): multi-turn sessions
# re-send the same growing prefix, so ~4M tokens covers hundreds of long
# chat sessions while bounding memory at tens of MB of ints.
DEFAULT_PREFIX_CACHE_TOKENS = 4 * 2**20


@dataclass
class TokenProcessorConfig:
    """Configuration for the token processor.

    ``block_size_tokens``: tokens per canonical block (0 → default 16).
    ``hash_seed``: seeds the chain like vLLM's NONE_HASH; deployers must
    align it across engines and indexer.
    ``prefix_cache_tokens``: token budget for the incremental prefix-key
    cache (0 disables; re-hashing every block on every call).
    """

    block_size_tokens: int = DEFAULT_BLOCK_SIZE
    hash_seed: str = ""
    prefix_cache_tokens: int = DEFAULT_PREFIX_CACHE_TOKENS

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TokenProcessorConfig":
        if not d:
            return cls()
        block_size = d.get("blockSizeTokens", d.get("block_size_tokens", 0)) or 0
        if block_size == 0:
            # deprecated alias accepted for config compatibility
            block_size = d.get("blockSize", d.get("block_size", 0)) or 0
        if block_size == 0:
            block_size = DEFAULT_BLOCK_SIZE
        prefix_cache = d.get("prefixCacheTokens", d.get("prefix_cache_tokens"))
        if prefix_cache is None:
            prefix_cache = DEFAULT_PREFIX_CACHE_TOKENS
        return cls(
            block_size_tokens=block_size,
            hash_seed=d.get("hashSeed", d.get("hash_seed", "")) or "",
            prefix_cache_tokens=prefix_cache,
        )


class PrefixKeyCache:
    """Bounded LRU mapping block-aligned token-prefix fingerprints →
    chained block keys.

    Keyed by ``(resolved_parent, n_tokens, fingerprint)`` where the
    fingerprint is Python's 64-bit tuple hash of the block-aligned token
    prefix. The parent alone namespaces correctly because continuation
    block hashes depend only on the parent key and the chunk — the model
    name enters the chain solely through the EMPTY-parent init step,
    which is already folded into ``resolved_parent``. Fingerprint keying
    keeps every cache operation O(1)-ish dict probes on small int tuples
    (no token tuples are retained or compared), at the price of trusting
    a 64-bit fingerprint: a collision would return another prefix's keys.
    That is a ~2^-64 event on non-adversarial traffic — routing soft
    state, acceptable for a scheduler hint; set ``prefix_cache_tokens: 0``
    where it is not.

    Besides exact matches, a small per-parent MRU bucket of recent prefix
    fingerprints enables longest-aligned-prefix matching, so a multi-turn
    session that appends a delta only hashes the delta's blocks. Bucket
    probes pre-filter on the candidate prefix's first/last token (O(1))
    before paying an O(prefix) slice+hash verification, and at most
    ``MAX_VERIFY_PROBES`` verifications run per call so cold traffic is
    not taxed by warm sessions sharing the model seed.

    Each entry also carries the keys as a ready ``np.uint64`` array so
    the native fused score path skips its per-call ``asarray``
    conversion. Eviction is by total cached tokens (LRU order), not entry
    count; a single coarse lock guards all state.
    """

    BUCKET_LIMIT = 16  # recent prefixes tracked per parent seed
    MAX_VERIFY_PROBES = 2  # full slice+hash verifications per call

    def __init__(self, capacity_tokens: int):
        self._capacity = capacity_tokens
        self._mu = new_lock()
        # (parent, n_tokens, fp) → (keys_tuple, keys_arr)
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        # parent → MRU list of (n_tokens, fp, first_token, last_token)
        self._buckets: dict[int, list[tuple]] = {}
        self._cached_tokens = 0
        self.hits = 0  # calls that reused at least one cached block
        self.misses = 0  # calls that reused nothing
        self.hit_blocks = 0  # block keys served from cache
        self.miss_blocks = 0  # block keys that had to be hashed

    def match(self, parent: int, trimmed: tuple):
        """Find the longest cached block-aligned prefix of ``trimmed``.

        Returns ``(fp, keys_tuple, keys_arr)`` — ``fp`` is the full
        fingerprint of ``trimmed`` (reused by ``store`` so the caller
        never hashes twice), and ``keys_tuple`` covers the matched prefix
        (empty on a full miss; ``len(trimmed)``-covering on an exact hit).
        """
        fp = hash(trimmed)
        n = len(trimmed)
        with self._mu:
            exact_key = (parent, n, fp)
            exact = self._entries.get(exact_key)
            if exact is not None:
                self._entries.move_to_end(exact_key)
                return fp, exact[0], exact[1]
            bucket = self._buckets.get(parent)
            if not bucket:
                return fp, (), None
            first = trimmed[0]
            candidates = [
                row for row in bucket
                if row[0] < n and row[2] == first and row[3] == trimmed[row[0] - 1]
            ]
        # Verify outside the lock: slicing+hashing a long prefix is the
        # expensive part and needs no cache state.
        for n_tok, row_fp, _, _ in candidates[: self.MAX_VERIFY_PROBES]:
            if hash(trimmed[:n_tok]) != row_fp:
                continue
            with self._mu:
                entry = self._entries.get((parent, n_tok, row_fp))
                if entry is None:  # evicted between probe and verify
                    continue
                self._entries.move_to_end((parent, n_tok, row_fp))
                return fp, entry[0], entry[1]
        return fp, (), None

    def store(self, parent: int, trimmed_len: int, fp: int,
              keys: tuple, keys_arr, first_token: int, last_token: int) -> None:
        with self._mu:
            entry_key = (parent, trimmed_len, fp)
            if entry_key in self._entries:
                self._entries.move_to_end(entry_key)
                return
            self._entries[entry_key] = (keys, keys_arr)
            self._cached_tokens += trimmed_len
            bucket = self._buckets.setdefault(parent, [])
            bucket.insert(0, (trimmed_len, fp, first_token, last_token))
            if len(bucket) > self.BUCKET_LIMIT:
                n_tok, old_fp, _, _ = bucket.pop()
                self._drop(parent, n_tok, old_fp)
            while self._cached_tokens > self._capacity and self._entries:
                old_parent, n_tok, old_fp = next(iter(self._entries))
                bkt = self._buckets.get(old_parent)
                if bkt is not None:
                    for i, row in enumerate(bkt):
                        if row[0] == n_tok and row[1] == old_fp:
                            del bkt[i]
                            break
                    if not bkt:
                        del self._buckets[old_parent]
                self._drop(old_parent, n_tok, old_fp)

    def _drop(self, parent: int, n_tokens: int, fp: int) -> None:
        if self._entries.pop((parent, n_tokens, fp), None) is not None:
            self._cached_tokens -= n_tokens

    def note(self, matched_blocks: int, hashed_blocks: int) -> None:
        with self._mu:
            self.hit_blocks += matched_blocks
            self.miss_blocks += hashed_blocks
            if matched_blocks:
                self.hits += 1
            else:
                self.misses += 1

    def stats(self) -> dict:
        with self._mu:
            total = self.hit_blocks + self.miss_blocks
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_blocks": self.hit_blocks,
                "miss_blocks": self.miss_blocks,
                "block_hit_rate": (self.hit_blocks / total) if total else 0.0,
                "entries": len(self._entries),
                "cached_tokens": self._cached_tokens,
            }


class ChunkedTokenDatabase:
    """Concrete token processor implementing the chained block-hash scheme.

    Text-only blocks take a native (C++) fast path when ``csrc/kvindex``
    builds; multimodal-tainted blocks always use the Python encoder. Both
    produce identical hashes (covered by equivalence tests).
    """

    def __init__(self, config: Optional[TokenProcessorConfig] = None,
                 use_native: bool = True):
        cfg = config or TokenProcessorConfig()
        block_size = cfg.block_size_tokens or DEFAULT_BLOCK_SIZE
        if block_size <= 0:
            raise ValueError(
                f"block_size_tokens must be greater than 0, got {cfg.block_size_tokens}"
            )
        self._block_size = block_size
        self._hash_seed = cfg.hash_seed
        self._init_hash = fnv1a_64(self._hash_seed.encode("utf-8"))
        # Per-model seed cache: the init step hashes the model name into the
        # chain once; memoize since model cardinality is tiny.
        self._model_seed_cache: dict[str, int] = {}
        self._native = None
        if use_native:
            try:
                from ..index import native as _native_mod

                if _native_mod.native_available():
                    self._native = _native_mod
            except Exception:  # pragma: no cover - toolchain-less envs
                self._native = None
        self._prefix_cache: Optional[PrefixKeyCache] = (
            PrefixKeyCache(cfg.prefix_cache_tokens)
            if cfg.prefix_cache_tokens > 0 else None
        )
        # Blocks actually hashed (native or Python), across all call paths.
        # Approximate under concurrency (unlocked increment); used by the
        # perf_smoke test to prove the cache short-circuits hashing.
        self.hash_calls = 0

    @property
    def block_size(self) -> int:
        return self._block_size

    def prefix_cache_stats(self) -> Optional[dict]:
        """Hit/miss counters of the prefix-key cache (None when disabled)."""
        return self._prefix_cache.stats() if self._prefix_cache is not None else None

    def _hash(self, parent: int, tokens: Optional[Sequence[int]], extra) -> int:
        # `tokens` is hashed as passed: lists and tuples (and their slices)
        # produce identical canonical-CBOR arrays, so no copy is taken here.
        payload = [parent, tokens, extra]
        return fnv1a_64(canonical_cbor_encode(payload))

    def _get_init_hash(self, model_name: str) -> int:
        cached = self._model_seed_cache.get(model_name)
        if cached is None:
            cached = self._hash(self._init_hash, None, model_name)
            self._model_seed_cache[model_name] = cached
        return cached

    def _hash_text_chain(
        self, parent: int, tokens: Sequence[int], n_chunks: int
    ) -> list[BlockHash]:
        """Hash full text-only blocks, native when available. Trailing
        partial tokens are ignored."""
        self.hash_calls += n_chunks
        if self._native is not None:
            return self._native.hash_chain(parent, tokens, self._block_size)
        bs = self._block_size
        keys: list[BlockHash] = []
        prefix = parent
        for i in range(n_chunks):
            prefix = self._hash(prefix, tokens[i * bs:(i + 1) * bs], None)
            keys.append(prefix)
        return keys

    def _hash_text_chain_with_array(
        self, parent: int, tokens: Sequence[int], n_chunks: int
    ):
        """Like ``_hash_text_chain`` but also returns the keys as a
        ``np.uint64`` array (None without numpy) for the prefix cache, so
        warm score calls hand the native fused scorer a ready array."""
        self.hash_calls += n_chunks
        if self._native is not None:
            return self._native.hash_chain_with_array(
                parent, tokens, self._block_size)
        bs = self._block_size
        keys: list[BlockHash] = []
        prefix = parent
        for i in range(n_chunks):
            prefix = self._hash(prefix, tokens[i * bs:(i + 1) * bs], None)
            keys.append(prefix)
        arr = None
        if _np is not None:
            arr = _np.asarray([k & 0xFFFFFFFFFFFFFFFF for k in keys], _np.uint64)
        return keys, arr

    def _hash_tainted_chain(
        self,
        parent: int,
        tokens: Sequence[int],
        extra_features: Sequence[Optional[BlockExtraFeatures]],
    ) -> list[BlockHash]:
        """Python path for multimodal-tainted chains: per-block ``extra``
        feeds the hash, so neither the native chain nor the prefix cache
        may serve these."""
        self.hash_calls += len(extra_features)
        bs = self._block_size
        keys: list[BlockHash] = []
        prefix = parent
        for i, features in enumerate(extra_features):
            extra = None
            if features is not None:
                extra = [{"Hash": h} for h in features.mm_hashes]
            prefix = self._hash(prefix, tokens[i * bs:(i + 1) * bs], extra)
            keys.append(prefix)
        return keys

    def tokens_to_kv_block_keys(
        self,
        parent_key: BlockHash,
        tokens: Sequence[int],
        model_name: str,
        extra_features: Optional[Sequence[Optional[BlockExtraFeatures]]] = None,
    ) -> list[BlockHash]:
        """Convert tokens into chained block keys.

        ``parent_key`` continues an existing chain (``EMPTY_BLOCK_HASH`` to
        start fresh from the model-seeded init hash). ``extra_features``, if
        given, must have exactly one entry per full token chunk.
        """
        return self.tokens_to_kv_block_keys_with_array(
            parent_key, tokens, model_name, extra_features)[0]

    def tokens_to_kv_block_keys_with_array(
        self,
        parent_key: BlockHash,
        tokens: Sequence[int],
        model_name: str,
        extra_features: Optional[Sequence[Optional[BlockExtraFeatures]]] = None,
    ):
        """Like ``tokens_to_kv_block_keys`` but returns ``(keys, arr)``
        where ``arr`` is the same keys as a ``np.uint64`` array when the
        prefix cache produced one (else None). The array feeds
        ``NativeIndex.score`` directly, skipping its per-call ``asarray``
        over thousands of keys on warm sessions.
        """
        parent = parent_key if parent_key != EMPTY_BLOCK_HASH else self._get_init_hash(model_name)

        n_chunks = len(tokens) // self._block_size
        if n_chunks == 0:
            return [], None

        if extra_features is not None and len(extra_features) != n_chunks:
            raise ValueError(
                f"extra_features length {len(extra_features)} does not match "
                f"token chunk count {n_chunks} (block_size_tokens="
                f"{self._block_size}, tokens={len(tokens)})"
            )

        if extra_features is not None and any(f is not None for f in extra_features):
            return self._hash_tainted_chain(parent, tokens, extra_features), None

        cache = self._prefix_cache
        if cache is None:
            return self._hash_text_chain(parent, tokens, n_chunks), None

        # Incremental path: reuse the longest cached block-aligned prefix
        # under this parent and hash only the suffix chunks. The cache is
        # fingerprint-keyed over the block-aligned token prefix (trailing
        # partial tokens never influence keys, so they must not defeat
        # exact matches); ``match`` hands back the full-prefix fingerprint
        # so the store below never hashes the tokens a second time.
        aligned = n_chunks * self._block_size
        trimmed = tuple(tokens) if len(tokens) == aligned else tuple(tokens[:aligned])
        fp, cached_keys, cached_arr = cache.match(parent, trimmed)
        matched = len(cached_keys)
        if matched == n_chunks:
            cache.note(matched, 0)
            return list(cached_keys), cached_arr
        sub_parent = cached_keys[-1] if matched else parent
        suffix_keys, suffix_arr = self._hash_text_chain_with_array(
            sub_parent, trimmed[matched * self._block_size:], n_chunks - matched
        )
        if matched:
            keys_t = cached_keys + tuple(suffix_keys)
            arr = None
            if cached_arr is not None and suffix_arr is not None:
                arr = _np.concatenate([cached_arr, suffix_arr])
        else:
            keys_t = tuple(suffix_keys)
            arr = suffix_arr
        cache.store(parent, aligned, fp, keys_t, arr,
                    trimmed[0], trimmed[-1])
        cache.note(matched, n_chunks - matched)
        return list(keys_t), arr


# Backwards-friendly alias matching the reference interface name.
TokenProcessor = ChunkedTokenDatabase
