"""``jax.shard_map`` across JAX versions.

Newer JAX exports ``jax.shard_map`` (varying-axes check spelled
``check_vma``); older releases have ``jax.experimental.shard_map`` with
``check_rep``. One shim, one spelling everywhere else.
"""

from __future__ import annotations

try:
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

__all__ = ["shard_map"]
