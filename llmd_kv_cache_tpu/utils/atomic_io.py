"""Durable atomic file publication.

``os.replace`` alone gives atomicity against concurrent readers but not
against power loss: without an ``fsync`` of the tmp file the rename can
land on disk *before* the data blocks, publishing a torn file behind a
valid name, and without an ``fsync`` of the containing directory the
rename itself may vanish. Every persistence site in the project (snapshot
writer, offload run-config/object-store publication, checkpoint metadata)
goes through :func:`atomic_write_bytes` so the tmp + fsync(file) +
``os.replace`` + fsync(dir) sequence lives in exactly one place.
"""

from __future__ import annotations

import os
import threading


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it survives power loss.

    Best-effort: some filesystems (and all of Windows) refuse ``open`` on
    directories; the rename is still atomic there, just not durable.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Durably publish ``data`` at ``path``: tmp + fsync + replace + dirsync.

    The tmp name embeds pid and thread id so concurrent writers to the
    same target never collide on the intermediate file.
    """
    directory = os.path.dirname(os.path.abspath(path))
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:  # lint: allow-swallow (tmp already gone)
            pass
        raise
    fsync_dir(directory)
