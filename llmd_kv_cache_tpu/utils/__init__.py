"""Shared utilities: canonical CBOR, FNV hashing, LRU caches, logging."""

from .cbor import canonical_cbor_encode
from .fnv import fnv1a_32, fnv1a_64
from .lru import LRUCache

__all__ = ["canonical_cbor_encode", "fnv1a_32", "fnv1a_64", "LRUCache"]
