"""Runtime lockdep witness: opt-in deadlock detection for library locks.

The static pass (``hack/lint_concurrency.py``) proves properties about
the *source*; this module witnesses them at *runtime*. Every lock the
library constructs goes through :func:`new_lock` / :func:`new_rlock` /
:func:`new_condition`. With ``KVTPU_LOCKDEP=1`` (exported by
``make unit-test-race`` and ``make chaos``) those factories return
instrumented wrappers that, in the style of the Linux kernel's lockdep:

- record per-thread acquisition stacks (which locks this thread holds,
  and the Python stack at each acquire);
- key locks by *site* (``file:line`` of construction), so every
  ``Pool._lag_mu`` across all instances is one node — a B→A ordering
  seen in one test plus an A→B in another is still a reported cycle;
- maintain the observed lock-order graph and raise
  :class:`LockOrderViolation` on the first acquisition that closes a
  cycle — on the *potential* deadlock, not the once-in-a-thousand-runs
  interleaving that actually wedges;
- raise :class:`LockReentryViolation` when a thread re-acquires a
  non-reentrant lock it already holds (the self-deadlock class the
  static CONC-REENTRY rule targets);
- enforce a hold-time budget (``KVTPU_LOCKDEP_BUDGET_S``, default off):
  releasing a lock held longer than the budget raises
  :class:`LockHoldBudgetViolation`, catching slow critical sections that
  the CONC-BLOCKING rule's syntactic patterns miss.

Before raising, the witness dumps the offending acquisition stacks and
the order-graph edge through the flight recorder (``KIND_LOCKDEP``), so
a violation inside a worker thread still leaves a black-box capture even
if the raising thread's traceback is swallowed by a ``Thread.run``.

When ``KVTPU_LOCKDEP`` is unset the factories return plain
``threading`` primitives — zero wrapper frames, zero overhead, which is
why call sites use the factories unconditionally rather than branching
themselves.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Optional

__all__ = [
    "new_lock",
    "new_rlock",
    "new_condition",
    "LockdepError",
    "LockOrderViolation",
    "LockReentryViolation",
    "LockHoldBudgetViolation",
    "enabled",
    "set_enabled",
    "reset",
    "graph_snapshot",
]

_STACK_LIMIT = 12  # frames kept per acquisition record


class LockdepError(RuntimeError):
    """Base class for lockdep violations."""


class LockOrderViolation(LockdepError):
    """An acquisition closed a cycle in the observed lock-order graph."""


class LockReentryViolation(LockdepError):
    """A thread re-acquired a non-reentrant lock it already holds."""


class LockHoldBudgetViolation(LockdepError):
    """A lock was held longer than ``KVTPU_LOCKDEP_BUDGET_S``."""


def _env_enabled() -> bool:
    return os.environ.get("KVTPU_LOCKDEP") == "1"


def _env_budget() -> Optional[float]:
    raw = os.environ.get("KVTPU_LOCKDEP_BUDGET_S", "")
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if val > 0 else None


_enabled = _env_enabled()
_budget_s = _env_budget()


class _State:
    """Process-wide witness state: the order graph and per-thread stacks.

    One plain ``threading.Lock`` guards the graph; per-thread held
    stacks live in ``threading.local`` and need no locking. The guard is
    deliberately *not* a lockdep lock (the witness must not witness
    itself) and nothing blocking runs under it.
    """

    def __init__(self):
        self.mu = threading.Lock()
        # site -> set of sites observed acquired while `site` was held.
        self.order: dict[str, set[str]] = {}
        # (a, b) -> short description of where the a→b edge was observed.
        self.edge_sites: dict[tuple[str, str], str] = {}
        self.tls = threading.local()

    def held(self) -> list:
        stack = getattr(self.tls, "held", None)
        if stack is None:
            stack = self.tls.held = []
        return stack


_state = _State()


def enabled() -> bool:
    """Whether the witness is active (wrappers being handed out)."""
    return _enabled


def set_enabled(on: bool, budget_s: Optional[float] = None) -> None:
    """Test hook: flip the witness on/off without touching the env.

    Only affects locks created *after* the call — existing plain locks
    stay plain (the zero-overhead property is decided at construction).
    """
    global _enabled, _budget_s
    _enabled = bool(on)
    if budget_s is not None:
        _budget_s = budget_s if budget_s > 0 else None


def reset() -> None:
    """Clear the observed order graph (test isolation between cases)."""
    with _state.mu:
        _state.order.clear()
        _state.edge_sites.clear()


def graph_snapshot() -> dict[str, list[str]]:
    """Copy of the observed lock-order graph (site -> successor sites)."""
    with _state.mu:
        return {a: sorted(bs) for a, bs in _state.order.items()}


def _caller_site() -> str:
    # Frame 0=_caller_site, 1=factory, 2=construction site.
    frame = traceback.extract_stack(limit=3)[0]
    return f"{frame.filename}:{frame.lineno}"


def _fmt_stack(stack: traceback.StackSummary) -> str:
    return "".join(stack.format())


def _reaches(graph: dict[str, set[str]], src: str, dst: str) -> bool:
    """DFS reachability over the order graph (held under ``_state.mu``)."""
    seen = set()
    todo = [src]
    while todo:
        node = todo.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        todo.extend(graph.get(node, ()))
    return False


def _dump(kind: str, data: dict) -> None:
    """Black-box the violation through the flight recorder before raising."""
    try:
        from ..telemetry.flight_recorder import (  # noqa: PLC0415
            KIND_LOCKDEP,
            record,
        )

        record(KIND_LOCKDEP, dict(data, violation=kind))
    except Exception:  # lint: allow-swallow (best-effort black-box; the violation raise right after must not be masked)
        pass


class _Held:
    """One entry on a thread's held-lock stack."""

    __slots__ = ("lock", "stack", "t_acquired")

    def __init__(self, lock: "DepLock"):
        self.lock = lock
        self.stack = traceback.extract_stack(limit=_STACK_LIMIT)
        self.t_acquired = time.monotonic()


class DepLock:
    """Instrumented non-reentrant lock (lockdep-enabled ``Lock``)."""

    _reentrant = False

    def __init__(self, site: Optional[str] = None):
        self._lk = self._make_inner()
        self.site = site or _caller_site()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    # -- witness core -------------------------------------------------

    def _depth(self, held: list) -> int:
        return sum(1 for h in held if h.lock is self)

    def _before_acquire(self) -> None:
        held = _state.held()
        depth = self._depth(held)
        if depth and not self._reentrant:
            first = next(h for h in held if h.lock is self)
            msg = (
                f"non-reentrant lock {self.site} re-acquired by thread "
                f"{threading.current_thread().name} that already holds it\n"
                f"first acquisition:\n{_fmt_stack(first.stack)}"
            )
            _dump("reentry", {"site": self.site, "thread": threading.current_thread().name})
            raise LockReentryViolation(msg)
        if depth:
            return  # legal RLock re-entry adds no order edges
        for h in reversed(held):
            if h.lock.site == self.site:
                continue
            # Only the innermost held lock needs an edge: when *it* was
            # acquired the outer→inner edges were already recorded, so
            # reachability covers outer→self transitively.
            self._note_edge(h, held)
            break

    def _note_edge(self, prev: "_Held", held: list) -> None:
        a, b = prev.lock.site, self.site
        where = traceback.extract_stack(limit=_STACK_LIMIT)
        back = None
        # The dump + raise happen *after* _state.mu is released: _dump
        # walks back into the flight recorder, whose own guard must not
        # nest under the witness's internal mutex.
        with _state.mu:
            cycle = b in _state.order and _reaches(_state.order, b, a)
            if cycle:
                back = _state.edge_sites.get((b, a), "<earlier edge>")
            else:
                _state.order.setdefault(a, set()).add(b)
                caller = next(
                    (f for f in reversed(where) if f.filename != __file__),
                    where[-1],
                )
                _state.edge_sites.setdefault(
                    (a, b), f"{caller.filename}:{caller.lineno} in {caller.name}"
                )
        if cycle:
            _dump(
                "lock-order",
                {
                    "holding": a,
                    "acquiring": b,
                    "reverse_edge": back,
                    "held": [h.lock.site for h in held],
                },
            )
            raise LockOrderViolation(
                f"lock-order cycle: acquiring {b} while holding {a}, "
                f"but {b}→{a} was already observed at {back}\n"
                f"current acquisition:\n{_fmt_stack(where)}"
                f"holding {a} since:\n{_fmt_stack(prev.stack)}"
            )

    def _after_acquire(self) -> None:
        _state.held().append(_Held(self))

    def _after_release(self) -> None:
        held = _state.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                entry = held.pop(i)
                break
        else:  # pragma: no cover - release without acquire raises below us
            return
        if _budget_s is not None:
            held_for = time.monotonic() - entry.t_acquired
            if held_for > _budget_s:
                _dump(
                    "hold-budget",
                    {"site": self.site, "held_s": round(held_for, 4), "budget_s": _budget_s},
                )
                raise LockHoldBudgetViolation(
                    f"lock {self.site} held {held_for:.4f}s "
                    f"(budget {_budget_s}s)\nacquired at:\n{_fmt_stack(entry.stack)}"
                )

    # -- threading.Lock surface ---------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._before_acquire()
        got = self._lk.acquire(blocking, timeout)
        if got:
            self._after_acquire()
        return got

    def release(self) -> None:
        self._lk.release()
        self._after_release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} site={self.site}>"

    # -- Condition interop (mirrors threading.Lock's private surface) --

    def _is_owned(self) -> bool:
        return any(h.lock is self for h in _state.held())

    def _release_save(self):
        self.release()

    def _acquire_restore(self, _saved) -> None:
        self.acquire()


class DepRLock(DepLock):
    """Instrumented reentrant lock (lockdep-enabled ``RLock``)."""

    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def _release_save(self):
        # Unwind the full recursion depth like threading.RLock does.
        count = self._depth(_state.held())
        for _ in range(count):
            self.release()
        return count

    def _acquire_restore(self, saved: int) -> None:
        for _ in range(saved):
            self.acquire()


def new_lock() -> "threading.Lock | DepLock":
    """A mutex for library state: plain ``Lock``, or witnessed when on."""
    if _enabled:
        return DepLock(site=_caller_site())
    return threading.Lock()


def new_rlock() -> "threading.RLock | DepRLock":
    """A reentrant mutex: plain ``RLock``, or witnessed when on."""
    if _enabled:
        return DepRLock(site=_caller_site())
    return threading.RLock()


def new_condition(lock=None) -> threading.Condition:
    """A condition variable over a lockdep-aware lock.

    ``threading.Condition`` drives its lock through ``acquire``/
    ``release``/``_is_owned``/``_release_save``/``_acquire_restore``,
    all of which :class:`DepLock` implements, so ``wait`` correctly
    drops the witnessed lock (popping it off the held stack) and
    re-acquires it (re-checking order) on wake.
    """
    if lock is None:
        lock = new_rlock()
    return threading.Condition(lock)
