"""Thread-safe LRU cache.

Equivalent in role to hashicorp/golang-lru in the reference in-memory index
(``pkg/kvcache/kvblock/in_memory.go:61-76``): bounded, promote-on-get, with a
non-promoting ``peek`` so maintenance scans (Clear) don't distort recency
(``in_memory.go:327-330``).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Generic, Hashable, Iterator, Optional, TypeVar

from .lockdep import new_lock

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")

_SENTINEL = object()


class LRUCache(Generic[K, V]):
    """Bounded LRU mapping with promote-on-get semantics."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError(f"LRU capacity must be positive, got {capacity}")
        self.capacity = capacity
        self._data: "OrderedDict[K, V]" = OrderedDict()
        self._lock = new_lock()

    def get(self, key: K, default: Optional[V] = None) -> Optional[V]:
        """Return value for ``key``, promoting it to most-recently-used."""
        with self._lock:
            value = self._data.get(key, _SENTINEL)
            if value is _SENTINEL:
                return default
            self._data.move_to_end(key)
            return value  # type: ignore[return-value]

    def peek(self, key: K, default: Optional[V] = None) -> Optional[V]:
        """Return value for ``key`` without promoting recency."""
        with self._lock:
            value = self._data.get(key, _SENTINEL)
            return default if value is _SENTINEL else value  # type: ignore[return-value]

    def add(self, key: K, value: V) -> bool:
        """Insert or update; returns True if an entry was evicted."""
        with self._lock:
            if key in self._data:
                self._data[key] = value
                self._data.move_to_end(key)
                return False
            self._data[key] = value
            if len(self._data) > self.capacity:
                self._data.popitem(last=False)
                return True
            return False

    def get_or_add(self, key: K, value: V) -> tuple[V, bool]:
        """Atomically return the existing value or insert ``value``.

        Returns ``(stored_value, existed)``. Mirrors golang-lru's
        ``ContainsOrAdd`` + ``Get`` dance in the reference Add path
        (``in_memory.go:206-219``) but without its bounded-retry race.
        """
        with self._lock:
            existing = self._data.get(key, _SENTINEL)
            if existing is not _SENTINEL:
                self._data.move_to_end(key)
                return existing, True  # type: ignore[return-value]
            self._data[key] = value
            if len(self._data) > self.capacity:
                self._data.popitem(last=False)
            return value, False

    def get_or_create(self, key: K, factory) -> tuple[V, bool]:
        """Like ``get_or_add`` but constructs the value lazily on miss.

        Avoids allocating a throwaway value on the hot path where the key
        usually exists. Returns ``(stored_value, existed)``.
        """
        with self._lock:
            existing = self._data.get(key, _SENTINEL)
            if existing is not _SENTINEL:
                self._data.move_to_end(key)
                return existing, True  # type: ignore[return-value]
            value = factory()
            self._data[key] = value
            if len(self._data) > self.capacity:
                self._data.popitem(last=False)
            return value, False

    def remove(self, key: K) -> bool:
        with self._lock:
            if key in self._data:
                del self._data[key]
                return True
            return False

    def keys(self) -> list[K]:
        """Snapshot of keys, oldest first."""
        with self._lock:
            return list(self._data.keys())

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key: K) -> bool:
        with self._lock:
            return key in self._data

    def __iter__(self) -> Iterator[K]:
        return iter(self.keys())
