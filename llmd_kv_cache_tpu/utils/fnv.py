"""FNV-1a hashing.

The 64-bit variant seeds/chains the block-key hashes (reference:
``pkg/kvcache/kvblock/token_processor.go:114-118,155-157``); the 32-bit
variant shards event-pool queues by pod id (``pkg/kvevents/pool.go:161-173``).
"""

from __future__ import annotations

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_FNV32_OFFSET = 0x811C9DC5
_FNV32_PRIME = 0x01000193
_MASK64 = 0xFFFFFFFFFFFFFFFF
_MASK32 = 0xFFFFFFFF


def fnv1a_64(data: bytes, seed: int = _FNV64_OFFSET) -> int:
    """64-bit FNV-1a hash of ``data``."""
    h = seed
    for b in data:
        h = ((h ^ b) * _FNV64_PRIME) & _MASK64
    return h


def fnv1a_32(data: bytes, seed: int = _FNV32_OFFSET) -> int:
    """32-bit FNV-1a hash of ``data``."""
    h = seed
    for b in data:
        h = ((h ^ b) * _FNV32_PRIME) & _MASK32
    return h
