"""gRPC address normalization shared by the sidecar services."""

from __future__ import annotations


def grpc_target(address: str) -> str:
    """Normalize an address for gRPC bind/dial.

    - explicit schemes (``unix:``, ``dns://`` etc.) pass through
    - bare filesystem paths (no colon, or leading ``/``) become ``unix:``
    - ``host:port`` strings pass through as TCP targets
    """
    if "://" in address or address.startswith("unix:"):
        return address
    if address.startswith("/") or ":" not in address:
        return f"unix:{address}"
    return address
