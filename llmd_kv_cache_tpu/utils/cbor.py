"""Canonical CBOR encoding (RFC 7049 §3.9).

The block-key hash chain hashes the canonical-CBOR encoding of
``[parent, tokens, extra]`` (reference:
``pkg/kvcache/kvblock/token_processor.go:146-158``, which uses
``fxamacker/cbor`` ``CanonicalEncOptions``). Interop with engines that
compute block hashes the same way requires byte-exact encodings, so this
module implements the canonical subset needed by the hash payloads:

- unsigned/negative integers in shortest form (major types 0/1)
- byte strings (major 2) and UTF-8 text strings (major 3)
- definite-length arrays (major 4) and maps (major 5)
- ``False``/``True``/``None`` simple values (0xf4/0xf5/0xf6)
- float64 (major 7, ai 27) — canonical float shortening is intentionally
  not implemented; hash payloads never contain floats.

Map keys are sorted per RFC 7049 canonical ordering: shorter encoded key
first, then bytewise lexicographic. ``None`` encodes as null (0xf6), which
matches fxamacker's ``NilContainerAsNull`` treatment of nil Go slices.
"""

from __future__ import annotations

import struct
from typing import Any

_MAJOR_UINT = 0
_MAJOR_NEGINT = 1
_MAJOR_BYTES = 2
_MAJOR_TEXT = 3
_MAJOR_ARRAY = 4
_MAJOR_MAP = 5


def _encode_head(major: int, value: int) -> bytes:
    """Encode a major type + unsigned argument in shortest form."""
    mt = major << 5
    if value < 24:
        return bytes((mt | value,))
    if value <= 0xFF:
        return bytes((mt | 24, value))
    if value <= 0xFFFF:
        return bytes((mt | 25,)) + value.to_bytes(2, "big")
    if value <= 0xFFFFFFFF:
        return bytes((mt | 26,)) + value.to_bytes(4, "big")
    if value <= 0xFFFFFFFFFFFFFFFF:
        return bytes((mt | 27,)) + value.to_bytes(8, "big")
    raise ValueError(f"integer too large for CBOR head: {value}")


def _encode_item(obj: Any, out: list[bytes]) -> None:
    if obj is None:
        out.append(b"\xf6")
    elif obj is True:
        out.append(b"\xf5")
    elif obj is False:
        out.append(b"\xf4")
    elif isinstance(obj, int):
        if obj >= 0:
            out.append(_encode_head(_MAJOR_UINT, obj))
        else:
            out.append(_encode_head(_MAJOR_NEGINT, -1 - obj))
    elif isinstance(obj, bytes):
        out.append(_encode_head(_MAJOR_BYTES, len(obj)))
        out.append(obj)
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(_encode_head(_MAJOR_TEXT, len(raw)))
        out.append(raw)
    elif isinstance(obj, (list, tuple)):
        out.append(_encode_head(_MAJOR_ARRAY, len(obj)))
        for item in obj:
            _encode_item(item, out)
    elif isinstance(obj, dict):
        out.append(_encode_head(_MAJOR_MAP, len(obj)))
        pairs = []
        for k, v in obj.items():
            kparts: list[bytes] = []
            _encode_item(k, kparts)
            vparts: list[bytes] = []
            _encode_item(v, vparts)
            pairs.append((b"".join(kparts), b"".join(vparts)))
        # RFC 7049 canonical: shorter key first, then bytewise.
        pairs.sort(key=lambda kv: (len(kv[0]), kv[0]))
        for kenc, venc in pairs:
            out.append(kenc)
            out.append(venc)
    elif isinstance(obj, float):
        out.append(b"\xfb" + struct.pack(">d", obj))
    else:
        raise TypeError(f"cannot canonically CBOR-encode {type(obj)!r}")


def canonical_cbor_encode(obj: Any) -> bytes:
    """Encode ``obj`` as canonical CBOR bytes."""
    out: list[bytes] = []
    _encode_item(obj, out)
    return b"".join(out)


class CBORDecodeError(ValueError):
    """Malformed or out-of-subset CBOR input."""


def _decode_head(data: bytes, pos: int) -> tuple[int, int, int]:
    """Decode a head at ``pos``; returns (major, argument, next_pos)."""
    if pos >= len(data):
        raise CBORDecodeError("truncated CBOR: missing head")
    b = data[pos]
    major, ai = b >> 5, b & 0x1F
    pos += 1
    if ai < 24:
        return major, ai, pos
    if ai > 27:
        raise CBORDecodeError(f"unsupported additional info {ai}")
    n = 1 << (ai - 24)
    if pos + n > len(data):
        raise CBORDecodeError("truncated CBOR: short head argument")
    return major, int.from_bytes(data[pos:pos + n], "big"), pos + n


def _decode_item(data: bytes, pos: int) -> tuple[Any, int]:
    b = data[pos] if pos < len(data) else None
    if b == 0xF4:
        return False, pos + 1
    if b == 0xF5:
        return True, pos + 1
    if b == 0xF6:
        return None, pos + 1
    if b == 0xFB:
        if pos + 9 > len(data):
            raise CBORDecodeError("truncated CBOR: short float64")
        return struct.unpack(">d", data[pos + 1:pos + 9])[0], pos + 9
    major, arg, pos = _decode_head(data, pos)
    if major == _MAJOR_UINT:
        return arg, pos
    if major == _MAJOR_NEGINT:
        return -1 - arg, pos
    if major in (_MAJOR_BYTES, _MAJOR_TEXT):
        if pos + arg > len(data):
            raise CBORDecodeError("truncated CBOR: short string body")
        raw = data[pos:pos + arg]
        return (raw if major == _MAJOR_BYTES else raw.decode("utf-8")), pos + arg
    if major == _MAJOR_ARRAY:
        items = []
        for _ in range(arg):
            item, pos = _decode_item(data, pos)
            items.append(item)
        return items, pos
    if major == _MAJOR_MAP:
        out: dict = {}
        for _ in range(arg):
            k, pos = _decode_item(data, pos)
            v, pos = _decode_item(data, pos)
            out[k] = v
        return out, pos
    raise CBORDecodeError(f"unsupported major type {major}")


def canonical_cbor_decode(data: bytes) -> Any:
    """Decode bytes produced by :func:`canonical_cbor_encode`.

    Accepts exactly the encoder's subset (shortest-form ints, definite
    strings/arrays/maps, false/true/null, float64) and raises
    :class:`CBORDecodeError` on anything else, on truncation, and on
    trailing bytes — a decode-encode round trip is byte-identical, which
    is what lets snapshot checksums cover the semantic content.
    """
    obj, pos = _decode_item(data, 0)
    if pos != len(data):
        raise CBORDecodeError(f"{len(data) - pos} trailing byte(s) after CBOR item")
    return obj
