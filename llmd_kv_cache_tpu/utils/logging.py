"""Leveled logging helpers.

Mirrors the reference's verbosity convention (``pkg/utils/logging/levels.go``:
DEBUG=1, TRACE=2 on top of INFO) onto Python's stdlib logging: DEBUG maps to
``logging.DEBUG`` and TRACE to a custom finer level. Level selection via the
``KVTPU_LOG_LEVEL`` env var (``info``/``debug``/``trace``).
"""

from __future__ import annotations

import logging
import os

TRACE = 5
logging.addLevelName(TRACE, "TRACE")


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"llmd_kv_cache_tpu.{name}")


def trace(logger: logging.Logger, msg: str, *args) -> None:
    if logger.isEnabledFor(TRACE):
        logger.log(TRACE, msg, *args)


def configure_from_env() -> None:
    """Configure root logger level from ``KVTPU_LOG_LEVEL``."""
    level_name = os.environ.get("KVTPU_LOG_LEVEL", "info").lower()
    level = {"trace": TRACE, "debug": logging.DEBUG, "info": logging.INFO,
             "warn": logging.WARNING, "warning": logging.WARNING,
             "error": logging.ERROR}.get(level_name, logging.INFO)
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
