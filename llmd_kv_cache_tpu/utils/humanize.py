"""Human-readable byte sizes.

The cost-aware index budget is configured as a string like ``"2GiB"``
(reference: ``pkg/kvcache/kvblock/cost_aware_memory.go:47-60``, which uses
go-humanize). Accepts both SI (kB/MB/GB, powers of 1000) and IEC
(KiB/MiB/GiB, powers of 1024) suffixes, case-insensitively, plus bare byte
counts.
"""

from __future__ import annotations

import re

_UNITS = {
    "": 1,
    "b": 1,
    "kb": 1000,
    "mb": 1000**2,
    "gb": 1000**3,
    "tb": 1000**4,
    "pb": 1000**5,
    "kib": 1024,
    "mib": 1024**2,
    "gib": 1024**3,
    "tib": 1024**4,
    "pib": 1024**5,
}

_SIZE_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([a-zA-Z]*)\s*$")


def parse_bytes(size: str | int | float) -> int:
    """Parse a human byte-size string (e.g. ``"2GiB"``, ``"500 MB"``) to bytes."""
    if isinstance(size, (int, float)):
        return int(size)
    m = _SIZE_RE.match(size)
    if not m:
        raise ValueError(f"cannot parse byte size: {size!r}")
    value, unit = m.groups()
    unit = unit.lower()
    if unit not in _UNITS:
        raise ValueError(f"unknown byte-size unit {unit!r} in {size!r}")
    return int(float(value) * _UNITS[unit])
