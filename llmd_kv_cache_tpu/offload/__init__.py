"""KV offload data plane: TPU HBM ↔ shared storage.

Counterpart of the reference's ``kv_connectors/llmd_fs_backend``: moves
paged KV blocks between device HBM and a content-addressed file store. The
CUDA D2H/H2D copy path is replaced by JAX/XLA device→host transfers
(``tpu_copier``); file I/O runs on a native C++ thread pool (``csrc/kvio``).
"""

from .file_mapper import FileMapper, FileMapperConfig
from .handoff import HandoffCoordinator, HandoffState
from .manager import SharedStorageOffloadManager
from .spec import SharedStorageOffloadSpec
from .worker import OffloadHandlers, TransferResult

__all__ = [
    "FileMapper",
    "FileMapperConfig",
    "HandoffCoordinator",
    "HandoffState",
    "SharedStorageOffloadManager",
    "SharedStorageOffloadSpec",
    "OffloadHandlers",
    "TransferResult",
]
