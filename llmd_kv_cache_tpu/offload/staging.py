"""Recycled host staging buffers for the offload data plane.

Counterpart of the reference's ``_StagedBackend`` mixin
(``llmd_nixl/staged_backend.py:25-106``): that design keeps a pool of
pre-registered pinned CPU buffers so the hot path never pays
allocate+register per transfer, sizes the pool as
``max(io_threads * 8, blocks / blocks_per_file + 1)``, extends it on
shortfall instead of failing, and returns slots on completion or submit
error. The TPU analog has no NIXL registration, but the same two costs
exist: large-buffer allocation (page faults on first touch) and the
allocator churn of a fresh multi-megabyte numpy array per load job.
Load destinations therefore come from this pool and return to it once
the H2D scatter has consumed them.

Stores don't stage through the pool: the device gather already lands in
a pinned-host jax buffer (``TPUBlockCopier._to_pinned_host``) that the
native writer reads directly — copying it into a pool slot would add
the copy the pool exists to avoid.
"""

from __future__ import annotations


import numpy as np

from ..utils.lockdep import new_lock
from ..utils.logging import get_logger

logger = get_logger("offload.staging")


class HostStagingPool:
    """Fixed-size-slot buffer pool with extend-on-shortfall.

    Slots are uint8 arrays of ``slot_bytes``; ``acquire(n)`` returns a
    length-``n`` view of a free slot (n ≤ slot_bytes) and ``release``
    returns the slot. Thread-safe: the I/O pool's completion threads
    release concurrently with the engine thread acquiring.
    """

    def __init__(self, slot_bytes: int, slots: int):
        self.slot_bytes = int(slot_bytes)
        self._lock = new_lock()
        self._free: list[np.ndarray] = [
            np.empty(self.slot_bytes, np.uint8) for _ in range(slots)
        ]
        self._total = slots
        # Views keyed by the base buffer id so release() can recover the
        # full slot from the view handed out by acquire().
        self._out: dict[int, np.ndarray] = {}

    @property
    def total_slots(self) -> int:
        return self._total

    @property
    def free_slots(self) -> int:
        with self._lock:
            return len(self._free)

    def acquire(self, nbytes: int) -> np.ndarray:
        """A length-``nbytes`` uint8 view of a free slot.

        Oversize requests (a caller reading more pages per unit than the
        pool was sized for) get a transient non-pool buffer — correct,
        just unrecycled; ``release`` no-ops on it."""
        if nbytes > self.slot_bytes:
            logger.debug("staging request %d B > slot %d B; transient "
                         "buffer", nbytes, self.slot_bytes)
            return np.empty(nbytes, np.uint8)
        with self._lock:
            if not self._free:
                # Extend instead of failing (reference
                # ``_extend_staging_pool``): a burst beyond the sizing
                # heuristic is a workload fact, not an error.
                added = max(self._total, 1)
                logger.info(
                    "staging pool exhausted: extending by %d slots "
                    "(%d -> %d)", added, self._total, self._total + added)
                self._free.extend(
                    np.empty(self.slot_bytes, np.uint8)
                    for _ in range(added))
                self._total += added
            slot = self._free.pop()
            view = slot[:nbytes]  # basic slice: view.base IS the slot
            self._out[id(slot)] = slot
            return view

    def release(self, view: np.ndarray) -> None:
        """Return the slot backing ``view`` (idempotent per acquire)."""
        base = view.base if view.base is not None else view
        with self._lock:
            slot = self._out.pop(id(base), None)
            if slot is not None:
                self._free.append(slot)


def pool_size_for(io_threads: int) -> int:
    """Slots for every I/O thread to keep several reads in flight
    (reference ``staged_backend.py:44-47``'s thread term). The
    reference's second term — one slot per file the whole cache could
    occupy — is dropped: there the pool doubled as the registered host
    storage tier, here it is transit staging only and extends on
    shortfall, so a cache-sized preallocation would be pure waste."""
    return max(io_threads * 8, 16)
