"""Deterministic on-disk layout for offloaded KV blocks.

Counterpart of reference ``llmd_fs_backend/file_mapper.py``: content-
addressed ``.bin`` files under a model+config-fingerprinted directory so
cache state survives engine restarts and is shared only between
identically-configured deployments.

Layout:
``<root>/<safe_model>_<fp12>/config.json``          (shared metadata)
``<root>/<safe_model>_<fp12>_r<rank>/<hhh>/<hh>_g<group>/<block_hash16>.bin``

The fingerprint covers the model, dtype, KV geometry, engine id and the
**mesh axis world sizes** (tp/pp/dp/sp) — the TPU-native equivalent of the
reference's ``tp/pp/pcp/dcp`` fields (``file_mapper.py:63-74``): blocks
written by a TP=4 deployment must not be read by a TP=8 one.
``parallel_agnostic`` collapses the rank dimension for single-host caches.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Optional

from ..utils.atomic_io import atomic_write_bytes


@dataclass
class FileMapperConfig:
    root: str
    model_name: str
    dtype: str = "bfloat16"
    page_size: int = 16
    kv_heads: int = 8
    head_dim: int = 128
    num_layers: int = 32
    pages_per_file: int = 1   # blocks (slots) per file
    pages_per_block: int = 1  # pages per slot — fixes the slot byte size
    # Hybrid attention geometry: per-group file contents depend on the
    # window size and the full/SWA layer split, so both enter the
    # fingerprint (when set) — a redeploy with a different window must not
    # resume from the old run's KV.
    sliding_window: Optional[int] = None
    swa_layers: tuple = ()
    # Streams per slab: 2 (K,V) for standard attention, 1 for MLA (the
    # latent IS the payload; there is no V stream).
    kv_streams: int = 2
    # StreamingLLM sinks: the sink mask changes deeper layers' KV for
    # positions past the window, so stores written with and without sinks
    # are byte-incompatible and must not share a directory.
    attention_sinks: int = 0
    # End-to-end integrity of the file payload: "crc32" appends a per-slot
    # CRC32 footer (resilience.integrity) verified on load; "none" writes
    # the bare payload. Fingerprinted: footer-bearing and bare files must
    # never share a directory, or readers would mis-size every load.
    integrity: str = "crc32"
    engine: str = "kvtpu"
    mesh_sizes: dict[str, int] = field(
        default_factory=lambda: {"tp_size": 1, "pp_size": 1, "dp_size": 1, "sp_size": 1}
    )
    rank: int = 0
    parallel_agnostic: bool = False


class FileMapper:
    """Maps block hashes to file paths."""

    def __init__(self, cfg: FileMapperConfig):
        self.cfg = cfg
        self._fingerprint = self._compute_fingerprint()
        safe_model = "".join(
            c if c.isalnum() or c in "-_." else "_" for c in cfg.model_name
        )
        self._base = os.path.join(cfg.root, f"{safe_model}_{self._fingerprint}")
        if cfg.parallel_agnostic:
            self._rank_dir = self._base
        else:
            self._rank_dir = f"{self._base}_r{cfg.rank}"

    def _compute_fingerprint(self) -> str:
        c = self.cfg
        payload = {
            "model": c.model_name,
            "dtype": c.dtype,
            "page_size": c.page_size,
            "kv_heads": c.kv_heads,
            "head_dim": c.head_dim,
            "num_layers": c.num_layers,
            "pages_per_file": c.pages_per_file,
            # Slab byte order: [layers, 2, pages, kv_heads, page_size, hd]
            # (heads-major pages — the Mosaic-tileable cache layout). Keyed
            # so stores written under the older page_size-major layout
            # resolve to a different directory instead of mixing formats.
            "kv_layout": "nkpd",
            # Only when non-default: a (N,1) store's on-disk layout is
            # byte-identical to the pre-pages_per_block format, and existing
            # deployments must keep resolving to the same directory.
            **({"pages_per_block": c.pages_per_block}
               if c.pages_per_block != 1 else {}),
            **({"sliding_window": c.sliding_window,
                "swa_layers": sorted(c.swa_layers)}
               if c.sliding_window is not None else {}),
            # Only when non-default (MLA's single latent stream): existing
            # two-stream deployments keep resolving to the same directory.
            **({"kv_streams": c.kv_streams} if c.kv_streams != 2 else {}),
            **({"attention_sinks": c.attention_sinks}
               if c.attention_sinks else {}),
            # Only when enabled (the default): checksummed and bare formats
            # differ in file size, so they must hash apart; "none" keeps
            # resolving wherever pre-integrity deployments wrote.
            **({"integrity": c.integrity} if c.integrity != "none" else {}),
            "engine": c.engine,
            **({k: v for k, v in sorted(c.mesh_sizes.items())}
               if not c.parallel_agnostic else {}),
        }
        digest = hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()
        ).hexdigest()
        return digest[:12]

    @property
    def fingerprint(self) -> str:
        return self._fingerprint

    @property
    def base_dir(self) -> str:
        return self._rank_dir

    def config_path(self) -> str:
        return os.path.join(self._base, "config.json")

    def write_run_config(self) -> None:
        """Persist the run metadata next to the store (idempotent)."""
        os.makedirs(self._base, exist_ok=True)
        path = self.config_path()
        if os.path.exists(path):
            return
        c = self.cfg
        # Durable publish (atomic_io): a crash right after os.replace must
        # not leave a zero-length/partial config — loaders treat a corrupt
        # config.json as a foreign store and refuse to serve it.
        atomic_write_bytes(
            path,
            json.dumps(
                {
                    "model": c.model_name,
                    "dtype": c.dtype,
                    "page_size": c.page_size,
                    "kv_heads": c.kv_heads,
                    "head_dim": c.head_dim,
                    "num_layers": c.num_layers,
                    "pages_per_file": c.pages_per_file,
                    "pages_per_block": c.pages_per_block,
                    "kv_layout": "nkpd",
                    "kv_streams": c.kv_streams,
                    "attention_sinks": c.attention_sinks,
                    "integrity": c.integrity,
                    "engine": c.engine,
                    "mesh_sizes": c.mesh_sizes,
                    "fingerprint": self._fingerprint,
                },
                indent=2,
            ).encode("utf-8"),
        )

    def block_path(self, block_hash: int, group_idx: int = 0) -> str:
        """Path of the file holding a block (hash masked to 64 bits).

        Two-level hex bucketing keeps directory fanout bounded at scale
        (reference ``file_mapper.py:112-143``).
        """
        h = block_hash & 0xFFFFFFFFFFFFFFFF
        hex16 = f"{h:016x}"
        return os.path.join(
            self._rank_dir, hex16[:3], f"{hex16[3:5]}_g{group_idx}", f"{hex16}.bin"
        )

    def tmp_path(self, block_hash: int, group_idx: int = 0,
                 unique_suffix: Optional[str] = None) -> str:
        """Unique temp path beside the final file for atomic rename."""
        suffix = unique_suffix if unique_suffix is not None else str(os.getpid())
        return self.block_path(block_hash, group_idx) + f".tmp.{suffix}"

    @staticmethod
    def parse_block_path(path: str) -> Optional[tuple[int, int]]:
        """Reverse mapping for the evictor: path → (block_hash, group_idx)."""
        name = os.path.basename(path)
        if not name.endswith(".bin"):
            return None
        try:
            block_hash = int(name[:-4], 16)
        except ValueError:
            return None
        parent = os.path.basename(os.path.dirname(path))
        group_idx = 0
        if "_g" in parent:
            try:
                group_idx = int(parent.split("_g")[-1])
            except ValueError:
                group_idx = 0
        return block_hash, group_idx
