"""vLLM ``OffloadingSpec`` shim: makes this repo's offload data plane
loadable by a vLLM-TPU pod.

Counterpart of reference ``llmd_fs_backend/spec.py:42-170``
(``SharedStorageOffloadingSpec``): vLLM's ``OffloadingConnector`` loads the
class named in ``kv_connector_extra_config`` and asks it for (a) the
scheduler-side ``OffloadingManager`` and (b) the worker-side
``OffloadingHandler`` pairs. This module adapts those contracts onto the
existing TPU-native pieces — ``SharedStorageOffloadSpec`` (fingerprinted
layout), ``SharedStorageOffloadManager`` (stateless filesystem manager),
``OffloadHandlers`` (device gather → native I/O pool) — so the same files
written by this repo's MiniEngine are readable by a vLLM pod and vice
versa.

Import-guarded: importing this module requires ``vllm`` (the real package
or a test double injected via ``sys.modules``, the reference's own CPU
test pattern — ``tests/cpu/test_storage_events.py:20-60``). Nothing else
in ``llmd_kv_cache_tpu`` imports it.

vLLM job-id discipline (reference ``worker.py:326-405``): the caller
assigns ``job_id`` in ``transfer_async``; our native pool assigns its own.
The handler keeps the two-way mapping and translates on ``get_finished``.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

try:
    from vllm.v1.kv_offload.base import (  # type: ignore
        LoadStoreSpec,
        OffloadingManager,
        OffloadingSpec,
        PrepareStoreOutput,
    )
    from vllm.v1.kv_offload.worker.worker import (  # type: ignore
        OffloadingHandler,
        TransferResult,
    )
    import vllm.v1.kv_offload.base as _vllm_base  # type: ignore
except ImportError as e:  # pragma: no cover - exercised only without vllm
    raise ImportError(
        "llmd_kv_cache_tpu.offload.vllm_spec requires vllm (or a test "
        "double registered in sys.modules before import); the rest of the "
        "offload package works without it"
    ) from e

from ..utils.logging import get_logger
from .spec import SharedStorageOffloadSpec

logger = get_logger("offload.vllm_spec")

# GPULoadStoreSpec lives in base in current vLLM; fall back to a local
# marker class so the handler-pair tuple stays well-formed against older
# or stubbed layouts.
GPULoadStoreSpec = getattr(_vllm_base, "GPULoadStoreSpec", None)
if GPULoadStoreSpec is None:  # pragma: no cover - stub layouts only
    class GPULoadStoreSpec:  # type: ignore[no-redef]
        def __init__(self, block_ids):
            self.block_ids = list(block_ids)

# Optional key helpers (hybrid-model group routing). Identity fallbacks
# keep plain-int keys working against minimal stubs.
_block_hash = getattr(_vllm_base, "get_offload_block_hash", None) or (
    lambda key: key)
_group_idx = getattr(_vllm_base, "get_offload_group_idx", None) or (
    lambda key: 0)

DEFAULT_STORAGE_BLOCK_SIZE = 256  # tokens per offloaded file (ref spec.py:39)


class TPUSharedStorageLoadStoreSpec(LoadStoreSpec):
    """Storage-side transfer spec: the offload keys of one transfer.

    Reference ``mediums.py:SharedStorageLoadStoreSpec``."""

    def __init__(self, keys):
        self.keys = list(keys)

    def __repr__(self) -> str:
        return repr(self.keys)

    @staticmethod
    def medium() -> str:
        return "SHARED_STORAGE"


class TPUOffloadingManager(OffloadingManager):
    """Scheduler-side adapter over ``SharedStorageOffloadManager``.

    Stateless like the reference (``manager.py``): lookup is file
    existence (touching atime for the evictor), stores are idempotent,
    eviction belongs to the storage-side evictor."""

    def __init__(self, inner):
        self.inner = inner

    def lookup(self, key, req_context=None):
        # Current vLLM contract (reference manager.py:100-105): single
        # key -> bool. Older generations passed an iterable of keys and
        # sliced by the returned hit-prefix length — accept both.
        if isinstance(key, (list, tuple)):
            counts = {}
            for k in key:
                g = _group_idx(k)
                if g not in counts:
                    counts[g] = self.inner.lookup(
                        [_block_hash(k2) for k2 in key
                         if _group_idx(k2) == g], g)
            return min(counts.values()) if counts else 0
        return self.inner.lookup([_block_hash(key)], _group_idx(key)) == 1

    def prepare_load(self, keys, req_context=None) -> LoadStoreSpec:
        return TPUSharedStorageLoadStoreSpec(keys)

    def touch(self, keys, req_context=None) -> None:
        # atime is touched by lookup's existence probe; nothing to do here
        # (reference manager.py "handled by the file thread").
        pass

    def complete_load(self, keys, req_context=None) -> None:
        self.inner.complete_load([_block_hash(k) for k in keys])

    def prepare_store(self, keys, req_context=None):
        # Shared storage always accepts; skip files already present
        # (stores are idempotent, the filter only saves device->host
        # traffic). PrepareStoreOutput carries the subset to write.
        # Freshness is per (group, hash): the same token block hashes
        # identically across a hybrid model's cache groups but lives in
        # per-group files.
        keys = list(keys)
        fresh: set[tuple[int, int]] = set()
        for g in {_group_idx(k) for k in keys}:
            fresh.update(
                (g, h) for h in self.inner.prepare_store(
                    [_block_hash(k) for k in keys if _group_idx(k) == g], g))
        to_store = [k for k in keys
                    if (_group_idx(k), _block_hash(k)) in fresh]
        return PrepareStoreOutput(
            keys_to_store=to_store,
            store_spec=TPUSharedStorageLoadStoreSpec(to_store),
            evicted_keys=[],
        )

    def complete_store(self, keys, req_context=None, success: bool = True):
        if success:
            self.inner.complete_store([_block_hash(k) for k in keys])

    def shutdown(self) -> None:
        publisher = getattr(self.inner, "event_publisher", None)
        if publisher is not None:
            publisher.close()


class _ResultMux:
    """Demultiplexes the shared engine's completions to the two direction
    handlers (store results to the store handler, loads to the load
    handler) — one ``OffloadHandlers`` engine serves both directions, so a
    poll from either side must not swallow the other side's results."""

    def __init__(self, handlers):
        self.handlers = handlers
        self._buffered: dict[bool, list] = {True: [], False: []}

    def drain(self, is_store: bool) -> list:
        for res in self.handlers.get_finished():
            self._buffered[res.is_store].append(res)
        out = self._buffered[is_store]
        self._buffered[is_store] = []
        return out


class _DirectionHandler(OffloadingHandler):
    """One transfer direction over the shared ``OffloadHandlers`` engine.

    Reference ``worker.py:326-405`` (GPUToStorageHandler /
    StorageToGPUHandler): ``transfer_async`` submits, ``get_finished``
    polls — with vLLM's caller-assigned job ids mapped onto the native
    pool's own ids."""

    def __init__(self, mux: _ResultMux, gpu_blocks_per_file: int,
                 is_store: bool, transfer_type):
        self.mux = mux
        self.handlers = mux.handlers
        self.gpu_blocks_per_file = gpu_blocks_per_file
        self.is_store = is_store
        self.transfer_type = transfer_type
        self._vllm_to_native: dict[int, int] = {}
        self._native_to_vllm: dict[int, int] = {}
        self._done: list = []  # translated results awaiting get_finished

    def _transfers(self, spec) -> list[tuple[int, list[int], int]]:
        """(block_hash, page_ids, group) triplets from a (src, dst) spec.

        The GPU side lists vLLM block ids (== this repo's page ids, one
        hash_block_size-token page each); the storage side lists offload
        keys, each covering ``gpu_blocks_per_file`` consecutive pages."""
        src, dst = spec
        gpu = src if self.is_store else dst
        storage = dst if self.is_store else src
        block_ids = [int(b) for b in gpu.block_ids]
        keys = storage.keys
        per = self.gpu_blocks_per_file
        if len(block_ids) != len(keys) * per:
            raise ValueError(
                f"transfer spec mismatch: {len(block_ids)} GPU blocks for "
                f"{len(keys)} offload keys x {per} blocks/file")
        return [
            (_block_hash(k), block_ids[i * per:(i + 1) * per], _group_idx(k))
            for i, k in enumerate(keys)
        ]

    def transfer_async(self, job_id: int, spec) -> bool:
        try:
            by_group: dict[int, list[tuple[int, list[int]]]] = {}
            for h, pages, g in self._transfers(spec):
                by_group.setdefault(g, []).append((h, pages))
            if len(by_group) != 1:
                # One native job per vLLM job keeps the id mapping 1:1;
                # multi-group transfers arrive as separate specs in vLLM
                # (per-group handlers), so this is a contract violation.
                raise ValueError(
                    f"transfer spans {len(by_group)} cache groups; expected 1")
            (group, transfers), = by_group.items()
            submit = (self.handlers.async_store_blocks if self.is_store
                      else self.handlers.async_load_blocks)
            native_id = submit(transfers, group_idx=group)
        except Exception:
            logger.exception("transfer_async failed (job_id=%d)", job_id)
            return False
        self._vllm_to_native[job_id] = native_id
        self._native_to_vllm[native_id] = job_id
        return True

    def _poll(self) -> None:
        """Translate newly-finished native results into ``_done``.

        Polling also applies load scatters (they run inside the engine's
        ``get_finished``), so ``wait`` must route through here rather than
        the engine's ``wait_job`` — that one is cancel-and-wait for
        preemption and would drop a completed load's H2D scatter."""
        for res in self.mux.drain(self.is_store):
            vllm_id = self._native_to_vllm.pop(res.job_id, None)
            if vllm_id is None:
                logger.warning("finished native job %d has no vLLM id",
                               res.job_id)
                continue
            self._vllm_to_native.pop(vllm_id, None)
            # A store whose writes were shed by the EMA queue limit did
            # not fully land; vLLM's binary result must not advertise it.
            success = res.success and not res.shed_hashes
            self._done.append(TransferResult(
                job_id=vllm_id,
                success=success,
                transfer_size=res.bytes_transferred,
                transfer_time=res.seconds,
                transfer_type=self.transfer_type,
            ))

    def get_finished(self) -> list:
        self._poll()
        out = self._done
        self._done = []
        return out

    def wait(self, job_ids, timeout_s: float = 60.0) -> None:
        """Block until the given vLLM jobs complete (reference
        ``worker.py:166-174``). Results stay queued for ``get_finished``."""
        import time as _time

        pending = {j for j in job_ids if j in self._vllm_to_native}
        deadline = _time.monotonic() + timeout_s
        while pending:
            self._poll()
            pending = {j for j in pending if j in self._vllm_to_native}
            if not pending:
                break
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"transfers {sorted(pending)} still in flight after "
                    f"{timeout_s}s")
            _time.sleep(0.001)


class TPUStorageOffloadingSpec(OffloadingSpec):
    """vLLM entry point: shared-storage offload for TPU pods.

    Reference ``spec.py:42-170``. Configure via
    ``kv_transfer_config.kv_connector_extra_config``:

    - ``shared_storage_path`` (default ``/tmp/shared-kv``)
    - ``block_size`` — tokens per offloaded file (default 256); must be a
      multiple of the GPU hash block size (this repo's page size)
    - ``threads_per_gpu``, ``read_preferring_ratio``,
      ``max_write_queued_seconds`` — native I/O pool knobs
    - geometry keys consumed by ``SharedStorageOffloadSpec.from_extra_config``
      (num_layers, kv_heads, head_dim, dtype, sliding_window, kv_streams, ...)
    """

    def __init__(self, vllm_config, kv_cache_config):
        try:
            super().__init__(vllm_config, kv_cache_config)
        except TypeError:  # minimal stubs whose base takes no args  # lint: allow-swallow
            pass
        self.vllm_config = vllm_config
        self.kv_cache_config = kv_cache_config

        # The real base class supplies extra_config/hash_block_size; keep
        # working against stubs (and older vLLMs) by deriving them.
        if not hasattr(self, "extra_config"):
            ktc = getattr(vllm_config, "kv_transfer_config", None)
            self.extra_config = dict(
                getattr(ktc, "kv_connector_extra_config", None) or {})
        if not hasattr(self, "hash_block_size"):
            cache_cfg = getattr(vllm_config, "cache_config", None)
            self.hash_block_size = int(
                self.extra_config.get(
                    "page_size", getattr(cache_cfg, "block_size", 16)))

        self.offloaded_block_size = int(
            self.extra_config.get("block_size", DEFAULT_STORAGE_BLOCK_SIZE))
        if self.offloaded_block_size % self.hash_block_size != 0:
            raise ValueError(
                f"block_size ({self.offloaded_block_size}) must be a "
                f"multiple of the hash block size ({self.hash_block_size})")
        self.gpu_blocks_per_file = (
            self.offloaded_block_size // self.hash_block_size)
        # vLLM sizes its offload-key granularity from this factor.
        self.block_size_factor = self.gpu_blocks_per_file

        extra = dict(self.extra_config)
        extra.setdefault("root", extra.pop("shared_storage_path",
                                           "/tmp/shared-kv"))
        extra.setdefault("page_size", self.hash_block_size)
        extra.setdefault("io_threads",
                         int(extra.pop("threads_per_gpu", 16)))
        model_cfg = getattr(vllm_config, "model_config", None)
        if model_cfg is not None:
            extra.setdefault("model_name", getattr(model_cfg, "model",
                                                   "unknown"))
        extra["pages_per_block"] = self.gpu_blocks_per_file
        extra["blocks_per_file"] = 1  # one content-addressed file per key
        self.inner = SharedStorageOffloadSpec.from_extra_config(extra)

        self._manager: Optional[TPUOffloadingManager] = None
        self._handlers = None

    # -- scheduler side --

    def get_manager(self) -> OffloadingManager:
        if self._manager is None:
            self._manager = TPUOffloadingManager(self.inner.get_manager())
        return self._manager

    # -- worker side --

    def get_handlers(self, kv_caches) -> Iterator[tuple]:
        """Yield (src spec type, dst spec type, handler) per direction.

        ``kv_caches``: the worker's cache pools. TPU-native contract: a
        ``(k_cache, v_cache)`` pair of jax arrays ``[layers, pages,
        kv_heads, page_size, head_dim]`` or a sequence of such pairs (one
        per cache group, hybrid models)."""
        if self._handlers is None:
            pairs = kv_caches
            if (isinstance(pairs, Sequence) and len(pairs) == 2
                    and not isinstance(pairs[0], Sequence)):
                pairs = [pairs]
            first_k, first_v = pairs[0]
            handlers = self.inner.get_handlers(first_k, first_v)
            if len(pairs) > 1:
                from .tpu_copier import TPUBlockCopier

                for g, (k, v) in enumerate(pairs[1:], start=1):
                    handlers.copiers[g] = TPUBlockCopier(k, v)
            self._handlers = handlers

            self._mux = _ResultMux(handlers)

        yield (
            GPULoadStoreSpec,
            TPUSharedStorageLoadStoreSpec,
            _DirectionHandler(self._mux, self.gpu_blocks_per_file,
                              is_store=True,
                              transfer_type=("gpu", "storage")),
        )
        yield (
            TPUSharedStorageLoadStoreSpec,
            GPULoadStoreSpec,
            _DirectionHandler(self._mux, self.gpu_blocks_per_file,
                              is_store=False,
                              transfer_type=("storage", "gpu")),
        )
