"""Worker-side offload handlers: device ↔ storage transfer execution.

Counterpart of reference ``llmd_fs_backend/worker.py`` + the C++
``StorageOffloadEngine`` job lifecycle (``storage_offload.cpp``): async
store/load jobs over groups of KV pages, completion polling, cancellation,
per-job throughput accounting. The device↔host leg is JAX/XLA
(``tpu_copier``); the host↔file leg is the native I/O pool (``native``).

Store: gather pages → host slab (D2H DMA) → queue atomic file write.
Load:  queue file read into a host buffer → on completion, H2D + scatter.
Loads are processed by read-preferring workers at high priority; writes
may be shed under sustained pressure (EMA limit), degrading to future
cache misses rather than latency.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..utils.logging import get_logger
from .file_mapper import FileMapper
from .native import STATUS_OK, STATUS_PENDING, NativeIOEngine
from .tpu_copier import TPUBlockCopier

logger = get_logger("offload.worker")


@dataclass
class TransferResult:
    job_id: int
    success: bool
    is_store: bool
    bytes_transferred: int = 0
    seconds: float = 0.0
    # Block hashes whose writes were shed by the EMA queue limit (stores
    # only): these blocks are NOT on disk and must not be advertised.
    shed_hashes: list = field(default_factory=list)

    @property
    def shed_blocks(self) -> int:
        return len(self.shed_hashes)

    @property
    def throughput_gbps(self) -> float:
        if self.seconds <= 0:
            return 0.0
        return self.bytes_transferred / self.seconds / 1e9


@dataclass
class _PendingJob:
    job_id: int
    is_store: bool
    started: float
    nbytes: int
    shed_hashes: list = field(default_factory=list)
    # Keep host buffers alive until the native engine is done with them.
    buffers: list = field(default_factory=list)
    # Loads: (buffer, page_ids) to scatter on completion.
    scatters: list = field(default_factory=list)


class OffloadHandlers:
    """Bidirectional transfer engine for one worker (one device's caches)."""

    def __init__(
        self,
        copier: TPUBlockCopier,
        mapper: FileMapper,
        io_threads: int = 4,
        read_preferring_ratio: float = 0.75,
        max_write_queued_seconds: float = 10.0,
        numa_node: int = -1,
        staging_bytes: Optional[int] = None,
        direct_io: bool = False,
    ):
        self.copier = copier
        self.mapper = mapper
        read_pref = max(1, int(io_threads * read_preferring_ratio))
        if staging_bytes is None:
            # Size each worker's pinned staging to one single-page slab,
            # floored at 1 MiB (the reference sizes per-thread staging to
            # the largest-group file, thread_pool.cpp:134-144; our files
            # hold one canonical block each).
            staging_bytes = max(copier.slab_nbytes(1), 1 << 20)
        self.io = NativeIOEngine(
            num_threads=io_threads,
            read_preferring_workers=read_pref,
            max_write_queued_seconds=max_write_queued_seconds,
            numa_node=numa_node,
            staging_bytes=staging_bytes,
            direct_io=direct_io,
        )
        self._pending: dict[int, _PendingJob] = {}
        self._lock = threading.Lock()

    # -- store path --

    def async_store_blocks(
        self,
        transfers: Sequence[tuple[int, Sequence[int]]],  # (block_hash, page_ids)
        group_idx: int = 0,
    ) -> int:
        """Start an async store job; returns the job id.

        Each (block_hash, page_ids) pair becomes one content-addressed
        file. The device-side gather + D2H happens here (synchronous with
        respect to the device stream, overlapped across files); file writes
        are queued on the native pool.
        """
        job_id = self.io.begin_job()
        job = _PendingJob(job_id=job_id, is_store=True, started=time.perf_counter(),
                          nbytes=0)
        suffix = uuid.uuid4().hex[:8]
        # One device program + one D2H transfer for the whole job.
        slabs = self.copier.gather_many_to_host(
            [list(page_ids) for _, page_ids in transfers]
        )
        for (block_hash, _page_ids), slab in zip(transfers, slabs):
            queued = self.io.submit_write(
                job_id,
                self.mapper.block_path(block_hash, group_idx),
                self.mapper.tmp_path(block_hash, group_idx, unique_suffix=suffix),
                slab,
            )
            if queued:
                job.buffers.append(slab)
                job.nbytes += slab.nbytes
            else:
                job.shed_hashes.append(block_hash)
        self.io.seal_job(job_id)
        with self._lock:
            self._pending[job_id] = job
        return job_id

    # -- load path --

    def async_load_blocks(
        self,
        transfers: Sequence[tuple[int, Sequence[int]]],
        group_idx: int = 0,
    ) -> int:
        """Start an async load job; returns the job id.

        File reads land in host buffers on the native pool (high
        priority); the H2D scatter happens when the caller polls
        ``get_finished`` and the job is complete.
        """
        job_id = self.io.begin_job()
        job = _PendingJob(job_id=job_id, is_store=False, started=time.perf_counter(),
                          nbytes=0)
        for block_hash, page_ids in transfers:
            buf = np.empty(self.copier.slab_nbytes(len(page_ids)), np.uint8)
            self.io.submit_read(
                job_id, self.mapper.block_path(block_hash, group_idx), buf
            )
            job.buffers.append(buf)
            job.scatters.append((buf, list(page_ids)))
            job.nbytes += buf.nbytes
        self.io.seal_job(job_id)
        with self._lock:
            self._pending[job_id] = job
        return job_id

    # -- completion --

    def get_finished(self) -> list[TransferResult]:
        """Poll completed jobs; apply load scatters; release buffers."""
        results = []
        for job_id, status in self.io.poll_finished():
            with self._lock:
                job = self._pending.pop(job_id, None)
            if job is None:
                continue
            success = status == STATUS_OK
            if success and not job.is_store:
                self.copier.scatter_many_from_host([
                    (
                        np.frombuffer(buf, dtype=self.copier.dtype).reshape(
                            self.copier.slab_shape(len(page_ids))
                        ),
                        page_ids,
                    )
                    for buf, page_ids in job.scatters
                ])
            elif not success and not job.is_store:
                logger.warning("load job %d failed (status %d)", job_id, status)
            elif not success:
                logger.warning("store job %d failed (status %d)", job_id, status)
            results.append(
                TransferResult(
                    job_id=job_id,
                    success=success,
                    is_store=job.is_store,
                    bytes_transferred=job.nbytes if success else 0,
                    seconds=time.perf_counter() - job.started,
                    shed_hashes=job.shed_hashes,
                )
            )
        return results

    def wait_job(self, job_id: int, timeout_s: float = 30.0) -> int:
        """Cancel-and-wait for preemption (request aborted mid-transfer)."""
        status = self.io.wait_job(job_id, timeout_s)
        if status != STATUS_PENDING:
            # Only release the host buffers once the native side has truly
            # drained: a timed-out job may still have an in-flight read
            # holding raw pointers into them.
            with self._lock:
                self._pending.pop(job_id, None)
        else:
            logger.warning(
                "job %d still in flight after cancel timeout; parking buffers",
                job_id,
            )
        return status

    def shutdown(self) -> None:
        self.io.close()
