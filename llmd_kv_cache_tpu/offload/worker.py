"""Worker-side offload handlers: device ↔ storage transfer execution.

Counterpart of reference ``llmd_fs_backend/worker.py`` + the C++
``StorageOffloadEngine`` job lifecycle (``storage_offload.cpp``): async
store/load jobs over groups of KV pages, completion polling, cancellation,
per-job throughput accounting. The device↔host leg is JAX/XLA
(``tpu_copier``); the host↔file leg is the native I/O pool (``native``).

Store: gather pages → host slab (D2H DMA) → queue atomic file write.
Load:  queue file read into a host buffer → on completion, H2D + scatter.
Loads are processed by read-preferring workers at high priority; writes
may be shed under sustained pressure (EMA limit), degrading to future
cache misses rather than latency.
"""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..utils.lockdep import new_lock
from ..resilience.failpoints import FaultInjected, failpoints
from ..resilience.integrity import (
    IntegrityError,
    build_footer,
    footer_size,
    parse_footer,
    slot_crcs,
)
from ..resilience.policy import RetryPolicy
from ..telemetry import current_traceparent, flight_recorder, tracer
from ..telemetry.flight_recorder import KIND_OFFLOAD, KIND_RETRY
from ..utils.logging import get_logger
from .file_mapper import FileMapper
from .native import (
    STATUS_CANCELLED,
    STATUS_IO_ERROR,
    STATUS_OK,
    STATUS_PENDING,
    NativeIOEngine,
)
from .tpu_copier import TPUBlockCopier

logger = get_logger("offload.worker")

# Failpoints on the offload data plane (docs/resilience.md):
#   - io_error pair: force a completed job's status to IO_ERROR, exercising
#     the retry/backoff path without touching the native pool;
#   - torn: corrupt the written payload AFTER its checksums are computed,
#     simulating a torn write / bitrot that only load-time verification
#     can catch.
FP_STORE_IO_ERROR = "offload.store.io_error"
FP_LOAD_IO_ERROR = "offload.load.io_error"
FP_STORE_TORN = "offload.store.torn"

QUARANTINE_SUFFIX = ".quarantine"


@dataclass
class TransferResult:
    job_id: int
    success: bool
    is_store: bool
    bytes_transferred: int = 0
    seconds: float = 0.0
    # Block hashes whose writes were shed by the EMA queue limit (stores
    # only): these blocks are NOT on disk and must not be advertised.
    shed_hashes: list = field(default_factory=list)
    # Loads: file keys whose checksum verification failed. The files have
    # been quarantined on disk; the caller must de-advertise the blocks.
    corrupt_hashes: list = field(default_factory=list)
    # Submission rounds the job took (1 = no retry).
    attempts: int = 1

    @property
    def shed_blocks(self) -> int:
        return len(self.shed_hashes)

    @property
    def throughput_gbps(self) -> float:
        if self.seconds <= 0:
            return 0.0
        return self.bytes_transferred / self.seconds / 1e9


@dataclass
class _StoreUnit:
    """One file write of a store job (payload with footer pre-appended)."""

    key: int
    buf: "np.ndarray"


@dataclass
class _LoadUnit:
    """One file's reads within a load job.

    ``payload`` covers file slots ``[slot_lo, slot_lo + covered)`` of a
    file with ``num_slots`` total slots; ``footer`` (when integrity is on)
    receives the checksum footer read from the file tail.
    """

    key: int
    payload: "np.ndarray"
    footer: Optional["np.ndarray"]
    slot_lo: int
    covered: int
    num_slots: int
    # (buffer_slice, page_ids) pairs to scatter once verified.
    scatters: list = field(default_factory=list)


@dataclass
class _PendingJob:
    job_id: int  # current native job id (changes across retries)
    report_id: int  # job id the caller polls/waits on (first native id)
    is_store: bool
    started: float
    nbytes: int
    attempt: int = 1
    shed_hashes: list = field(default_factory=list)
    # Keep host buffers alive until the native engine is done with them.
    buffers: list = field(default_factory=list)
    store_units: list = field(default_factory=list)
    load_units: list = field(default_factory=list)
    group_idx: int = 0  # cache group the job's pages belong to
    # An injected submission fault left part of the job unqueued; the job
    # must complete as failed even if every queued op succeeded.
    submit_failed: bool = False
    # Submitter's W3C trace context, captured at submission so the
    # completion span joins the trace that caused the transfer.
    traceparent: Optional[str] = None


@dataclass
class FileSpan:
    """One file's slice of a multi-block transfer.

    A file holds ``blocks_per_file`` logically-consecutive blocks in fixed
    slots; a span addresses the consecutive slots
    ``[head_offset, head_offset + len(blocks))`` of the file keyed by
    ``file_key``. ``blocks[i]`` is the page-id list of slot
    ``head_offset + i``.
    """

    file_key: int
    head_offset: int
    blocks: list


def map_blocks_to_file_spans(
    file_keys: Sequence[int],
    start_block_idx: int,
    blocks: Sequence[Sequence[int]],
    blocks_per_file: int,
) -> list[FileSpan]:
    """Split logically-consecutive blocks into per-file spans.

    Files are aligned at multiples of ``blocks_per_file`` in logical block
    space; a transfer may start AND/OR end mid-file (the reference's
    unaligned head/tail mapping, ``worker.py:187-255``). ``file_keys`` has
    one key per file the range [start_block_idx, +len(blocks)) intersects.
    """
    if not blocks:
        return []
    bpf = blocks_per_file
    end_block_idx = start_block_idx + len(blocks)
    start_file_idx = start_block_idx // bpf
    num_files = (end_block_idx - 1) // bpf + 1 - start_file_idx
    if len(file_keys) != num_files:
        raise ValueError(
            f"range [{start_block_idx}, {end_block_idx}) spans {num_files} "
            f"files of {bpf} blocks, got {len(file_keys)} keys"
        )
    spans = []
    consumed = 0
    for f_idx, key in enumerate(file_keys):
        file_lo = (start_file_idx + f_idx) * bpf
        slice_lo = max(start_block_idx, file_lo)
        slice_hi = min(end_block_idx, file_lo + bpf)
        spans.append(FileSpan(
            file_key=key,
            head_offset=slice_lo - file_lo,
            blocks=[list(b) for b in blocks[consumed:consumed + slice_hi - slice_lo]],
        ))
        consumed += slice_hi - slice_lo
    return spans



def check_span(span: FileSpan, blocks_per_file: int,
               pages_per_block: int) -> None:
    """Validate one span against the fixed file geometry (shared by the
    POSIX and object-store backends)."""
    if span.head_offset + len(span.blocks) > blocks_per_file:
        raise ValueError(
            f"span [{span.head_offset}, "
            f"{span.head_offset + len(span.blocks)}) exceeds "
            f"{blocks_per_file} slots")
    for b in span.blocks:
        if len(b) != pages_per_block:
            raise ValueError(
                f"block has {len(b)} pages, file layout expects "
                f"{pages_per_block}")


def validate_store_coverage(
    spans: Sequence[FileSpan], blocks_per_file: int, pages_per_block: int
) -> dict[int, list[FileSpan]]:
    """Group spans by file and enforce the durability rule: every touched
    file/object must be FULLY covered by its spans' union — lookup treats
    existence as "stored" and writes publish atomically, so a partially-
    provisioned file would serve holes as successful loads. Returns the
    per-file grouping."""
    by_file: dict[int, list[FileSpan]] = {}
    for span in spans:
        check_span(span, blocks_per_file, pages_per_block)
        by_file.setdefault(span.file_key, []).append(span)
    for file_key, file_spans in by_file.items():
        slots: list[int] = []
        for lo, hi in sorted((s.head_offset, s.head_offset + len(s.blocks))
                             for s in file_spans):
            slots.extend(range(lo, hi))
        if slots != list(range(blocks_per_file)):
            raise ValueError(
                f"store for file {file_key:#x} covers slots {slots}, "
                f"need all of 0..{blocks_per_file - 1} (files "
                "publish atomically; partial stores are not durable)")
    return by_file


def assemble_file_buffers(
    spans: Sequence[FileSpan], slabs: Sequence, expected_file_bytes: int
) -> dict[int, "np.ndarray"]:
    """Concatenate per-block slabs into one contiguous uint8 buffer per
    file, slots ordered by head offset. ``slabs`` aligns with the spans'
    flattened block lists (the gather output)."""
    file_parts: dict[int, list[tuple[int, list]]] = {}
    i = 0
    for span in spans:
        part = slabs[i:i + len(span.blocks)]
        i += len(span.blocks)
        file_parts.setdefault(span.file_key, []).append(
            (span.head_offset, part))
    out: dict[int, "np.ndarray"] = {}
    for file_key, parts in file_parts.items():
        flat = [
            np.ascontiguousarray(s).view(np.uint8).reshape(-1)
            for _off, ss in sorted(parts, key=lambda p: p[0])
            for s in ss
        ]
        buf = flat[0] if len(flat) == 1 else np.concatenate(flat)
        assert buf.nbytes == expected_file_bytes, (
            f"file {file_key:#x}: assembled {buf.nbytes} B, layout "
            f"expects {expected_file_bytes} B")
        out[file_key] = buf
    return out


class OffloadHandlers:
    """Bidirectional transfer engine for one worker (one device's caches)."""

    def __init__(
        self,
        copier: TPUBlockCopier,
        mapper: FileMapper,
        io_threads: int = 4,
        read_preferring_ratio: float = 0.75,
        max_write_queued_seconds: float = 10.0,
        numa_node: int = -1,
        staging_bytes: Optional[int] = None,
        direct_io: bool = False,
        blocks_per_file: int = 1,
        pages_per_block: int = 1,
        copiers: Optional[dict[int, TPUBlockCopier]] = None,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self.copier = copier
        # Per-cache-group copiers (hybrid models: group 0 full-attention
        # pool, group 1 SWA pool); group 0 defaults to ``copier``.
        self.copiers: dict[int, TPUBlockCopier] = {0: copier}
        if copiers:
            self.copiers.update(copiers)
        self.mapper = mapper
        # Multi-block file geometry (reference spec.py:76-89): files hold
        # blocks_per_file consecutive blocks in fixed slots of
        # pages_per_block pages each.
        self.blocks_per_file = blocks_per_file
        self.pages_per_block = pages_per_block
        self.slot_bytes = copier.slab_nbytes(pages_per_block)
        self.file_bytes = self.slot_bytes * blocks_per_file
        # Recycled host destinations for load jobs (reference
        # _StagedBackend pool; see offload.staging). Slots are sized to
        # the largest read unit ANY group's copier issues — a hybrid
        # model's SWA pool can have more layers than group 0, and a slot
        # sized for group 0 alone would push every group-1 load onto the
        # transient-allocation path the pool exists to eliminate.
        from .staging import HostStagingPool, pool_size_for

        max_slot = max(
            c.slab_nbytes(pages_per_block) * blocks_per_file
            for c in self.copiers.values())
        self.staging = HostStagingPool(
            slot_bytes=max_slot, slots=pool_size_for(io_threads))
        read_pref = max(1, int(io_threads * read_preferring_ratio))
        if staging_bytes is None:
            # Size each worker's pinned staging to one single-page slab,
            # floored at 1 MiB (the reference sizes per-thread staging to
            # the largest-group file, thread_pool.cpp:134-144; our files
            # hold one canonical block each).
            staging_bytes = max(copier.slab_nbytes(1), 1 << 20)
        self.io = NativeIOEngine(
            num_threads=io_threads,
            read_preferring_workers=read_pref,
            max_write_queued_seconds=max_write_queued_seconds,
            numa_node=numa_node,
            staging_bytes=staging_bytes,
            direct_io=direct_io,
        )
        self._pending: dict[int, _PendingJob] = {}
        self._lock = new_lock()
        # Integrity: when the mapper's format carries a CRC footer, stores
        # append it and loads verify it (docs/resilience.md).
        self.integrity = getattr(mapper.cfg, "integrity", "none") == "crc32"
        # Transient I/O failures are retried with jittered backoff; the
        # default is deliberately short — offload is a cache, so a job that
        # keeps failing should fail fast and let the request path move on.
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=2, base_delay_s=0.05, max_delay_s=0.5
        )
        # Jobs awaiting resubmission: (due_monotonic, job). Flushed at the
        # top of get_finished; report_id maps to -1 while a job sits here.
        self._retry_q: list[tuple[float, _PendingJob]] = []
        self._by_report: dict[int, int] = {}

    def footer_bytes(self, num_slots: Optional[int] = None) -> int:
        """On-disk footer overhead per file (0 when integrity is off)."""
        if not self.integrity:
            return 0
        return footer_size(self.blocks_per_file if num_slots is None else num_slots)

    def _with_footer(self, payload: "np.ndarray", num_slots: int) -> "np.ndarray":
        """Append the CRC footer to a file payload (one host copy).

        The native writer needs one contiguous buffer for the atomic
        tmp+rename write, so payload and footer are concatenated; the
        ``offload.store.torn`` failpoint corrupts a payload byte *after*
        checksumming to stage a torn-write for load-time verification.
        """
        flat = payload.view(np.uint8).reshape(-1)
        slot = flat.nbytes // num_slots
        crcs = slot_crcs([flat[i * slot:(i + 1) * slot] for i in range(num_slots)])
        buf = np.concatenate([flat, np.frombuffer(build_footer(crcs), np.uint8)])
        if failpoints.should_fire(FP_STORE_TORN):
            buf[flat.nbytes // 2] ^= 0xFF
            logger.warning("failpoint %s tore a store payload", FP_STORE_TORN)
        return buf

    # -- store path --

    def async_store_blocks(
        self,
        transfers: Sequence[tuple[int, Sequence[int]]],  # (block_hash, page_ids)
        group_idx: int = 0,
    ) -> int:
        """Start an async store job; returns the job id.

        Each (block_hash, page_ids) pair becomes one content-addressed
        file. The device-side gather + D2H happens here (synchronous with
        respect to the device stream, overlapped across files); file writes
        are queued on the native pool.
        """
        copier = self.copiers[group_idx]
        job_id = self.io.begin_job()
        job = _PendingJob(job_id=job_id, report_id=job_id, is_store=True,
                          started=time.perf_counter(), nbytes=0,
                          group_idx=group_idx,
                          traceparent=current_traceparent())
        suffix = uuid.uuid4().hex[:8]
        # One device program + one D2H transfer for the whole job.
        slabs = copier.gather_many_to_host(
            [list(page_ids) for _, page_ids in transfers]
        )
        for (block_hash, _page_ids), slab in zip(transfers, slabs):
            # Block-mode files hold exactly one block: one checksum slot.
            buf = self._with_footer(slab, 1) if self.integrity else slab
            try:
                queued = self.io.submit_write(
                    job_id,
                    self.mapper.block_path(block_hash, group_idx),
                    self.mapper.tmp_path(block_hash, group_idx, unique_suffix=suffix),
                    buf,
                )
            except FaultInjected:
                job.submit_failed = True
                job.store_units.append(_StoreUnit(key=block_hash, buf=buf))
                continue
            if queued:
                job.buffers.append(buf)
                job.store_units.append(_StoreUnit(key=block_hash, buf=buf))
                job.nbytes += slab.nbytes
            else:
                job.shed_hashes.append(block_hash)
        self.io.seal_job(job_id)
        self._register(job)
        return job_id

    # -- load path --

    def async_load_blocks(
        self,
        transfers: Sequence[tuple[int, Sequence[int]]],
        group_idx: int = 0,
    ) -> int:
        """Start an async load job; returns the job id.

        File reads land in host buffers on the native pool (high
        priority); the H2D scatter happens when the caller polls
        ``get_finished`` and the job is complete.
        """
        copier = self.copiers[group_idx]
        job_id = self.io.begin_job()
        job = _PendingJob(job_id=job_id, report_id=job_id, is_store=False,
                          started=time.perf_counter(), nbytes=0,
                          group_idx=group_idx,
                          traceparent=current_traceparent())
        for block_hash, page_ids in transfers:
            buf = self.staging.acquire(copier.slab_nbytes(len(page_ids)))
            footer = None
            if self.integrity:
                footer = self.staging.acquire(footer_size(1))
            unit = _LoadUnit(key=block_hash, payload=buf, footer=footer,
                             slot_lo=0, covered=1, num_slots=1,
                             scatters=[(buf, list(page_ids))])
            job.buffers.append(buf)
            if footer is not None:
                job.buffers.append(footer)
            job.load_units.append(unit)
            job.nbytes += buf.nbytes
            self._submit_load_unit(job, unit, group_idx)
        self.io.seal_job(job_id)
        self._register(job)
        return job_id

    def _submit_load_unit(self, job: _PendingJob, unit: _LoadUnit,
                          group_idx: int) -> None:
        """Queue one file's payload (+footer) reads on the current job."""
        path = self.mapper.block_path(unit.key, group_idx)
        slot_bytes = unit.payload.nbytes // unit.covered
        try:
            self.io.submit_read(
                job.job_id, path, unit.payload,
                offset=unit.slot_lo * slot_bytes,
            )
            if unit.footer is not None:
                self.io.submit_read(
                    job.job_id, path, unit.footer,
                    offset=unit.num_slots * slot_bytes,
                )
        except FaultInjected:
            job.submit_failed = True

    # -- multi-block file spans (unaligned head/tail) --

    def _check_span(self, span: FileSpan) -> None:
        check_span(span, self.blocks_per_file, self.pages_per_block)

    def async_store_spans(self, spans: Sequence[FileSpan],
                          group_idx: int = 0) -> int:
        """Store multi-block file spans; returns the job id.

        Every touched file must be FULLY covered (spans for one file may be
        split, but their union must be all ``blocks_per_file`` slots):
        lookup treats file existence as "stored", so a file must only ever
        appear atomically (tmp+rename) with every slot written — a
        partially-provisioned file would serve zeros for its holes as
        successful loads. Partial writes stay a load-side concept (head
        offsets); this mirrors the reference, where a file is one offload
        block and only complete offload blocks are stored.
        """
        validate_store_coverage(spans, self.blocks_per_file,
                                self.pages_per_block)

        copier = self.copiers[group_idx]
        file_bytes = copier.slab_nbytes(self.pages_per_block) * self.blocks_per_file
        job_id = self.io.begin_job()
        job = _PendingJob(job_id=job_id, report_id=job_id, is_store=True,
                          started=time.perf_counter(), nbytes=0,
                          group_idx=group_idx,
                          traceparent=current_traceparent())
        suffix = uuid.uuid4().hex[:8]
        # One device program per job: per-block gathers keep slots
        # independently addressable in the file (a fused multi-block gather
        # would interleave blocks by layer).
        all_slabs = copier.gather_many_to_host(
            [list(b) for span in spans for b in span.blocks]
        )
        for file_key, payload in assemble_file_buffers(
                spans, all_slabs, file_bytes).items():
            # Span-mode files checksum per slot so partial (head-offset)
            # loads can verify just the slots they read.
            buf = (self._with_footer(payload, self.blocks_per_file)
                   if self.integrity else payload)
            try:
                queued = self.io.submit_write(
                    job_id,
                    self.mapper.block_path(file_key, group_idx),
                    self.mapper.tmp_path(file_key, group_idx, unique_suffix=suffix),
                    buf,
                )
            except FaultInjected:
                job.submit_failed = True
                job.store_units.append(_StoreUnit(key=file_key, buf=buf))
                continue
            if queued:
                job.buffers.append(buf)
                job.store_units.append(_StoreUnit(key=file_key, buf=buf))
                job.nbytes += payload.nbytes
            else:
                job.shed_hashes.append(file_key)
        self.io.seal_job(job_id)
        self._register(job)
        return job_id

    def async_load_spans(self, spans: Sequence[FileSpan],
                         group_idx: int = 0) -> int:
        """Load multi-block file spans (partial-file reads start at the
        span's head-offset byte); returns the job id."""
        for span in spans:
            self._check_span(span)
        copier = self.copiers[group_idx]
        slot_bytes = copier.slab_nbytes(self.pages_per_block)
        job_id = self.io.begin_job()
        job = _PendingJob(job_id=job_id, report_id=job_id, is_store=False,
                          started=time.perf_counter(), nbytes=0,
                          group_idx=group_idx,
                          traceparent=current_traceparent())
        for span in spans:
            buf = self.staging.acquire(len(span.blocks) * slot_bytes)
            footer = None
            if self.integrity:
                footer = self.staging.acquire(footer_size(self.blocks_per_file))
            unit = _LoadUnit(
                key=span.file_key, payload=buf, footer=footer,
                slot_lo=span.head_offset, covered=len(span.blocks),
                num_slots=self.blocks_per_file,
                scatters=[
                    (buf[k * slot_bytes:(k + 1) * slot_bytes], list(page_ids))
                    for k, page_ids in enumerate(span.blocks)
                ],
            )
            job.buffers.append(buf)
            if footer is not None:
                job.buffers.append(footer)
            job.load_units.append(unit)
            job.nbytes += buf.nbytes
            self._submit_load_unit(job, unit, group_idx)
        self.io.seal_job(job_id)
        self._register(job)
        return job_id

    # -- completion --

    def _register(self, job: _PendingJob) -> None:
        with self._lock:
            self._pending[job.job_id] = job
            self._by_report[job.report_id] = job.job_id

    def _quarantine(self, key: int, group_idx: int) -> None:
        """Move a checksum-failed file out of the content-addressed
        namespace so lookups stop advertising it; the evictor reclaims
        ``*.quarantine`` files on its age sweep."""
        path = self.mapper.block_path(key, group_idx)
        try:
            os.replace(path, path + QUARANTINE_SUFFIX)
            logger.error("quarantined corrupt offload file %s", path)
        except OSError as e:
            logger.warning("could not quarantine %s: %s", path, e)

    def _verify_load(self, job: _PendingJob) -> list[int]:
        """Checksum every read unit; quarantine and report corrupt files."""
        corrupt: list[int] = []
        for unit in job.load_units:
            if unit.footer is None:
                continue
            flat = unit.payload.view(np.uint8).reshape(-1)
            slot = flat.nbytes // unit.covered
            try:
                crcs = parse_footer(bytes(unit.footer), unit.num_slots)
                got = slot_crcs(
                    [flat[i * slot:(i + 1) * slot] for i in range(unit.covered)]
                )
                for i, crc in enumerate(got):
                    if crc != crcs[unit.slot_lo + i]:
                        raise IntegrityError(
                            f"slot {unit.slot_lo + i} crc mismatch: "
                            f"footer={crcs[unit.slot_lo + i]:#010x} data={crc:#010x}"
                        )
            except IntegrityError as e:
                logger.error("load of %#x failed verification: %s", unit.key, e)
                self._quarantine(unit.key, job.group_idx)
                corrupt.append(unit.key)
        return corrupt

    def _schedule_retry(self, job: _PendingJob) -> None:
        delay = self.retry_policy.delay(job.attempt - 1)
        flight_recorder().record(
            KIND_RETRY,
            {
                "subsystem": "offload",
                "job_id": job.report_id,
                "direction": "store" if job.is_store else "load",
                "attempt": job.attempt,
                "delay_s": delay,
            },
        )
        logger.warning(
            "job %d (%s) attempt %d failed; retrying in %.3fs",
            job.report_id, "store" if job.is_store else "load",
            job.attempt, delay,
        )
        with self._lock:
            self._retry_q.append((time.monotonic() + delay, job))
            self._by_report[job.report_id] = -1

    def _resubmit(self, job: _PendingJob) -> None:
        job.attempt += 1
        job.submit_failed = False
        job.job_id = self.io.begin_job()
        if job.is_store:
            suffix = uuid.uuid4().hex[:8]
            kept = []
            for unit in job.store_units:
                try:
                    queued = self.io.submit_write(
                        job.job_id,
                        self.mapper.block_path(unit.key, job.group_idx),
                        self.mapper.tmp_path(unit.key, job.group_idx,
                                             unique_suffix=suffix),
                        unit.buf,
                    )
                except FaultInjected:
                    job.submit_failed = True
                    kept.append(unit)
                    continue
                if queued:
                    kept.append(unit)
                else:
                    job.shed_hashes.append(unit.key)
            job.store_units = kept
        else:
            for unit in job.load_units:
                self._submit_load_unit(job, unit, job.group_idx)
        self.io.seal_job(job.job_id)
        self._register(job)

    def _flush_retries(self) -> None:
        now = time.monotonic()
        with self._lock:
            due = [j for t, j in self._retry_q if t <= now]
            self._retry_q = [(t, j) for t, j in self._retry_q if t > now]
        for job in due:
            self._resubmit(job)

    def _release_job_buffers(self, job: _PendingJob) -> None:
        for buf in job.buffers:
            self.staging.release(buf)

    def get_finished(self) -> list[TransferResult]:
        """Poll completed jobs; verify + apply load scatters; retry or
        report; release buffers."""
        self._flush_retries()
        results = []
        for job_id, status in self.io.poll_finished():
            with self._lock:
                job = self._pending.pop(job_id, None)
            if job is None:
                continue
            if status == STATUS_OK:
                fp = FP_STORE_IO_ERROR if job.is_store else FP_LOAD_IO_ERROR
                if job.submit_failed or failpoints.should_fire(fp):
                    status = STATUS_IO_ERROR
            success = status == STATUS_OK
            corrupt: list[int] = []
            if success and not job.is_store:
                corrupt = self._verify_load(job)
                success = not corrupt
            if success and not job.is_store:
                copier = self.copiers[job.group_idx]
                copier.scatter_many_from_host([
                    (
                        np.frombuffer(buf, dtype=copier.dtype).reshape(
                            copier.slab_shape(len(page_ids))
                        ),
                        page_ids,
                    )
                    for unit in job.load_units
                    for buf, page_ids in unit.scatters
                ])
            elif not success:
                logger.warning(
                    "%s job %d failed (status %d, attempt %d)",
                    "store" if job.is_store else "load",
                    job.report_id, status, job.attempt,
                )
            # Transient failures (IO error, injected fault) retry under the
            # policy; checksum corruption is deterministic and cancellation
            # is intentional — neither is worth a second attempt.
            if (not success and not corrupt and status == STATUS_IO_ERROR
                    and job.attempt < self.retry_policy.max_attempts):
                self._schedule_retry(job)
                continue
            if not job.is_store:
                # Scatter (or abandonment) has consumed the staged bytes:
                # recycle the slots (release no-ops on non-pool buffers).
                self._release_job_buffers(job)
            with self._lock:
                self._by_report.pop(job.report_id, None)
            result = TransferResult(
                job_id=job.report_id,
                success=success,
                is_store=job.is_store,
                bytes_transferred=job.nbytes if success else 0,
                seconds=time.perf_counter() - job.started,
                shed_hashes=job.shed_hashes,
                corrupt_hashes=corrupt,
                attempts=job.attempt,
            )
            # Completion marker span joining the submitter's trace, plus a
            # flight record: "why did this block come back cold?" is
            # answerable after the fact from either surface.
            direction = "store" if job.is_store else "load"
            with tracer().span(
                "llm_d.kv_cache.offload.job",
                parent_traceparent=job.traceparent,
                direction=direction,
                job_id=job.report_id,
                success=success,
                attempts=job.attempt,
                bytes=result.bytes_transferred,
                seconds=result.seconds,
            ):
                pass
            flight_recorder().record(
                KIND_OFFLOAD,
                {
                    "job_id": job.report_id,
                    "direction": direction,
                    "success": success,
                    "bytes": result.bytes_transferred,
                    "seconds": result.seconds,
                    "attempts": job.attempt,
                    "shed": len(job.shed_hashes),
                    "corrupt": len(corrupt),
                },
            )
            results.append(result)
        return results

    def wait_job(self, job_id: int, timeout_s: float = 30.0) -> int:
        """Cancel-and-wait for preemption (request aborted mid-transfer).

        ``job_id`` is the id the submit call returned; retries run under
        fresh native ids, so resolve through the report map first.
        """
        with self._lock:
            native_id = self._by_report.get(job_id, job_id)
            if native_id == -1:
                # Parked in the retry queue: nothing in flight natively —
                # drop the pending retry and release its buffers.
                job = None
                for i, (_t, j) in enumerate(self._retry_q):
                    if j.report_id == job_id:
                        job = j
                        del self._retry_q[i]
                        break
                self._by_report.pop(job_id, None)
                if job is not None and not job.is_store:
                    self._release_job_buffers(job)
                return STATUS_CANCELLED
        status = self.io.wait_job(native_id, timeout_s)
        if status != STATUS_PENDING:
            # Only release the host buffers once the native side has truly
            # drained: a timed-out job may still have an in-flight read
            # holding raw pointers into them.
            with self._lock:
                job = self._pending.pop(native_id, None)
                self._by_report.pop(job_id, None)
            if job is not None and not job.is_store:
                self._release_job_buffers(job)
        else:
            logger.warning(
                "job %d still in flight after cancel timeout; parking buffers",
                job_id,
            )
        return status

    def flush(self, deadline_s: float = 10.0) -> bool:
        """Pump completions until no jobs are pending or queued for retry,
        or ``deadline_s`` elapses (graceful drain, recovery.drain).

        Completed results reach their engine reports (and store checksums
        land on disk) instead of being abandoned by shutdown. Returns True
        when fully flushed inside the budget.
        """
        t_end = time.monotonic() + deadline_s
        while True:
            self.get_finished()
            with self._lock:
                idle = not self._pending and not self._retry_q
            if idle:
                return True
            if time.monotonic() >= t_end:
                with self._lock:
                    pending = len(self._pending)
                    queued = len(self._retry_q)
                logger.warning(
                    "offload flush deadline: %d in flight, %d retry-queued "
                    "abandoned", pending, queued,
                )
                return False
            time.sleep(0.005)

    def shutdown(self) -> None:
        self.io.close()
