"""Scheduler-side offload manager.

Counterpart of reference ``llmd_fs_backend/manager.py``: decides which
blocks to store/load against the shared file store. Stateless by design —
``lookup`` is file existence (touching atime as a recency signal for the
evictor), stores are idempotent, and eviction is delegated entirely to the
storage-side evictor. ``complete_store`` publishes tokenless BlockStored
events so the global index learns the storage tier; ``BlockRemoved`` events
come from the evictor, not from here.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..events.publisher import StorageEventPublisher
from ..utils.logging import get_logger
from .file_mapper import FileMapper
from .native import file_exists

logger = get_logger("offload.manager")


class SharedStorageOffloadManager:
    """Tracks nothing; the filesystem is the source of truth."""

    def __init__(
        self,
        mapper: FileMapper,
        event_publisher: Optional[StorageEventPublisher] = None,
        block_size_tokens: int = 16,
    ):
        self.mapper = mapper
        self.event_publisher = event_publisher
        self.block_size_tokens = block_size_tokens
        # Optional working-set tap (telemetry.workingset): lookups feed
        # the storage-tier reuse stream, completed stores the
        # written-never-read ledger. Wired by engine.attach_workingset.
        self.workingset = None
        mapper.write_run_config()

    def lookup(self, block_hashes: Sequence[int], group_idx: int = 0) -> int:
        """Longest stored prefix: count of leading blocks present on disk.

        Touches atime on hits so the evictor sees them as recently used
        (reference ``manager.py:100-105``).
        """
        hits = 0
        for h in block_hashes:
            if not file_exists(self.mapper.block_path(h, group_idx), touch_atime=True):
                break
            hits += 1
        if self.workingset is not None and group_idx == 0:
            self.workingset.record_offload_read(block_hashes, hits=hits)
        return hits

    def prepare_store(
        self, block_hashes: Sequence[int], group_idx: int = 0
    ) -> list[int]:
        """Filter to blocks not yet stored (stores are idempotent, but
        skipping known files avoids pointless device→host traffic)."""
        return [
            h for h in block_hashes
            if not file_exists(self.mapper.block_path(h, group_idx))
        ]

    def complete_store(self, block_hashes: Sequence[int]) -> None:
        """Publish the storage-tier BlockStored event (tokenless; the
        indexer resolves request keys via the engine→request mapping)."""
        if self.workingset is not None and block_hashes:
            self.workingset.record_offload_write(block_hashes)
        if self.event_publisher is not None and block_hashes:
            self.event_publisher.publish_block_stored(
                list(block_hashes), self.block_size_tokens
            )

    def complete_load(self, block_hashes: Sequence[int]) -> None:
        """Loads don't change global state (files remain)."""

    def complete_load_failure(self, corrupt_hashes: Sequence[int]) -> None:
        """De-advertise blocks whose files failed checksum verification.

        The worker has already quarantined the files (renamed out of the
        content-addressed namespace), so ``lookup`` misses immediately;
        this publishes BlockRemoved so remote index views stop routing to
        the storage tier for these blocks too.
        """
        if corrupt_hashes:
            logger.warning(
                "de-advertising %d corrupt block(s): %s",
                len(corrupt_hashes),
                ", ".join(f"{h:#x}" for h in list(corrupt_hashes)[:8]),
            )
        if self.event_publisher is not None and corrupt_hashes:
            self.event_publisher.publish_block_removed(list(corrupt_hashes))
