"""Offload spec: the engine-facing configuration plugin.

Counterpart of reference ``llmd_fs_backend/spec.py``: one object that an
engine (vLLM-TPU's OffloadingConnector, or this repo's MiniEngine) loads
from its connector config to get (a) the scheduler-side manager and (b)
the worker-side handlers, wired consistently from a single fingerprinted
layout.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax

from ..events.publisher import StorageEventPublisher
from ..parallel.mesh import mesh_fingerprint_fields
from ..utils.logging import get_logger
from .file_mapper import FileMapper, FileMapperConfig
from .manager import SharedStorageOffloadManager
from .tpu_copier import TPUBlockCopier
from .worker import OffloadHandlers

logger = get_logger("offload.spec")


@dataclass
class SharedStorageOffloadSpec:
    """Builds the manager/handlers pair for shared-storage offload."""

    root: str
    model_name: str
    page_size: int = 16
    num_layers: int = 32
    kv_heads: int = 8
    head_dim: int = 128
    dtype: str = "bfloat16"
    io_threads: int = 4
    read_preferring_ratio: float = 0.75
    max_write_queued_seconds: float = 10.0
    # Multi-block file geometry (reference spec.py:76-89): consecutive
    # blocks per file (1 = one content-addressed file per block) and fixed
    # pages per block slot.
    blocks_per_file: int = 1
    pages_per_block: int = 1
    # Hybrid attention geometry (enters the store fingerprint: files
    # written under one window/layer-split must not be resumed by another).
    sliding_window: Optional[int] = None
    swa_layers: tuple = ()
    # 1 for MLA latent stores (use cfg.kv_cache_heads/kv_cache_head_dim
    # for kv_heads/head_dim then); 2 for standard K+V.
    kv_streams: int = 2
    # StreamingLLM sinks (enters the store fingerprint: sink and
    # sink-free KV of the same model are byte-incompatible).
    attention_sinks: int = 0
    # End-to-end payload integrity: "crc32" (default) appends the per-slot
    # checksum footer verified on load; "none" for raw-throughput setups
    # that accept silent corruption. Fingerprinted either way.
    integrity: str = "crc32"
    # Transient-failure retry: attempts per offload job (1 disables retry)
    # and the base backoff delay (jittered exponential, resilience.policy).
    retry_attempts: int = 2
    retry_base_delay_s: float = 0.05
    rank: int = 0
    parallel_agnostic: bool = False
    events_endpoint: Optional[str] = None
    mesh: Optional[object] = None  # jax.sharding.Mesh
    # Backend selection: "posix" (native kvio file engine) or "object"
    # (S3-style store via offload.object_store — the reference's NIXL OBJ
    # equivalent). For "object", ``object_store_client`` may inject any
    # ObjectStoreClient; default is the directory-backed client at ``root``.
    backend: str = "posix"
    object_store_client: Optional[object] = None

    @property
    def medium(self) -> str:
        """Canonical medium name for events and metrics."""
        from ..events.publisher import MEDIUM_OBJECT_STORE, MEDIUM_SHARED_STORAGE

        return MEDIUM_OBJECT_STORE if self.backend == "object" else MEDIUM_SHARED_STORAGE

    @classmethod
    def from_extra_config(cls, extra: dict) -> "SharedStorageOffloadSpec":
        """Build from a connector-style extra-config dict (camelCase or
        snake_case keys accepted)."""
        def get(*names, default=None):
            for n in names:
                if n in extra:
                    return extra[n]
            return default

        return cls(
            root=get("root", "sharedStorageRoot", default="/tmp/kvtpu-offload"),
            model_name=get("modelName", "model_name", default="unknown"),
            page_size=get("pageSize", "page_size", default=16),
            num_layers=get("numLayers", "num_layers", default=32),
            kv_heads=get("kvHeads", "kv_heads", default=8),
            head_dim=get("headDim", "head_dim", default=128),
            dtype=get("dtype", default="bfloat16"),
            io_threads=get("ioThreads", "io_threads", default=4),
            read_preferring_ratio=get(
                "readPreferringRatio", "read_preferring_ratio", default=0.75
            ),
            max_write_queued_seconds=get(
                "maxWriteQueuedSeconds", "max_write_queued_seconds", default=10.0
            ),
            blocks_per_file=get("blocksPerFile", "blocks_per_file", default=1),
            pages_per_block=get("pagesPerBlock", "pages_per_block", default=1),
            sliding_window=get("slidingWindow", "sliding_window"),
            swa_layers=tuple(get("swaLayers", "swa_layers", default=()) or ()),
            kv_streams=get("kvStreams", "kv_streams", default=2),
            attention_sinks=get("attentionSinks", "attention_sinks",
                                default=0),
            integrity=get("integrity", default="crc32"),
            retry_attempts=get("retryAttempts", "retry_attempts", default=2),
            retry_base_delay_s=get(
                "retryBaseDelaySeconds", "retry_base_delay_s", default=0.05
            ),
            rank=get("rank", default=0),
            parallel_agnostic=get(
                "parallelAgnostic", "parallel_agnostic", default=False
            ),
            events_endpoint=get("eventsEndpoint", "events_endpoint"),
            backend=get("backend", default="posix"),
        )

    def build_mapper(self) -> FileMapper:
        return FileMapper(
            FileMapperConfig(
                root=self.root,
                model_name=self.model_name,
                dtype=self.dtype,
                page_size=self.page_size,
                kv_heads=self.kv_heads,
                head_dim=self.head_dim,
                num_layers=self.num_layers,
                pages_per_file=self.blocks_per_file,
                pages_per_block=self.pages_per_block,
                sliding_window=self.sliding_window,
                swa_layers=tuple(self.swa_layers),
                kv_streams=self.kv_streams,
                attention_sinks=self.attention_sinks,
                integrity=self.integrity,
                mesh_sizes=mesh_fingerprint_fields(self.mesh),
                rank=self.rank,
                parallel_agnostic=self.parallel_agnostic,
            )
        )

    def _object_pieces(self):
        from .object_store import FSObjectStoreClient, ObjectKeyMapper

        client = self.object_store_client or FSObjectStoreClient(self.root)
        mapper = ObjectKeyMapper(
            prefix="kv",
            fingerprint=self.build_mapper().fingerprint,
            rank=self.rank,
            parallel_agnostic=self.parallel_agnostic,
        )
        return client, mapper

    def _publisher(self, medium: str) -> Optional[StorageEventPublisher]:
        if not self.events_endpoint:
            return None
        return StorageEventPublisher(
            self.events_endpoint, self.model_name, medium=medium, bind=False
        )

    def get_manager(self):
        """Scheduler-side (rank 0) manager with optional event publishing."""
        if self.backend == "object":
            from .object_store import ObjectStoreOffloadManager

            client, mapper = self._object_pieces()
            return ObjectStoreOffloadManager(
                client, mapper,
                event_publisher=self._publisher(self.medium),
                block_size_tokens=self.page_size,
            )
        return SharedStorageOffloadManager(
            self.build_mapper(),
            self._publisher(self.medium),
            block_size_tokens=self.page_size,
        )

    def get_handlers(self, k_cache: jax.Array, v_cache: jax.Array):
        """Worker-side handlers bound to this worker's cache pools."""
        copier = TPUBlockCopier(k_cache, v_cache)
        # The fingerprint/config.json must describe the bytes the copier
        # actually moves — a misdeclared spec (e.g. an MLA engine left at
        # the kv_streams=2 default) would silently write files under
        # metadata for a different layout. Per-shard head counts may be
        # below the spec's full-model kv_heads under tp, so heads are
        # checked as an upper bound only.
        layers, _, kv_heads, page_size, head_dim = k_cache.shape
        if (self.kv_streams != copier.streams
                or head_dim != self.head_dim
                or page_size != self.page_size
                or layers > self.num_layers
                or kv_heads > self.kv_heads):
            raise ValueError(
                f"offload spec geometry (streams={self.kv_streams}, "
                f"kv_heads={self.kv_heads}, head_dim={self.head_dim}, "
                f"page_size={self.page_size}, layers={self.num_layers}) "
                f"does not match the bound cache "
                f"(streams={copier.streams}, kv_heads={kv_heads}, "
                f"head_dim={head_dim}, page_size={page_size}, "
                f"layers={layers}); MLA engines must set kv_streams=1 and "
                "size kv_heads/head_dim from cfg.kv_cache_heads/"
                "cfg.kv_cache_head_dim")
        if self.backend == "object":
            from .object_store import ObjectStoreOffloadHandlers

            client, mapper = self._object_pieces()
            return ObjectStoreOffloadHandlers(
                copier, client, mapper, io_threads=self.io_threads,
                blocks_per_file=self.blocks_per_file,
                pages_per_block=self.pages_per_block,
            )
        from ..resilience.policy import RetryPolicy

        return OffloadHandlers(
            copier,
            self.build_mapper(),
            io_threads=self.io_threads,
            read_preferring_ratio=self.read_preferring_ratio,
            max_write_queued_seconds=self.max_write_queued_seconds,
            blocks_per_file=self.blocks_per_file,
            pages_per_block=self.pages_per_block,
            retry_policy=RetryPolicy(
                max_attempts=max(1, self.retry_attempts),
                base_delay_s=self.retry_base_delay_s,
                max_delay_s=max(0.5, self.retry_base_delay_s * 10),
            ),
        )
