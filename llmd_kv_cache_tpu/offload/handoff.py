"""Prefill→decode KV handoff coordination over the offload plane.

Disaggregated serving splits a request across two pods: a **prefill pod**
runs chunked prefill and write-through-commits each chunk's full blocks to
the shared transfer tier (the existing CRC-checksummed offload data plane),
while a **decode pod** admits the same request with the deferred-restore
path polling those blocks in. This module is the small control plane
between them: per-request transfer state (blocks landed vs in flight),
chunk-completion streaming so the decode side can start restoring
layer-early blocks before the prefill tail finishes, the prefill→decode
pair picker, and the failure story — a prefill pod that dies mid-handoff
flips the state to ``failed`` and the decode pod falls back to local
prefill instead of losing the request (PR 4 recovery semantics).

The coordinator is engine-service-local state (one per cooperating pod
group, in-process for the bench and tests); cross-process deployments
publish :class:`~..events.model.TransferBlocksAvailableEvent` through the
``publish`` hook so remote decode pods learn availability over the event
plane.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from ..utils.lockdep import new_lock
from ..events.model import TransferBlocksAvailableEvent
from ..telemetry.tracing import tracer
from ..utils.logging import get_logger

logger = get_logger("offload.handoff")

# Engine roles (EngineConfig.role / ScoreRequest.role). "" on the wire
# means an unspecified role (legacy peers) and scores like "both".
ROLE_BOTH = "both"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


@dataclass
class HandoffState:
    """One request's prefill→decode transfer ledger."""

    request_id: str
    prefill_pod: str
    decode_pod: str
    # Full prompt blocks the transfer can ever cover (the partial tail and
    # the last prompt token are always recomputed on the decode pod).
    total_blocks: int
    started: float = 0.0
    landed_blocks: int = 0
    in_flight_blocks: int = 0
    in_flight_jobs: int = 0
    # Prefill pod has issued its last chunk's store (no more blocks will
    # be queued; some may still be in flight).
    prefill_finished: bool = False
    # Every queued store has settled and no more are coming. ``failed``
    # additionally means the prefill pod died / aborted mid-handoff and
    # the decode side must re-prefill the remainder itself.
    done: bool = False
    failed: bool = False
    finished: Optional[float] = None
    traceparent: Optional[str] = None
    # Topology epoch the handoff was paired under (cluster.membership);
    # 0 = pre-epoch caller. A pairing planned under a retired topology is
    # suspect — the decode pod may no longer own the transferred range.
    epoch: int = 0


class HandoffCoordinator:
    """Tracks prefill→decode transfers and streams chunk completions.

    All methods are thread-safe (offload completions drain on engine
    threads). Metric updates and the optional ``publish``/``residency``
    hooks fire outside the lock.
    """

    def __init__(
        self,
        publish: Optional[Callable[[TransferBlocksAvailableEvent], None]] = None,
        residency=None,
    ):
        self._mu = new_lock()
        self._states: dict[str, HandoffState] = {}
        self.publish = publish
        # Optional scoring.residency.ResidencyTracker: transfer progress
        # feeds residency-aware decode-pod scoring.
        self.residency = residency
        self.completed = 0
        self.failed = 0
        self.last_latency_s: Optional[float] = None
        # Traffic-mix EMA (prefill-token fraction) + per-outcome counters:
        # the fleet controller's starvation signal. ``mix_alpha`` is the
        # EMA weight of one observation batch.
        self.mix_alpha = 0.2
        self._mix_fraction: Optional[float] = None
        self._mix_samples = 0
        self._outcomes: dict[str, int] = {}

    # -- pair picking ----------------------------------------------------

    @staticmethod
    def pick_pair(
        prefill_pods: Sequence[str],
        decode_pods: Sequence[str],
        prefill_scores: Optional[dict[str, float]] = None,
        decode_scores: Optional[dict[str, float]] = None,
    ) -> tuple[str, str]:
        """Pick the prefill→decode pair for one request.

        Highest score wins on each side (prefill: prefix-cache reuse;
        decode: residency-aware score from the indexer); ties and missing
        scores fall back to list order, so with no scores at all the
        first pod of each role serves — deterministic round-robin is the
        caller's job via list rotation.
        """
        if not prefill_pods or not decode_pods:
            raise ValueError("pick_pair needs at least one pod per role")
        ps = prefill_scores or {}
        ds = decode_scores or {}
        prefill = max(prefill_pods, key=lambda p: (ps.get(p, 0.0),
                                                   -prefill_pods.index(p)))
        decode = max(decode_pods, key=lambda p: (ds.get(p, 0.0),
                                                 -decode_pods.index(p)))
        return prefill, decode

    # -- lifecycle -------------------------------------------------------

    def begin(
        self,
        request_id: str,
        prefill_pod: str,
        decode_pod: str,
        total_blocks: int,
        traceparent: Optional[str] = None,
        epoch: int = 0,
    ) -> HandoffState:
        st = HandoffState(
            request_id=request_id,
            prefill_pod=prefill_pod,
            decode_pod=decode_pod,
            total_blocks=max(int(total_blocks), 0),
            started=time.monotonic(),
            traceparent=traceparent,
            epoch=int(epoch),
        )
        with self._mu:
            self._states[request_id] = st
        if traceparent is not None:
            with tracer().span(
                "llm_d.kv_cache.handoff.begin",
                parent_traceparent=traceparent,
                request_id=request_id,
                prefill_pod=prefill_pod,
                decode_pod=decode_pod,
                total_blocks=st.total_blocks,
                epoch=st.epoch,
                process=prefill_pod,
            ):
                pass  # event-style span: marks the pairing decision
        self._update_gauges()
        return st

    def on_chunk_start(self, request_id: str,
                       block_hashes: Sequence[int]) -> None:
        """A prefill chunk's store job entered the offload plane."""
        with self._mu:
            st = self._states.get(request_id)
            if st is None:
                return
            st.in_flight_blocks += len(block_hashes)
            st.in_flight_jobs += 1
        if self.residency is not None:
            self.residency.on_transfer_started(
                st.decode_pod, list(block_hashes))
        self._update_gauges()

    def on_chunk_landed(self, request_id: str,
                        block_hashes: Sequence[int],
                        shed: Sequence[int] = ()) -> None:
        """A chunk's blocks are durably on the transfer tier.

        ``shed`` lists blocks of the same store job the worker dropped
        under pressure — they never land, so their claims are released
        while the rest of the chunk counts as landed.
        """
        with self._mu:
            st = self._states.get(request_id)
            if st is None:
                return
            n = len(block_hashes)
            st.landed_blocks += n
            st.in_flight_blocks = max(st.in_flight_blocks - n - len(shed), 0)
            st.in_flight_jobs = max(st.in_flight_jobs - 1, 0)
            if st.prefill_finished and st.in_flight_jobs == 0:
                st.done = True
            tp = st.traceparent
            decode_pod = st.decode_pod
            prefill_pod = st.prefill_pod
            done = st.done
            landed = st.landed_blocks
            total = st.total_blocks
        self._record_chunk("landed")
        if self.residency is not None:
            self.residency.on_landed(decode_pod, list(block_hashes))
            if shed:
                self.residency.on_released(decode_pod, list(shed))
        if tp is not None:
            with tracer().span(
                "llm_d.kv_cache.handoff.prefill_commit",
                parent_traceparent=tp,
                request_id=request_id,
                blocks=len(block_hashes),
                landed_blocks=landed,
                total_blocks=total,
                process=prefill_pod,
            ):
                pass  # event-style span: one per landed chunk
        if self.publish is not None:
            self.publish(TransferBlocksAvailableEvent(
                request_id=request_id,
                block_hashes=list(block_hashes),
                decode_pod=decode_pod,
                done=done,
            ))
        self._update_gauges()

    def on_chunk_failed(self, request_id: str,
                        block_hashes: Sequence[int]) -> None:
        """A chunk's store failed or was shed: its blocks never land.

        Not terminal for the handoff — the decode pod recomputes from the
        first missing block once the transfer settles.
        """
        with self._mu:
            st = self._states.get(request_id)
            if st is None:
                return
            st.in_flight_blocks = max(
                st.in_flight_blocks - len(block_hashes), 0)
            st.in_flight_jobs = max(st.in_flight_jobs - 1, 0)
            if st.prefill_finished and st.in_flight_jobs == 0:
                st.done = True
            decode_pod = st.decode_pod
        self._record_chunk("failed")
        if self.residency is not None:
            self.residency.on_released(decode_pod, list(block_hashes))
        self._update_gauges()

    def prefill_finished(self, request_id: str) -> None:
        """The prefill pod issued its final chunk (stores may still be in
        flight); once they settle the transfer is ``done``."""
        with self._mu:
            st = self._states.get(request_id)
            if st is None:
                return
            st.prefill_finished = True
            if st.in_flight_jobs == 0:
                st.done = True
        self._update_gauges()

    def fail(self, request_id: str, reason: str = "") -> None:
        """Prefill pod died / aborted mid-handoff: the decode pod must
        re-prefill the un-transferred remainder (nothing already landed is
        wasted — landed blocks stay restorable and checksummed)."""
        with self._mu:
            st = self._states.get(request_id)
            if st is None or st.failed:
                return
            st.failed = True
            st.done = True
            st.in_flight_blocks = 0
            st.in_flight_jobs = 0
        logger.warning("handoff for %s failed mid-transfer%s", request_id,
                       f": {reason}" if reason else "")
        self._update_gauges()

    def decode_settled(self, request_id: str, outcome: str) -> None:
        """The decode pod stopped waiting on this transfer.

        ``outcome``: ``complete`` (every transferable block restored),
        ``fallback`` (peer failed → local re-prefill), ``timeout`` (gave
        up at the deadline), or ``failed``. Terminal: records the handoff
        latency histogram, emits the completion span, and releases the
        residency claim (the storage tier's own BlockStored advertisements
        cover the blocks from here on).
        """
        with self._mu:
            st = self._states.pop(request_id, None)
        if st is None:
            return
        st.finished = time.monotonic()
        latency = st.finished - st.started
        self.last_latency_s = latency
        if outcome == "complete":
            self.completed += 1
        else:
            self.failed += 1
        with self._mu:
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
        try:
            from ..metrics.collector import record_handoff_request

            record_handoff_request(outcome, latency)
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
        if st.traceparent is not None:
            with tracer().span(
                "llm_d.kv_cache.handoff.complete",
                parent_traceparent=st.traceparent,
                request_id=request_id,
                outcome=outcome,
                landed_blocks=st.landed_blocks,
                total_blocks=st.total_blocks,
                process=st.decode_pod,
            ):
                pass  # event-style span: terminal handoff outcome
        if self.residency is not None:
            self.residency.release_pod_claims(st.decode_pod)
        self._update_gauges()

    # -- traffic mix / starvation ----------------------------------------

    def observe_mix(self, prefill_tokens: int, decode_tokens: int) -> None:
        """Fold one batch's prefill/decode token split into the mix EMA.

        The router (or engine service) calls this per admitted request or
        per batch; the EMA'd prefill fraction is what the fleet controller
        compares against the provisioned role split to spot a starved
        side.
        """
        total = max(prefill_tokens, 0) + max(decode_tokens, 0)
        if total <= 0:
            return
        frac = max(prefill_tokens, 0) / total
        with self._mu:
            if self._mix_fraction is None:
                self._mix_fraction = frac
            else:
                self._mix_fraction += self.mix_alpha * (frac - self._mix_fraction)
            self._mix_samples += 1

    def starvation(self) -> dict:
        """Residency/starvation view for the fleet controller + kvdiag.

        ``starved_side`` is a *hint* from transfer pressure alone:
        ``timeout``/``fallback`` outcomes mean decode pods gave up waiting
        on prefill output (prefill capacity starved); a deep transfer
        queue with healthy outcomes means decode pods are not draining
        restores (decode starved). The controller combines this with the
        mix-vs-provisioned imbalance before acting.
        """
        with self._mu:
            active = [st for st in self._states.values() if not st.done]
            in_flight = sum(st.in_flight_jobs for st in self._states.values())
            outcomes = dict(self._outcomes)
            mix = self._mix_fraction
            samples = self._mix_samples
        gave_up = outcomes.get("timeout", 0) + outcomes.get("fallback", 0) \
            + outcomes.get("failed", 0)
        settled = gave_up + outcomes.get("complete", 0)
        starved_side = None
        if settled and gave_up / settled > 0.1:
            starved_side = ROLE_PREFILL
        elif len(active) > 2 * max(in_flight, 1):
            starved_side = ROLE_DECODE
        return {
            "mix": {
                "prefill_fraction": None if mix is None else round(mix, 4),
                "samples": samples,
                "alpha": self.mix_alpha,
            },
            "outcomes": outcomes,
            "transfer_queue_depth": len(active),
            "in_flight_jobs": in_flight,
            "last_handoff_latency_s": self.last_latency_s,
            "starved_side": starved_side,
        }

    # -- introspection ---------------------------------------------------

    def state(self, request_id: str) -> Optional[HandoffState]:
        with self._mu:
            return self._states.get(request_id)

    def queue_depth(self) -> int:
        with self._mu:
            return sum(1 for st in self._states.values() if not st.done)

    def in_flight_jobs(self) -> int:
        with self._mu:
            return sum(st.in_flight_jobs for st in self._states.values())

    def debug(self) -> dict:
        """Snapshot for kvdiag's ``handoff`` section / admin providers."""
        with self._mu:
            active = [st for st in self._states.values() if not st.done]
            in_flight = sum(st.in_flight_jobs
                            for st in self._states.values())
        return {
            "transfer_queue_depth": len(active),
            "in_flight_jobs": in_flight,
            "completed": self.completed,
            "failed": self.failed,
            "last_handoff_latency_s": self.last_latency_s,
            "starvation": self.starvation(),
        }

    # -- internals -------------------------------------------------------

    def _record_chunk(self, outcome: str) -> None:
        try:
            from ..metrics.collector import record_handoff_chunk

            record_handoff_chunk(outcome)
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass

    def _update_gauges(self) -> None:
        try:
            from ..metrics.collector import record_handoff_gauges

            record_handoff_gauges(self.queue_depth(), self.in_flight_jobs())
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
