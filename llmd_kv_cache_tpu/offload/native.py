"""ctypes binding for the native kvio engine (csrc/kvio).

Builds ``libkvio.so`` on demand with the in-image toolchain (no
pip/pybind11 dependency) and caches it next to the sources. All file I/O
runs on the C++ pool threads, off the GIL.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional

from ..utils.lockdep import new_lock
from ..resilience.failpoints import failpoints
from ..utils.logging import get_logger

logger = get_logger("offload.native")

# Failpoints at the native submission boundary (docs/resilience.md):
# error-mode raises FaultInjected before the op reaches the C++ pool
# (callers in offload.worker translate this into a failed, retryable
# job); delay-mode simulates a slow disk. ``file_exists`` is custom-mode:
# firing makes the probe report "missing", shrinking lookup prefixes.
FP_SUBMIT_WRITE = "offload.native.submit_write"
FP_SUBMIT_READ = "offload.native.submit_read"
FP_FILE_EXISTS = "offload.native.file_exists"

_CSRC_DIR = Path(__file__).resolve().parent.parent.parent / "csrc" / "kvio"
_LIB_PATH = _CSRC_DIR / "libkvio.so"

_build_lock = new_lock()
_lib: Optional[ctypes.CDLL] = None

STATUS_PENDING = -1
STATUS_OK = 0
STATUS_IO_ERROR = 1
STATUS_CANCELLED = 2


def _build() -> None:
    subprocess.run(
        ["make", "-s"], cwd=str(_CSRC_DIR), check=True, capture_output=True
    )


def load_library() -> ctypes.CDLL:
    """Load (building if necessary) the kvio shared library."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        sources = [_CSRC_DIR / n for n in
                   ("kvio.cpp", "kvio.hpp", "kvio_numa.cpp", "kvio_numa.hpp")]
        if not _LIB_PATH.exists() or any(
            s.exists() and s.stat().st_mtime > _LIB_PATH.stat().st_mtime
            for s in sources
        ):
            if os.environ.get("KVTPU_NATIVE_NO_BUILD") == "1":
                raise RuntimeError(
                    f"{_LIB_PATH} is missing or stale and "
                    "KVTPU_NATIVE_NO_BUILD=1 forbids compiling at import "
                    "time; run `make native` first (or drop the env knob)")
            # Loud on purpose: an import-time compile means the prebuilt
            # path was skipped, which in production adds seconds of
            # latency (and a toolchain dependency) to first use.
            logger.warning(
                "libkvio.so missing/stale at %s — compiling at import "
                "time; prebuild with `make native` to avoid this",
                _LIB_PATH)
            _build()
        lib = ctypes.CDLL(str(_LIB_PATH))

        lib.kvio_create.restype = ctypes.c_void_p
        lib.kvio_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int,
        ]
        lib.kvio_destroy.argtypes = [ctypes.c_void_p]
        lib.kvio_begin_job.restype = ctypes.c_uint64
        lib.kvio_begin_job.argtypes = [ctypes.c_void_p]
        lib.kvio_seal_job.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kvio_submit_write.restype = ctypes.c_int
        lib.kvio_submit_write.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.kvio_submit_write_at.restype = ctypes.c_int
        lib.kvio_submit_write_at.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.kvio_submit_read.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.kvio_poll_finished.restype = ctypes.c_int
        lib.kvio_poll_finished.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ]
        lib.kvio_wait_job.restype = ctypes.c_int
        lib.kvio_wait_job.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_double]
        lib.kvio_avg_write_seconds.restype = ctypes.c_double
        lib.kvio_avg_write_seconds.argtypes = [ctypes.c_void_p]
        lib.kvio_queued_writes.restype = ctypes.c_int
        lib.kvio_queued_writes.argtypes = [ctypes.c_void_p]
        lib.kvio_file_exists.restype = ctypes.c_int
        lib.kvio_file_exists.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.kvio_numa_node.restype = ctypes.c_int
        lib.kvio_numa_node.argtypes = [ctypes.c_void_p]
        lib.kvio_worker_cpu.restype = ctypes.c_int
        lib.kvio_worker_cpu.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.kvio_workers_ready.restype = ctypes.c_int
        lib.kvio_workers_ready.argtypes = [ctypes.c_void_p]
        lib.kvio_pinned_staging_workers.restype = ctypes.c_int
        lib.kvio_pinned_staging_workers.argtypes = [ctypes.c_void_p]
        lib.kvio_direct_transfers.restype = ctypes.c_uint64
        lib.kvio_direct_transfers.argtypes = [ctypes.c_void_p]
        lib.kvio_discover_numa_node.restype = ctypes.c_int
        lib.kvio_discover_numa_node.argtypes = []
        lib.kvio_cpus_in_node.restype = ctypes.c_int
        lib.kvio_cpus_in_node.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ]
        lib.kvio_parse_cpulist.restype = ctypes.c_int
        lib.kvio_parse_cpulist.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ]

        _lib = lib
        return _lib


class NativeIOEngine:
    """Thin OO wrapper over the C ABI.

    Workers are pinned round-robin to the CPUs of ``numa_node`` (-1
    auto-discovers the TPU's host node from PCI sysfs, -2 disables
    placement), prefer that node for allocations, and hold a page-aligned
    mlock'd staging buffer each. ``direct_io`` routes transfers >= 4 KiB
    through O_DIRECT via the staging buffer (page-cache bypass; buffered
    fallback per file when the filesystem refuses).
    """

    def __init__(self, num_threads: int = 4, read_preferring_workers: int = 3,
                 max_write_queued_seconds: float = 10.0, numa_node: int = -1,
                 staging_bytes: int = 4 << 20, direct_io: bool = False):
        self._lib = load_library()
        self._handle = self._lib.kvio_create(
            num_threads, read_preferring_workers, max_write_queued_seconds,
            numa_node, staging_bytes, int(direct_io),
        )
        if not self._handle:
            raise RuntimeError("failed to create kvio engine")
        self.num_threads = num_threads

    def begin_job(self) -> int:
        return self._lib.kvio_begin_job(self._handle)

    def seal_job(self, job_id: int) -> None:
        self._lib.kvio_seal_job(self._handle, job_id)

    @staticmethod
    def _buffer_address(buffer, writable: bool) -> tuple[int, int]:
        """(address, nbytes) of a numpy array / bytes / bytearray without
        copying. The caller must keep the object alive until completion."""
        import numpy as np

        if isinstance(buffer, np.ndarray):
            if writable and not buffer.flags.writeable:
                raise ValueError("read destination must be writable")
            if not buffer.flags.c_contiguous:
                raise ValueError("buffer must be C-contiguous")
            return buffer.ctypes.data, buffer.nbytes
        if isinstance(buffer, bytes):
            if writable:
                raise ValueError("read destination must be writable")
            # Pointer into the caller's bytes object; valid while the caller
            # keeps the object alive (bytes storage is never relocated).
            return (
                ctypes.cast(ctypes.c_char_p(buffer), ctypes.c_void_p).value,
                len(buffer),
            )
        if isinstance(buffer, bytearray):
            c_buf = ctypes.c_char.from_buffer(buffer)
            return ctypes.addressof(c_buf), len(buffer)
        raise TypeError(f"unsupported buffer type: {type(buffer)!r}")

    def submit_write(self, job_id: int, path: str, tmp_path: str,
                     buffer, skip_if_exists: bool = True) -> bool:
        """Queue a write of ``buffer`` (numpy array or bytes; caller must
        keep it alive until the job completes). Returns False when shed."""
        failpoints.hit(FP_SUBMIT_WRITE)
        address, nbytes = self._buffer_address(buffer, writable=False)
        return bool(self._lib.kvio_submit_write(
            self._handle, job_id, path.encode(), tmp_path.encode(),
            address, nbytes, int(skip_if_exists),
        ))

    def submit_write_at(self, job_id: int, path: str, buffer, offset: int,
                        file_size: int) -> bool:
        """Queue an in-place write of ``buffer`` at a byte offset into a
        file provisioned to ``file_size`` (multi-block file slot update;
        NOT atomic). Returns False when shed."""
        address, nbytes = self._buffer_address(buffer, writable=False)
        return bool(self._lib.kvio_submit_write_at(
            self._handle, job_id, path.encode(), address, nbytes, offset,
            file_size,
        ))

    def submit_read(self, job_id: int, path: str, buffer, offset: int = 0) -> None:
        failpoints.hit(FP_SUBMIT_READ)
        address, nbytes = self._buffer_address(buffer, writable=True)
        self._lib.kvio_submit_read(
            self._handle, job_id, path.encode(), address, nbytes, offset,
        )

    def poll_finished(self, max_items: int = 64) -> list[tuple[int, int]]:
        ids = (ctypes.c_uint64 * max_items)()
        statuses = (ctypes.c_int * max_items)()
        n = self._lib.kvio_poll_finished(self._handle, ids, statuses, max_items)
        return [(ids[i], statuses[i]) for i in range(n)]

    def wait_job(self, job_id: int, timeout_s: float = 30.0) -> int:
        return self._lib.kvio_wait_job(self._handle, job_id, timeout_s)

    def avg_write_seconds(self) -> float:
        return self._lib.kvio_avg_write_seconds(self._handle)

    def queued_writes(self) -> int:
        return self._lib.kvio_queued_writes(self._handle)

    # -- placement visibility --

    def numa_node(self) -> int:
        """Resolved NUMA node (-1 when unknown or placement disabled)."""
        return self._lib.kvio_numa_node(self._handle)

    def worker_cpus(self) -> list[int]:
        return [self._lib.kvio_worker_cpu(self._handle, i)
                for i in range(self.num_threads)]

    def workers_ready(self) -> bool:
        return bool(self._lib.kvio_workers_ready(self._handle))

    def pinned_staging_workers(self) -> int:
        """Workers whose staging buffer mlock succeeded."""
        return self._lib.kvio_pinned_staging_workers(self._handle)

    def direct_transfers(self) -> int:
        """Transfers that took the O_DIRECT staged path (vs buffered
        fallback)."""
        return self._lib.kvio_direct_transfers(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.kvio_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:  # lint: allow-swallow (best-effort __del__ cleanup)
            pass


def file_exists(path: str, touch_atime: bool = False) -> bool:
    if failpoints.should_fire(FP_FILE_EXISTS):
        return False
    return bool(load_library().kvio_file_exists(path.encode(), int(touch_atime)))


def discover_numa_node() -> int:
    """Accelerator host NUMA node (KVIO_NUMA_NODE override, PCI sysfs scan,
    -1 unknown)."""
    return load_library().kvio_discover_numa_node()


def cpus_in_node(node: int, max_items: int = 1024) -> list[int]:
    lib = load_library()
    out = (ctypes.c_int * max_items)()
    n = lib.kvio_cpus_in_node(node, out, max_items)
    return [out[i] for i in range(min(n, max_items))]


def parse_cpulist(s: str, max_items: int = 1024) -> list[int]:
    """Parse a kernel cpulist string like ``0-13,84-97`` (test hook for the
    native parser)."""
    lib = load_library()
    out = (ctypes.c_int * max_items)()
    n = lib.kvio_parse_cpulist(s.encode(), out, max_items)
    return [out[i] for i in range(min(n, max_items))]
