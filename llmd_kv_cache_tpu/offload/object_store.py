"""Object-store offload backend.

Counterpart of reference ``kv_connectors/llmd_fs_backend/llmd_nixl/``
(NIXL object-store engine + ObjBackend + NixlLookup): offload KV blocks to
an S3-style key/value store for cross-node sharing where no POSIX
filesystem spans the fleet (e.g. 70B multi-host offload,
``BASELINE.json.configs[4]``).

Pieces:

- ``ObjectStoreClient`` protocol — minimal S3-ish surface (put/get/exists/
  delete/list). ``FSObjectStoreClient`` backs it with a directory (tests,
  NFS); ``S3ObjectStoreClient`` with boto3 when available; anything
  implementing the protocol plugs in.
- ``ObjectKeyMapper`` — same fingerprint discipline as the FileMapper, flat
  key namespace ``<prefix>/<fingerprint>/r<rank>/g<group>/<hash16>``.
- ``ObjectStoreOffloadHandlers`` — the same async job surface as the POSIX
  ``OffloadHandlers`` (store/load/get_finished/wait_job), with transfers on
  a Python thread pool (object I/O is client-library code, unlike the
  GIL-free POSIX path).
- ``ObjectStoreOffloadManager`` — lookup via ``exists``.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent import futures
from dataclasses import dataclass, field
from typing import Optional, Protocol, Sequence

import numpy as np

from ..utils.lockdep import new_lock
from ..events.publisher import StorageEventPublisher
from ..utils.atomic_io import atomic_write_bytes
from ..utils.logging import get_logger
from .tpu_copier import TPUBlockCopier
from .worker import (FileSpan, TransferResult, assemble_file_buffers,
                     check_span, validate_store_coverage)

logger = get_logger("offload.object_store")


class ObjectStoreClient(Protocol):
    def put(self, key: str, data: bytes) -> None: ...

    def get(self, key: str) -> Optional[bytes]: ...

    def exists(self, key: str) -> bool: ...

    def delete(self, key: str) -> bool: ...

    def list_keys(self, prefix: str) -> list[str]: ...


def client_get_range(client: ObjectStoreClient, key: str, start: int,
                     length: int) -> Optional[bytes]:
    """Ranged read: ``client.get_range`` when the client offers it (S3
    Range GETs, seek+read on files), else a full ``get`` sliced host-side.
    The fallback costs the whole object's bytes over the wire but keeps
    every protocol-conforming client usable for multi-block span loads."""
    getter = getattr(client, "get_range", None)
    if getter is not None:
        return getter(key, start, length)
    data = client.get(key)
    if data is None:
        return None
    if start + length > len(data):
        return None  # short object: treat like a missing range
    return data[start:start + length]


class FSObjectStoreClient:
    """Directory-backed object store (tests / shared-FS deployments).

    Keys map to files under the root; puts are atomic (tmp+rename) so
    concurrent readers never see partial objects.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        safe = key.replace("/", os.sep)
        return os.path.join(self.root, safe)

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # Durable publish (atomic_io): fsync file + dir before/after the
        # rename so a crash can't surface a renamed-but-empty object.
        atomic_write_bytes(path, data)

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def get_range(self, key: str, start: int, length: int) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                f.seek(start)
                data = f.read(length)
        except FileNotFoundError:
            return None
        return data if len(data) == length else None

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def delete(self, key: str) -> bool:
        try:
            os.unlink(self._path(key))
            return True
        except FileNotFoundError:
            return False

    def list_keys(self, prefix: str) -> list[str]:
        base = self._path(prefix)
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for name in files:
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, self.root)
                out.append(rel.replace(os.sep, "/"))
        return out


class _BotoS3:  # pragma: no cover - requires boto3 + credentials
    """boto3 transport (AWS-grade auth/retries when the package exists)."""

    def __init__(self, bucket: str, endpoint_url: Optional[str],
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 region: Optional[str] = None):
        import boto3

        kwargs: dict = {"endpoint_url": endpoint_url}
        if access_key and secret_key:
            kwargs.update(aws_access_key_id=access_key,
                          aws_secret_access_key=secret_key)
        if region:
            kwargs["region_name"] = region
        self._s3 = boto3.client("s3", **kwargs)
        self.bucket = bucket

    def put(self, key: str, data: bytes) -> None:
        self._s3.put_object(Bucket=self.bucket, Key=key, Body=data)

    def get(self, key: str) -> Optional[bytes]:
        try:
            return self._s3.get_object(Bucket=self.bucket, Key=key)["Body"].read()
        except self._s3.exceptions.NoSuchKey:
            return None

    def exists(self, key: str) -> bool:
        try:
            self._s3.head_object(Bucket=self.bucket, Key=key)
            return True
        except Exception:
            return False

    def get_range(self, key: str, start: int, length: int) -> Optional[bytes]:
        try:
            resp = self._s3.get_object(
                Bucket=self.bucket, Key=key,
                Range=f"bytes={start}-{start + length - 1}",
            )
            data = resp["Body"].read()
        except self._s3.exceptions.NoSuchKey:
            return None
        return data if len(data) == length else None

    def delete(self, key: str) -> bool:
        self._s3.delete_object(Bucket=self.bucket, Key=key)
        return True

    def list_keys(self, prefix: str) -> list[str]:
        out = []
        paginator = self._s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            out.extend(obj["Key"] for obj in page.get("Contents", []))
        return out


class _HttpS3:
    """Stdlib S3 REST transport: path-style addressing against any
    S3-compatible endpoint (MinIO, Ceph RGW, in-cluster gateways), with
    optional AWS SigV4 signing when credentials are provided. Exists so
    the cross-node offload path works in hermetic environments without
    boto3 — the analog of the reference's NIXL OBJ plugin speaking the
    wire protocol directly."""

    def __init__(self, bucket: str, endpoint_url: str,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 region: str = "us-east-1", timeout_s: float = 30.0):
        self.bucket = bucket
        self.endpoint = endpoint_url.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout_s = timeout_s

    # -- SigV4 (AWS auth sigv4-create-signed-request); skipped unsigned --

    def _sign(self, method: str, path: str, query: str,
              payload: bytes) -> dict:
        import datetime
        import hashlib
        import hmac
        from urllib.parse import urlparse

        if not (self.access_key and self.secret_key):
            return {}  # unsigned: no auth headers, no payload hashing
        host = urlparse(self.endpoint).netloc
        payload_hash = hashlib.sha256(payload).hexdigest()
        headers = {"host": host, "x-amz-content-sha256": payload_hash}
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers["x-amz-date"] = amz_date
        signed = ";".join(sorted(headers))
        canonical = "\n".join([
            method, path, query,
            "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
            signed, payload_hash,
        ])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ])
        key = f"AWS4{self.secret_key}".encode()
        for part in (datestamp, self.region, "s3", "aws4_request"):
            key = hmac.new(key, part.encode(), hashlib.sha256).digest()
        sig = hmac.new(key, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}")
        return headers

    def _request(self, method: str, key: str = "", query: str = "",
                 data: bytes = b"", range_header: Optional[str] = None):
        import urllib.error
        import urllib.request
        from urllib.parse import quote, urlparse

        # The signed canonical URI is the full path the SERVER sees —
        # including any path component of the endpoint (reverse-proxied
        # gateways like http://host/minio).
        base = urlparse(self.endpoint).path.rstrip("/")
        path = (base + "/"
                + quote(f"{self.bucket}/{key}" if key else self.bucket))
        url = (self.endpoint[:len(self.endpoint) - len(base)] if base
               else self.endpoint) + path + (f"?{query}" if query else "")
        headers = self._sign(method, path, query, data)
        if range_header:
            headers["Range"] = range_header
        req = urllib.request.Request(url, data=data or None, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def put(self, key: str, data: bytes) -> None:
        status, body = self._request("PUT", key, data=data)
        if status not in (200, 201):
            raise IOError(f"S3 PUT {key} failed: HTTP {status}")

    def get(self, key: str) -> Optional[bytes]:
        status, body = self._request("GET", key)
        if status == 404:
            return None
        if status != 200:
            raise IOError(f"S3 GET {key} failed: HTTP {status}")
        return body

    def get_range(self, key: str, start: int, length: int) -> Optional[bytes]:
        status, body = self._request(
            "GET", key, range_header=f"bytes={start}-{start + length - 1}")
        if status == 404:
            return None
        if status not in (200, 206):
            raise IOError(f"S3 ranged GET {key} failed: HTTP {status}")
        if status == 200:  # endpoint ignored Range: slice host-side
            body = body[start:start + length]
        return body if len(body) == length else None

    def exists(self, key: str) -> bool:
        status, _ = self._request("HEAD", key)
        return status == 200

    def delete(self, key: str) -> bool:
        status, _ = self._request("DELETE", key)
        return status in (200, 204)

    def list_keys(self, prefix: str) -> list[str]:
        import xml.etree.ElementTree as ET
        from urllib.parse import quote

        out: list[str] = []
        token: Optional[str] = None
        while True:
            # Sorted params: SigV4 canonicalizes the query string.
            params = [("list-type", "2"), ("prefix", prefix)]
            if token:
                params.append(("continuation-token", token))
            query = "&".join(
                f"{k}={quote(v, safe='')}" for k, v in sorted(params))
            status, body = self._request("GET", "", query=query)
            if status != 200:
                raise IOError(f"S3 LIST {prefix} failed: HTTP {status}")
            root = ET.fromstring(body)
            ns = root.tag[:root.tag.index("}") + 1] if "}" in root.tag else ""
            out.extend(el.text for el in root.iter(f"{ns}Key"))
            token_el = root.find(f"{ns}NextContinuationToken")
            truncated = root.findtext(f"{ns}IsTruncated", "false")
            if truncated != "true" or token_el is None or not token_el.text:
                return out
            token = token_el.text


class S3ObjectStoreClient:
    """S3-compatible client: boto3 when importable, else the stdlib HTTP
    transport (``endpoint_url`` required in that case — path-style
    S3-compatible endpoints)."""

    def __init__(self, bucket: str, endpoint_url: Optional[str] = None,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 region: Optional[str] = None,
                 transport: Optional[str] = None):
        # Standard AWS env credentials work on BOTH transports (k8s pods
        # inject them as env vars; boto3 reads them natively, the HTTP
        # transport must read them here or auth silently differs by
        # which transport auto-detection picked).
        access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID")
        secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY")
        region = region or os.environ.get("AWS_DEFAULT_REGION") or "us-east-1"
        if transport is None:
            try:
                import boto3  # noqa: F401
                transport = "boto3"
            except ImportError:
                transport = "http"
        if transport not in ("boto3", "http"):
            raise ValueError(
                f"unknown transport {transport!r}; expected 'boto3' or "
                "'http'")
        if transport == "boto3":  # pragma: no cover - needs boto3
            self._impl = _BotoS3(bucket, endpoint_url, access_key,
                                 secret_key, region)
        else:
            if not endpoint_url:
                raise ValueError(
                    "S3ObjectStoreClient without boto3 needs endpoint_url "
                    "(path-style S3-compatible endpoint)")
            self._impl = _HttpS3(bucket, endpoint_url, access_key,
                                 secret_key, region)
        self.bucket = bucket

    def put(self, key: str, data: bytes) -> None:
        self._impl.put(key, data)

    def get(self, key: str) -> Optional[bytes]:
        return self._impl.get(key)

    def get_range(self, key: str, start: int, length: int) -> Optional[bytes]:
        return self._impl.get_range(key, start, length)

    def exists(self, key: str) -> bool:
        return self._impl.exists(key)

    def delete(self, key: str) -> bool:
        return self._impl.delete(key)

    def list_keys(self, prefix: str) -> list[str]:
        return self._impl.list_keys(prefix)


@dataclass
class ObjectKeyMapper:
    """Fingerprinted flat key namespace for offloaded blocks."""

    prefix: str
    fingerprint: str
    rank: int = 0
    parallel_agnostic: bool = False

    def block_key(self, block_hash: int, group_idx: int = 0) -> str:
        h = block_hash & 0xFFFFFFFFFFFFFFFF
        rank_seg = "" if self.parallel_agnostic else f"/r{self.rank}"
        return f"{self.prefix}/{self.fingerprint}{rank_seg}/g{group_idx}/{h:016x}"

    @staticmethod
    def parse_block_key(key: str) -> Optional[int]:
        name = key.rsplit("/", 1)[-1]
        try:
            return int(name, 16)
        except ValueError:
            return None


@dataclass
class _ObjJob:
    job_id: int
    is_store: bool
    started: float
    futures: list = field(default_factory=list)
    # (future, page_ids, byte offset into payload, length|None=whole)
    scatters: list = field(default_factory=list)
    shed_hashes: list = field(default_factory=list)
    nbytes: int = 0
    cancelled: bool = False
    group_idx: int = 0  # cache group the job's pages belong to


class ObjectStoreOffloadHandlers:
    """Async store/load over an object store, same surface as the POSIX
    handlers (per-group copiers for hybrid models, multi-block span
    objects with ranged loads)."""

    def __init__(
        self,
        copier: TPUBlockCopier,
        client: ObjectStoreClient,
        mapper: ObjectKeyMapper,
        io_threads: int = 4,
        max_queued_puts: Optional[int] = None,
        blocks_per_file: int = 1,
        pages_per_block: int = 1,
        copiers: Optional[dict[int, TPUBlockCopier]] = None,
    ):
        self.copier = copier
        # Per-cache-group copiers (hybrid models: group 0 full-attention
        # pool, group 1 SWA pool); group 0 defaults to ``copier``.
        self.copiers: dict[int, TPUBlockCopier] = {0: copier}
        if copiers:
            self.copiers.update(copiers)
        self.client = client
        self.mapper = mapper
        self.blocks_per_file = blocks_per_file
        self.pages_per_block = pages_per_block
        self._executor = futures.ThreadPoolExecutor(
            max_workers=io_threads, thread_name_prefix="objstore-io"
        )
        self._jobs: dict[int, _ObjJob] = {}
        self._next_job = 1
        self._lock = new_lock()
        # Backpressure: each queued put pins a full host slab, so bound the
        # number in flight and shed the rest (the object-store analogue of
        # the POSIX engine's EMA write shedding — a future cache miss, not
        # unbounded host memory).
        self._put_slots = threading.Semaphore(
            max_queued_puts if max_queued_puts is not None else io_threads * 4
        )

    def _make_job(self, is_store: bool) -> _ObjJob:
        with self._lock:
            job_id = self._next_job
            self._next_job += 1
        return _ObjJob(job_id=job_id, is_store=is_store,
                       started=time.perf_counter())

    def _register(self, job: _ObjJob) -> int:
        # Register only after every future is attached: a concurrent
        # get_finished() poll must never observe a half-submitted job (an
        # empty futures list reads as "complete").
        with self._lock:
            self._jobs[job.job_id] = job
        return job.job_id

    def _put_released(self, fut) -> None:
        self._put_slots.release()

    def async_store_blocks(
        self, transfers: Sequence[tuple[int, Sequence[int]]], group_idx: int = 0
    ) -> int:
        job = self._make_job(is_store=True)
        job.group_idx = group_idx
        copier = self.copiers[group_idx]
        # Acquire put slots BEFORE gathering: a saturated store must shed
        # without paying device gathers/DMAs for data it will discard.
        admitted: list[tuple[int, list[int]]] = []
        for block_hash, page_ids in transfers:
            if self._put_slots.acquire(blocking=False):
                admitted.append((block_hash, list(page_ids)))
            else:
                job.shed_hashes.append(block_hash)
        slabs = copier.gather_many_to_host([p for _, p in admitted])
        for (block_hash, _page_ids), slab in zip(admitted, slabs):
            key = self.mapper.block_key(block_hash, group_idx)
            # Zero-copy byte view (bfloat16 etc. lack the buffer protocol,
            # so reinterpret as uint8 first).
            data = memoryview(np.ascontiguousarray(slab).view(np.uint8).reshape(-1))
            job.nbytes += len(data)
            fut = self._executor.submit(self.client.put, key, data)
            fut.add_done_callback(self._put_released)
            job.futures.append(fut)
        return self._register(job)

    def async_load_blocks(
        self, transfers: Sequence[tuple[int, Sequence[int]]], group_idx: int = 0
    ) -> int:
        job = self._make_job(is_store=False)
        job.group_idx = group_idx
        for block_hash, page_ids in transfers:
            key = self.mapper.block_key(block_hash, group_idx)
            fut = self._executor.submit(self.client.get, key)
            job.futures.append(fut)
            # (future, page_ids, byte offset into the payload, length|None
            # = whole payload) — same record shape as the span loads.
            job.scatters.append((fut, list(page_ids), 0, None))
        return self._register(job)

    # -- multi-block span objects (unaligned head/tail) --

    def _check_span(self, span: FileSpan) -> None:
        check_span(span, self.blocks_per_file, self.pages_per_block)

    def async_store_spans(self, spans: Sequence[FileSpan],
                          group_idx: int = 0) -> int:
        """Store multi-block spans as whole objects; returns the job id.

        Same durability rule as the POSIX engine: every touched object must
        be FULLY covered by the spans' union (lookup treats object
        existence as "stored", and object puts are atomic — a partially-
        provisioned object would serve holes as successful loads).
        """
        by_file = validate_store_coverage(spans, self.blocks_per_file,
                                          self.pages_per_block)

        job = self._make_job(is_store=True)
        job.group_idx = group_idx
        copier = self.copiers[group_idx]
        object_bytes = (copier.slab_nbytes(self.pages_per_block)
                        * self.blocks_per_file)
        admitted: list[FileSpan] = []
        # Shed whole objects (every span of the object together): a put
        # slot covers one assembled object buffer.
        for file_key, file_spans in by_file.items():
            if self._put_slots.acquire(blocking=False):
                admitted.extend(file_spans)
            else:
                job.shed_hashes.append(file_key)
        all_slabs = copier.gather_many_to_host(
            [list(b) for span in admitted for b in span.blocks]
        )
        for file_key, buf in assemble_file_buffers(
                admitted, all_slabs, object_bytes).items():
            key = self.mapper.block_key(file_key, group_idx)
            job.nbytes += buf.nbytes
            fut = self._executor.submit(self.client.put, key, memoryview(buf))
            fut.add_done_callback(self._put_released)
            job.futures.append(fut)
        return self._register(job)

    def async_load_spans(self, spans: Sequence[FileSpan],
                         group_idx: int = 0) -> int:
        """Load multi-block spans via ranged object reads (partial objects
        start at the span's head-offset byte); returns the job id."""
        for span in spans:
            self._check_span(span)
        job = self._make_job(is_store=False)
        job.group_idx = group_idx
        copier = self.copiers[group_idx]
        slot_bytes = copier.slab_nbytes(self.pages_per_block)
        for span in spans:
            key = self.mapper.block_key(span.file_key, group_idx)
            fut = self._executor.submit(
                client_get_range, self.client, key,
                span.head_offset * slot_bytes, len(span.blocks) * slot_bytes,
            )
            job.futures.append(fut)
            # One ranged read covers several block slots; split it into
            # per-block scatters at completion.
            for k, page_ids in enumerate(span.blocks):
                job.scatters.append(
                    (fut, list(page_ids), k * slot_bytes, slot_bytes))
        return self._register(job)

    def get_finished(self) -> list[TransferResult]:
        results = []
        with self._lock:
            done_ids = [
                jid for jid, job in self._jobs.items()
                if all(f.done() for f in job.futures)
            ]
            done_jobs = [self._jobs.pop(jid) for jid in done_ids]

        for job in done_jobs:
            copier = self.copiers[job.group_idx]
            success = not job.cancelled
            for f in job.futures:
                if f.cancelled() or f.exception() is not None:
                    success = False
                elif not job.is_store and f.result() is None:  # lint: allow-no-deadline (done() filtered above)
                    success = False  # missing object / short range
            if success and not job.is_store:
                batch = []
                counted = set()
                for fut, page_ids, off, length in job.scatters:
                    data = fut.result()  # lint: allow-no-deadline (done() filtered above)
                    if id(fut) not in counted:  # span loads share a future
                        counted.add(id(fut))
                        job.nbytes += len(data)
                    payload = data if length is None else data[off:off + length]
                    batch.append((
                        np.frombuffer(payload, dtype=copier.dtype).reshape(
                            copier.slab_shape(len(page_ids))
                        ),
                        page_ids,
                    ))
                copier.scatter_many_from_host(batch)
            results.append(
                TransferResult(
                    job_id=job.job_id,
                    success=success,
                    is_store=job.is_store,
                    bytes_transferred=job.nbytes if success else 0,
                    seconds=time.perf_counter() - job.started,
                    shed_hashes=job.shed_hashes,
                )
            )
        return results

    def wait_job(self, job_id: int, timeout_s: float = 30.0) -> int:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return 0
            job.cancelled = True
        for f in job.futures:
            f.cancel()
        deadline = time.monotonic() + timeout_s
        for f in job.futures:
            if f.cancelled():
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return -1
            try:
                f.exception(timeout=remaining)
            except futures.TimeoutError:
                return -1
            except Exception:  # lint: allow-swallow (failure reported via job status)
                pass
        with self._lock:
            self._jobs.pop(job_id, None)
        return 2  # cancelled

    def shutdown(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)


class ObjectStoreOffloadManager:
    """Scheduler-side manager over an object store."""

    def __init__(
        self,
        client: ObjectStoreClient,
        mapper: ObjectKeyMapper,
        event_publisher: Optional[StorageEventPublisher] = None,
        block_size_tokens: int = 16,
    ):
        self.client = client
        self.mapper = mapper
        self.event_publisher = event_publisher
        self.block_size_tokens = block_size_tokens
        # Optional working-set tap (telemetry.workingset), same contract
        # as SharedStorageOffloadManager.workingset.
        self.workingset = None

    def lookup(self, block_hashes: Sequence[int], group_idx: int = 0) -> int:
        hits = 0
        for h in block_hashes:
            if not self.client.exists(self.mapper.block_key(h, group_idx)):
                break
            hits += 1
        if self.workingset is not None and group_idx == 0:
            self.workingset.record_offload_read(block_hashes, hits=hits)
        return hits

    def prepare_store(self, block_hashes: Sequence[int], group_idx: int = 0) -> list[int]:
        return [
            h for h in block_hashes
            if not self.client.exists(self.mapper.block_key(h, group_idx))
        ]

    def complete_store(self, block_hashes: Sequence[int]) -> None:
        if self.workingset is not None and block_hashes:
            self.workingset.record_offload_write(block_hashes)
        if self.event_publisher is not None and block_hashes:
            self.event_publisher.publish_block_stored(
                list(block_hashes), self.block_size_tokens
            )

    def complete_load(self, block_hashes: Sequence[int]) -> None:
        pass
