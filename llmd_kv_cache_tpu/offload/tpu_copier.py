"""TPU HBM ↔ host transfers for paged KV blocks.

The TPU-native replacement for the reference's CUDA ``TensorCopier``
(``tensor_copier.cu:222-249``): instead of per-block ``cudaMemcpyAsync``
into pinned staging, the paged-KV gather happens **on device** inside one
jitted XLA program (``gather_pages_flat`` over both K and V pools for all
layers), producing one contiguous slab per file, which is then moved to
host memory in a single device→host DMA. The reverse path scatters a host
slab back into the paged pools inside one jit with donation.

Slab layout per offloaded file (dtype = cache dtype):
``[num_layers, 2 (K,V), pages_per_file, kv_heads, page_size, head_dim]``

On TPU the host side lands in pinned host memory (`jax.device_get` uses
the PJRT pinned path); on the CPU backend the same code degrades to plain
copies, keeping tests hardware-free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import get_logger

logger = get_logger("offload.copier")


@partial(jax.jit, static_argnames=("streams",))
def _gather_slab(k_cache: jax.Array, v_cache: jax.Array,
                 page_ids: jax.Array, streams: int = 2) -> jax.Array:
    """Gather pages into one contiguous slab.

    k_cache/v_cache: [layers, num_pages, kv_heads, page_size, head_dim]
    page_ids: [n] physical page indices
    returns: [layers, streams, n, kv_heads, page_size, head_dim]

    ``streams=1`` is the MLA layout: the K pool holds the whole per-token
    latent and the V pool is width-0, so block files carry one stream.
    """
    k = k_cache[:, page_ids]  # [layers, n, kvh, page, hd]
    if streams == 1:
        return k[:, None]
    v = v_cache[:, page_ids]
    return jnp.stack([k, v], axis=1)


@partial(jax.jit, donate_argnames=("k_cache", "v_cache"),
         static_argnames=("streams",))
def _scatter_slab(k_cache: jax.Array, v_cache: jax.Array, slab: jax.Array,
                  page_ids: jax.Array,
                  streams: int = 2) -> tuple[jax.Array, jax.Array]:
    """Scatter a slab back into the paged pools (donated, in-place)."""
    k_cache = k_cache.at[:, page_ids].set(slab[:, 0])
    if streams == 2:
        v_cache = v_cache.at[:, page_ids].set(slab[:, 1])
    return k_cache, v_cache


class TPUBlockCopier:
    """Moves groups of KV pages between device pools and host slabs."""

    def __init__(self, k_cache: jax.Array, v_cache: jax.Array):
        # The copier owns the cache references so scatter can donate them.
        self.k_cache = k_cache
        self.v_cache = v_cache
        layers, _, kv_heads, page_size, head_dim = k_cache.shape
        # MLA pools: V is width-0 (values live in the latent K pool), so
        # block files carry a single stream.
        self.streams = 1 if v_cache.shape[-1] == 0 else 2
        self.slab_shape = lambda n: (layers, self.streams, n, kv_heads,
                                     page_size, head_dim)
        self.dtype = k_cache.dtype
        try:
            self._pinned_sharding = jax.sharding.SingleDeviceSharding(
                list(k_cache.devices())[0], memory_kind="pinned_host"
            )
        except Exception:  # pragma: no cover - runtime without memory kinds
            self._pinned_sharding = None

    def slab_nbytes(self, n_pages: int) -> int:
        return int(np.prod(self.slab_shape(n_pages))) * self.dtype.itemsize

    @property
    def pinned_host_active(self) -> bool:
        """True while the D2H leg routes through ``pinned_host`` memory.
        Surfaced (not just best-effort) so deployments can assert the true
        DMA path instead of silently degrading."""
        return self._pinned_sharding is not None

    def _to_pinned_host(self, x: jax.Array) -> jax.Array:
        """Route the device→host leg through pinned host memory when the
        runtime supports memory kinds (true DMA staging, the role the
        reference's cudaHostAlloc buffers play); plain transfer otherwise."""
        if self._pinned_sharding is None:
            return x
        try:
            return jax.device_put(x, self._pinned_sharding)
        except Exception:  # pragma: no cover - runtime without the kind
            logger.warning(
                "pinned_host memory kind unavailable on %s; D2H falls back "
                "to unpinned transfers", x.devices())
            self._pinned_sharding = None
            return x

    def gather_to_host(self, page_ids: list[int]) -> np.ndarray:
        """Device-side page gather + one D2H transfer; returns the host slab."""
        ids = jnp.asarray(page_ids, jnp.int32)
        slab = _gather_slab(self.k_cache, self.v_cache, ids,
                            streams=self.streams)
        return np.asarray(jax.device_get(slab))

    # Cap on pages merged into one device transfer: bounds the transient
    # HBM slab (batching win saturates long before this; a job of hundreds
    # of blocks must not materialize job-sized scratch in already-pressured
    # HBM — offload runs exactly when HBM is tight).
    MAX_BATCH_PAGES = 128

    def gather_many_to_host(
        self, page_id_groups: list[list[int]]
    ) -> list[np.ndarray]:
        """Gather several page groups with few device programs/DMAs.

        Groups are merged into transfers of at most ``MAX_BATCH_PAGES``
        pages. Returns one independent contiguous host array per group
        (copies, not views — safe to hand to the I/O engine)."""
        out: list[np.ndarray] = []
        chunk: list[list[int]] = []
        chunk_pages = 0

        def flush():
            nonlocal chunk, chunk_pages
            if not chunk:
                return
            all_ids = [p for group in chunk for p in group]
            slab = _gather_slab(self.k_cache, self.v_cache,
                                jnp.asarray(all_ids, jnp.int32),
                                streams=self.streams)
            merged = np.asarray(jax.device_get(self._to_pinned_host(slab)))
            pos = 0
            for group in chunk:
                out.append(
                    np.ascontiguousarray(merged[:, :, pos:pos + len(group)])
                )
                pos += len(group)
            chunk, chunk_pages = [], 0

        for group in page_id_groups:
            if chunk and chunk_pages + len(group) > self.MAX_BATCH_PAGES:
                flush()
            chunk.append(group)
            chunk_pages += len(group)
        flush()
        return out

    def scatter_from_host(self, slab: np.ndarray, page_ids: list[int]) -> None:
        """One H2D transfer + device-side scatter into the pools."""
        self.scatter_many_from_host([(slab, page_ids)])

    def scatter_many_from_host(
        self, slabs: list[tuple[np.ndarray, list[int]]]
    ) -> None:
        """Scatter several host slabs with few device programs.

        Per-slab scatters each rewrite the cache arrays; batching turns N
        cache updates into ~1 (measured ~30× on the load path). Merged
        transfers are capped at ``MAX_BATCH_PAGES`` pages to bound the
        transient HBM slab.
        """
        chunk: list[tuple[np.ndarray, list[int]]] = []
        chunk_pages = 0

        def flush():
            nonlocal chunk, chunk_pages
            if not chunk:
                return
            all_ids: list[int] = []
            parts = []
            for slab, page_ids in chunk:
                parts.append(
                    np.asarray(slab).reshape(self.slab_shape(len(page_ids)))
                )
                all_ids.extend(page_ids)
            merged = np.concatenate(parts, axis=2)  # page axis
            device_slab = jax.device_put(merged)
            self.k_cache, self.v_cache = _scatter_slab(
                self.k_cache, self.v_cache, device_slab.astype(self.dtype),
                jnp.asarray(all_ids, jnp.int32), streams=self.streams,
            )
            chunk, chunk_pages = [], 0

        for slab, page_ids in slabs:
            if chunk and chunk_pages + len(page_ids) > self.MAX_BATCH_PAGES:
                flush()
            chunk.append((slab, page_ids))
            chunk_pages += len(page_ids)
        flush()
