"""TPU-native model family: paged-KV transformer + mini serving engine.

The in-tree stand-in for vLLM-TPU: exercises the whole cache stack (block
hashing, KV events, prefix reuse, offload) end-to-end on TPU hardware and
is the flagship model for benchmarks.
"""

from .llama import LlamaConfig, forward, init_params
from .engine import MiniEngine, EngineConfig

__all__ = ["LlamaConfig", "forward", "init_params", "MiniEngine", "EngineConfig"]
