"""Mini TPU serving engine: paged KV cache + prefix caching + KV events.

The in-tree stand-in for vLLM-TPU. One engine instance ≙ one "pod": it
manages a physical page pool with content-addressed prefix caching (block
hashes computed by the same ``ChunkedTokenDatabase`` as the indexer, so
engine keys ARE canonical keys — a 1:1 mapping), runs prefill/decode steps
on the paged Llama model, and emits BlockStored / BlockRemoved /
AllBlocksCleared events exactly like a real engine would, either to a ZMQ
publisher or to any callback.

Prefix caching semantics (mirroring vLLM's): on admission the prompt's
full blocks are hashed along the chain; the longest prefix of blocks
already resident is *reused* — those pages are attached to the new request
and their tokens are never recomputed, which is where the TTFT win comes
from. Evictions are LRU over unreferenced pages and emit BlockRemoved.
"""

from __future__ import annotations

import functools
import time
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.hma import (
    SPEC_FULL_ATTENTION,
    SPEC_MLA,
    SPEC_SINK_FULL,
    SPEC_SLIDING_WINDOW,
)
from ..core.keys import EMPTY_BLOCK_HASH
from ..core.token_processor import ChunkedTokenDatabase, TokenProcessorConfig
from ..events.model import (
    AllBlocksClearedEvent,
    BlockRemovedEvent,
    BlockStoredEvent,
    GenericEvent,
)
from ..ops.pallas_paged_attention import (
    head_dim_supported as _pallas_head_dim_supported,
)
from ..resilience.deadline import Deadline, current_deadline
from ..resilience.shedding import (
    BROWNOUT,
    PRIORITY_NORMAL,
    SHED,
    CoDelShedder,
    OverloadShedError,
)
from ..telemetry.tracing import tracer
from ..utils.logging import get_logger
from .llama import (
    LlamaConfig,
    forward,
    forward_decode_pallas,
    forward_decode_steps,
    forward_decode_steps_hybrid,
    forward_hybrid,
    forward_prefill_pallas,
    forward_ragged,
    init_kv_cache,
    init_kv_cache_hybrid,
    init_params,
)

logger = get_logger("models.engine")

EventSink = Callable[[list[GenericEvent]], None]


def _resolve_kv_dtype(name: str):
    """EngineConfig.kv_cache_dtype string → jnp dtype (loud on typos)."""
    table = {
        "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
        "f8_e4m3": jnp.float8_e4m3fn, "float8_e4m3fn": jnp.float8_e4m3fn,
    }
    if name not in table:
        raise ValueError(
            f"kv_cache_dtype must be one of {sorted(table)}, got {name!r}")
    return table[name]


@dataclass
class EngineConfig:
    model: LlamaConfig = field(default_factory=LlamaConfig.tiny)
    num_pages: int = 512
    # Hybrid models: size of the SWA group's separate page pool (None →
    # num_pages). SWA pages are allocated just-in-time and reclaimed as
    # slots fall out of the window, so per-request peak demand is
    # window + max(prefill-chunk, decode_burst) pages (+ the decode page),
    # not prompt length — the memory win of hybrid attention. Fused bursts
    # freeze the window tables for up to decode_burst tokens and reclaim
    # at the burst boundary; an undersized pool degrades that step to
    # single-token decoding rather than failing.
    num_swa_pages: Optional[int] = None
    max_pages_per_seq: int = 64
    max_batch: int = 8
    hash_seed: str = ""
    model_name: str = "tiny-llama"
    pod_identifier: str = "pod-0"
    # Decode attention backend: None = auto (Pallas flash-decode on TPU,
    # XLA reference elsewhere); True forces Pallas (interpreted on CPU);
    # False forces the XLA path.
    use_pallas_decode: Optional[bool] = None
    # Prefill attention backend: None = auto — the Pallas flash-prefill
    # kernel whenever the Pallas backend is active (TPU + aligned
    # head_dim), XLA paged attention otherwise. Measured on a real v5e
    # at the bench's production shapes (0.9B model, 2048-token chunks,
    # in-jit so dispatch is excluded — hack/mfu_probe.py): the superblock
    # flash kernel runs 1.9 ms/layer vs XLA's 3.5 ms — the fp32
    # logits/probs tensor XLA materializes per layer costs more HBM
    # round-trips than the kernel's streamed online softmax. (The
    # pre-superblock kernel this default once gated off was 12× *slower*:
    # 16-token DMAs and 16×128 tiles cannot feed the 128×128 MXU.)
    # False forces XLA prefill; True insists and warns if the Pallas
    # backend is inactive.
    use_pallas_prefill: Optional[bool] = None
    # Fuse QKV (and gate+up, MLA input) projections into single wider
    # matmuls at startup (models.llama.fuse_params). None = auto: fused
    # wherever the shape profits (llama.fuse_profitable — measured v5e
    # crossover: hidden 4096 gains ~7% prefill MFU, hidden 2048 loses
    # ~8%; benchmarking/r5-tpu). The gate evaluates PER-SHARD widths
    # (hidden_size / tp): tp narrows each rank's matmul columns, so
    # hidden 4096 at tp=2 is gated off like the regressing hidden-2048
    # single-shard case. Under a tp mesh the engine fuses in
    # the per-rank INTERLEAVED column order (LlamaConfig.fused_interleave
    # = tp) so the fused leaves stay Megatron-column-shardable; auto
    # additionally requires the projection widths to divide tp and
    # skips MLA-under-mesh and pp serving (those stay unfused; explicit
    # True raises there). When sharing one params tree across
    # single-shard pods, pass it through llama.maybe_fuse_params FIRST
    # (profit-gated; a no-op on a fused tree) — otherwise each engine
    # materializes its own fused weight copy; a tp engine re-layouts a
    # pre-fused canonical tree into its interleaved order itself.
    # Checkpoints store the canonical unfused layout either way
    # (models.checkpoint unfuses on save).
    fuse_projections: Optional[bool] = None
    # Paged KV pool element type: None (default — the model's dtype),
    # "bf16", or "f8_e4m3" (float8_e4m3fn). fp8 halves KV HBM traffic
    # and capacity — the decode-bandwidth lever at long context
    # (b32/ctx2048 decode is attention-bandwidth bound,
    # benchmarking/r5-tpu) — with ~2^-3 relative quantization error per
    # element (the established fp8-KV serving trade). e4m3's per-element
    # exponent needs no scale arrays: the cache keeps its layout,
    # scatter casts on write, attention upcasts on read,
    # offload/checkpoint move 1-byte elements (the store fingerprint's
    # dtype field separates fp8 stores from bf16). fp8 decode rides the
    # merged flash kernel's quantized arm (flat whole-page 1-byte DMAs,
    # needs kv_heads*page_size % 32 == 0); fp8 prefill runs XLA
    # attention — TTFT-bound deployments should keep bf16. Composes
    # with mesh-sharded serving (tp/dp/sp/pp: the cast is elementwise
    # and pools shard exactly like bf16 — token-identity pinned in
    # tests/test_kv_fp8.py); MLA latents refuse fp8 (absorbed-attention
    # latents are more quantization-sensitive).
    kv_cache_dtype: Optional[str] = None
    # Batch rows co-scheduled per flash-decode program (merged-heads
    # kernel): each round issues every row's page DMAs together and the
    # pipeline fills once per program instead of once per batch item —
    # the decode-bandwidth lever (VERDICT r4 #1). 1 = one program per
    # batch item (round-4 behavior). Single-shard Pallas decode only;
    # ignored under tp sharding and on the XLA backend.
    decode_batch_rows: int = 1
    # Chunked prefill: the uncached suffix is processed in chunks of at
    # most this many tokens (vLLM-style), bounding per-step activation
    # memory for long prompts. Must be a multiple of the page size.
    max_prefill_tokens: int = 512
    # Fused decode bursts: up to this many greedy tokens per device
    # dispatch (lax.scan inside one jit). 1 = one token per step() —
    # finest-grained continuous batching; larger values amortize dispatch
    # overhead (dominant on remote-tunneled TPUs, material everywhere) at
    # the cost of admitting new requests only between bursts. Bursts are
    # bucketed to powers of two so the jit cache stays O(log burst).
    decode_burst: int = 1
    # Ragged single-kernel attention: pack the step's admitted prefill
    # chunk and every active decode row into ONE flat-token-axis dispatch
    # (ops.pallas_paged_attention.pallas_paged_ragged_attention) instead
    # of the batch-1 prefill call plus the pad-to-max_batch decode call.
    # A decode row is a 1-token ragged row, a prefill chunk a longer one;
    # per-sequence padding disappears (the flat axis pads only to a
    # power-of-two token bucket) and mixed traffic stops paying two
    # kernel pipelines' fill/drain per step. Single-shard, non-hybrid,
    # decode_burst=1 only — other configurations warn once and keep the
    # padded two-kernel path; the same fallback serves shapes the kernel
    # cannot take (unaligned head_dim on real TPU, fp8 pages whose
    # kv_heads*page_size is not a 32 multiple). Runs interpreted on CPU.
    ragged_attention: bool = False
    # Engine data-plane telemetry (telemetry/engine_telemetry.py): an
    # EngineTelemetryConfig enables TTFT/ITL/TPOT histograms, KV-pool
    # gauges, per-request flight-recorder events, and the on-demand
    # jax.profiler capture surface. None (default) keeps the step path
    # free of every hook — each site costs one attribute load + branch.
    telemetry: Optional[Any] = None
    # Disaggregated serving role (offload.handoff): "both" (default —
    # monolithic pod, prefill and decode), "prefill" (prefill-only pod:
    # each chunk's full blocks commit write-through to the transfer tier
    # as they are computed, the request finishes at first token and
    # decoding happens elsewhere), or "decode" (decode-side pod:
    # ``enqueue(handoff=True)`` requests wait up to ``handoff_wait_s``
    # for transferred blocks before falling back to local prefill).
    # Non-hybrid engines only — hybrid restores are all-or-nothing and
    # cannot pull a transfer in chunk-granular rounds.
    role: str = "both"
    # Decode-side handoff patience: how long a ``handoff=True`` request
    # waits for the prefill peer's blocks to land before recomputing the
    # remainder locally. Decodes keep running the whole time (the wait
    # costs only that request's TTFT, never the running batch).
    handoff_wait_s: float = 10.0
    # CoDel-style overload shedding at admission (resilience.shedding):
    # when burst-admission delay (enqueue → first scheduler pick) stays
    # above the target for a full interval, ``enqueue`` sheds
    # lowest-priority work first instead of letting the queue grow
    # without bound. 0 (default) disables the shedder entirely — no
    # lock, no branch cost beyond one attribute load.
    shed_target_delay_s: float = 0.0
    shed_interval_s: float = 0.1


@dataclass
class _BlockInfo:
    page: int
    ref_count: int = 0
    last_used: float = 0.0
    parent_hash: int = 0
    tokens: tuple[int, ...] = ()


@dataclass
class Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int
    # runtime state
    output: list[int] = field(default_factory=list)
    pages: list[int] = field(default_factory=list)  # physical pages, logical order
    swa_pages: list[int] = field(default_factory=list)  # hybrid: group 1 pages
    # Hybrid: first logical block whose SWA page this request references
    # (earlier slots map to the garbage page — out of window at resume).
    swa_acquired_from: int = 0
    block_hashes: list[int] = field(default_factory=list)  # hash-chained, per full block
    cached_len: int = 0  # tokens skipped via prefix cache at admission
    computed_len: int = 0  # tokens with KV resident (cached + prefilled + decoded)
    last_logits: Optional[np.ndarray] = None
    done: bool = False
    # Continuous batching: next prompt index to prefill, or None once the
    # request is decoding. ``enqueue`` admits with this set; ``step``
    # advances one chunk at a time interleaved with decode.
    prefill_pos: Optional[int] = None
    # Deferred storage restore (enqueue path): the lookup hasn't run yet /
    # an async load is in flight. ``step`` polls the job across steps so a
    # slow restore never stalls running decodes (a synchronous restore in
    # _admit blocked them for up to the 30 s deadline).
    restore_pending: bool = False
    # enqueue() timestamp, cleared at first prefill schedule — feeds the
    # burst-admission-delay histogram.
    enqueued_at: Optional[float] = None
    # W3C traceparent carried from the scorer (ScoreResponse.traceparent →
    # enqueue()): when set, the engine parents admission/prefill/decode
    # spans under it so one trace covers score→serve. None = no spans.
    traceparent: Optional[str] = None
    # (job_id, first_missing_block, hashes, pages, deadline, started)
    # while loading.
    restore_job: Optional[tuple] = None
    # Prompt blocks registered in the block manager on this request's
    # behalf (acquired prefix at admission, extended by
    # _commit_full_blocks). _release must treat pages past this watermark
    # as unregistered orphans — an aborted mid-prefill request's blocks
    # were never committed, and release()ing unknown hashes would silently
    # leak their pages.
    committed_blocks: int = 0
    # Device-resident page table, cached across prefill chunks (pages are
    # fixed from admission until commit; each upload is a host→device
    # round trip). Cleared at prefill finish.
    table_dev: Any = None
    # Decode-side handoff wait (enqueue(handoff=True) on a decode-role
    # engine): monotonic deadline until which step() holds this request's
    # local prefill, polling the transfer tier for the prefill peer's
    # blocks in re-armed deferred-restore rounds. None once settled.
    handoff_deadline: Optional[float] = None
    # End-to-end budget carried from the caller (ScoreRequest.deadline_ms
    # → enqueue(deadline_s=...), or the ambient deadline_scope at enqueue
    # time). A deferred storage restore that cannot finish inside the
    # remaining budget is skipped — recompute beats a restore whose
    # result arrives after the caller stopped waiting.
    deadline: Optional[Deadline] = None
    # resilience.shedding priority class: sheds lowest-first under
    # admission overload.
    priority: int = PRIORITY_NORMAL
    # Ground-truth audit (telemetry/audit.py): the ScoreFeedback this
    # request was routed on (duck-typed, None when the scheduler passed
    # none), the HBM prefix hit at admission, and blocks restored from
    # the storage/transfer tier since — together they decompose the
    # realized prefix outcome emitted at prefill finish.
    feedback: Any = None
    hbm_hit_blocks: int = 0
    restored_blocks: int = 0

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.output)


class BlockManager:
    """Physical page pool with content-addressed prefix caching.

    Page 0 is the reserved garbage page (see ``ops.kv_pages``). Full blocks
    are indexed by chain hash; unreferenced pages stay cached until LRU
    eviction reclaims them.
    """

    def __init__(self, cfg: EngineConfig, processor: ChunkedTokenDatabase,
                 event_sink: Optional[EventSink] = None, group_idx: int = 0,
                 num_pages: Optional[int] = None,
                 spec_kind: Optional[str] = None,
                 spec_window: Optional[int] = None):
        self.cfg = cfg
        self.processor = processor
        self.event_sink = event_sink
        self.group_idx = group_idx
        pool = num_pages if num_pages is not None else cfg.num_pages
        self.num_pages = pool
        self.free_pages: list[int] = list(range(1, pool))  # 0 reserved
        self.blocks: dict[int, _BlockInfo] = {}  # block_hash → info
        self.page_to_hash: dict[int, int] = {}
        # Lifetime eviction count: a plain int (one add per eviction) that
        # telemetry turns into kvtpu_engine_kv_pool_evictions_total deltas.
        self.evictions = 0
        # Optional eviction tap: called with the victim's age (seconds
        # since last use) — the working-set tracker's eviction-age
        # histogram (engine.attach_workingset wires it).
        self.on_evict: Optional[Callable[[float], None]] = None
        if spec_kind is not None:
            self.spec_kind = spec_kind
            self.spec_window = spec_window
        else:
            # KV-cache spec advertised in events. A unified (single-group)
            # pool is sliding_window only when every layer is SWA; any
            # full-attention layer makes full retention the controlling
            # constraint. Hybrid engines construct one manager per group
            # with explicit specs instead. MLA pools advertise
            # mla_attention (events.go:34): block payloads are latents,
            # not per-head K/V, so consumers must not mix them with
            # full_attention blocks of the same tokens.
            mcfg = cfg.model
            if mcfg.is_mla:
                self.spec_kind = SPEC_MLA
                self.spec_window = None
            elif (
                mcfg.sliding_window is not None
                and set(mcfg.swa_layers) >= set(range(mcfg.num_layers))
            ):
                # Uniform SWA; with sinks it is the reference's
                # sink_full_attention kind (events.go:40).
                self.spec_kind = (SPEC_SINK_FULL if mcfg.attention_sinks
                                  else SPEC_SLIDING_WINDOW)
                self.spec_window = mcfg.sliding_window
            else:
                self.spec_kind = SPEC_FULL_ATTENTION
                self.spec_window = None

    # -- accounting --

    def num_free(self) -> int:
        return len(self.free_pages)

    def num_cached_blocks(self) -> int:
        return len(self.blocks)

    def pool_stats(self) -> dict:
        """Occupancy snapshot for telemetry/kvdiag: cheap plain-int reads.

        ``orphan_pages`` are pages neither free nor registered as hashed
        blocks — held by in-flight requests (partial tails, decode room)
        and not reusable as prefix cache until commit.
        """
        free = len(self.free_pages)
        cached_pages = len(self.page_to_hash)
        return {
            "total_pages": self.num_pages,
            "free_pages": free,
            "cached_blocks": len(self.blocks),
            "cached_pages": cached_pages,
            # Page 0 is the reserved garbage page.
            "orphan_pages": max((self.num_pages - 1) - free - cached_pages, 0),
            "evictions": self.evictions,
        }

    def _emit(self, events: list[GenericEvent]) -> None:
        if self.event_sink is not None and events:
            self.event_sink(events)

    # -- prefix cache --

    def match_prefix(self, block_hashes: Sequence[int]) -> list[int]:
        """Longest resident prefix: returns the pages for matched blocks."""
        pages = []
        for h in block_hashes:
            info = self.blocks.get(h)
            if info is None:
                break
            pages.append(info.page)
        return pages

    def acquire_prefix(self, block_hashes: Sequence[int]) -> list[int]:
        """Reference the longest resident prefix; bumps ref counts."""
        pages = self.match_prefix(block_hashes)
        now = time.monotonic()
        for h in block_hashes[: len(pages)]:
            info = self.blocks[h]
            info.ref_count += 1
            info.last_used = now
        return pages

    def try_acquire_blocks(self, block_hashes: Sequence[int]) -> Optional[list[int]]:
        """All-or-nothing reference of specific blocks (SWA trailing-window
        acquisition: the needed set is a window, not a prefix)."""
        infos = []
        for h in block_hashes:
            info = self.blocks.get(h)
            if info is None:
                return None
            infos.append(info)
        now = time.monotonic()
        for info in infos:
            info.ref_count += 1
            info.last_used = now
        return [info.page for info in infos]

    def allocate_page(self) -> Optional[int]:
        """Pop a free page, evicting LRU unreferenced blocks if needed."""
        if not self.free_pages and not self._evict_one():
            return None
        return self.free_pages.pop()

    def _evict_one(self) -> bool:
        victim_hash = None
        victim_time = float("inf")
        for h, info in self.blocks.items():
            if info.ref_count == 0 and info.last_used < victim_time:
                victim_time = info.last_used
                victim_hash = h
        if victim_hash is None:
            return False
        info = self.blocks.pop(victim_hash)
        self.page_to_hash.pop(info.page, None)
        self.free_pages.append(info.page)
        self.evictions += 1
        if self.on_evict is not None:
            try:
                self.on_evict(time.monotonic() - victim_time)
            except Exception:  # pragma: no cover  # lint: allow-swallow
                pass
        # Must carry the same group tag as the BlockStored that created the
        # entry, or the index's entry-match eviction is a silent no-op.
        self._emit([
            BlockRemovedEvent(block_hashes=[victim_hash],
                              group_idx=self.group_idx)
        ])
        return True

    def commit_blocks(
        self,
        block_hashes: Sequence[int],
        pages: Sequence[int],
        tokens_per_block: Sequence[Sequence[int]],
        parent_of_first: int,
    ) -> list[int]:
        """Register newly computed full blocks in the prefix cache.

        Returns the canonical page per block: when a block's content is
        already resident (recomputed duplicate), the existing page wins and
        the redundant page is freed — the KV bytes are identical.

        Emits one BlockStored event per *contiguous run* of newly stored
        blocks, each with its own correct parent hash, so the indexer's
        chained request-key recomputation never spans a gap (a duplicate in
        the middle must not fuse two runs into one false chain).
        """
        now = time.monotonic()
        canonical_pages: list[int] = []
        events: list[GenericEvent] = []
        run_hashes: list[int] = []
        run_tokens: list[int] = []
        run_parent = parent_of_first
        parent = parent_of_first

        def flush_run():
            nonlocal run_hashes, run_tokens
            if run_hashes:
                events.append(
                    BlockStoredEvent(
                        block_hashes=list(run_hashes),
                        tokens=list(run_tokens),
                        parent_hash=run_parent,
                        block_size=self.processor.block_size,
                        group_idx=self.group_idx,
                        kv_cache_spec_kind=self.spec_kind,
                        kv_cache_spec_sliding_window=self.spec_window,
                    )
                )
            run_hashes, run_tokens = [], []

        for h, page, toks in zip(block_hashes, pages, tokens_per_block):
            existing = self.blocks.get(h)
            if existing is None:
                self.blocks[h] = _BlockInfo(
                    page=page, ref_count=1, last_used=now,
                    parent_hash=parent, tokens=tuple(toks),
                )
                self.page_to_hash[page] = h
                if not run_hashes:
                    run_parent = parent
                run_hashes.append(h)
                run_tokens.extend(toks)
                canonical_pages.append(page)
            else:
                # Recomputed duplicate: adopt the resident page, free ours.
                existing.ref_count += 1
                existing.last_used = now
                if page != existing.page:
                    self.free_pages.append(page)
                canonical_pages.append(existing.page)
                flush_run()
            parent = h
        flush_run()
        self._emit(events)
        return canonical_pages

    def release(self, block_hashes: Sequence[int], orphan_pages: Sequence[int]) -> None:
        """Drop a finished request's references; free unhashed pages."""
        for h in block_hashes:
            info = self.blocks.get(h)
            if info is not None and info.ref_count > 0:
                info.ref_count -= 1
        self.free_pages.extend(orphan_pages)

    def clear(self, emit: bool = True) -> None:
        """Drop the whole prefix cache (weight rollout) and emit the reset.

        AllBlocksCleared is pod-wide (clears every group at the index), so
        a hybrid engine emits it from one manager only (``emit=False`` on
        the other).
        """
        for info in self.blocks.values():
            self.free_pages.append(info.page)
        self.blocks.clear()
        self.page_to_hash.clear()
        if emit:
            self._emit([AllBlocksClearedEvent()])


class MiniEngine:
    """Single-pod batched serving engine over the paged Llama model."""

    def __init__(
        self,
        cfg: Optional[EngineConfig] = None,
        event_sink: Optional[EventSink] = None,
        params=None,
        seed: int = 0,
        offload_spec=None,
        mesh=None,
    ):
        self.cfg = cfg or EngineConfig()
        mcfg = self.cfg.model
        # Tensor-parallel serving: with a mesh carrying a ``tp`` axis, the
        # params take the Megatron layout and the KV pools shard their
        # kv-heads axis (MLA: heads shard instead and the single shared
        # latent pool replicates); the same jitted forwards then run SPMD
        # (XLA inserts the per-block all-reduces). Paging stays host-side
        # and replicated — identical on every shard.
        self.mesh = mesh
        self._tp = 1
        self._sp = 1
        self._pp = 1
        if mesh is not None:
            from ..parallel.serve import mesh_tp_size, validate_tp_config

            # MLA shards on the head axis (wq/w_uk/w_uv/wo split per
            # head, latent projections + latent cache replicated) —
            # validate_tp_config checks the per-family divisibility.
            validate_tp_config(mcfg, mesh)
            self._tp = mesh_tp_size(mesh)
            # Sequence parallelism for prefill: with an ``sp`` mesh axis,
            # chunk tokens are placed sharded on the sequence dim and XLA
            # propagates — per-token projections/MLP/attention-q compute
            # splits sp-ways (one long prompt's prefill FLOPs spread over
            # sp chips), with the collectives (scatter all-gathers, one
            # logits all-reduce) derived from the shardings. Verified
            # bit-exact vs single-device and predominantly seq-sharded in
            # the compiled HLO (tests/test_sp_serve.py). Decode (seq=1)
            # is unaffected.
            self._sp = mesh.shape.get("sp", 1)
            # Pipeline-parallel serving: layer blocks + the layer axis of
            # the paged caches shard over ``pp``; prefill chunks and
            # decode batches stream through the stages as microbatches
            # (parallel.pp_serve). v1 scope: dense models, XLA attention,
            # no tp on the same mesh, single-token decode.
            self._pp = mesh.shape.get("pp", 1)
            if self._pp > 1:
                from ..parallel.pp_serve import validate_pp_serve_config

                if self._sp > 1:
                    raise NotImplementedError(
                        "pp serving does not yet compose with sp on one "
                        "mesh (tp composes: Megatron within each stage)")
                if self.cfg.max_batch % self._pp == 0:
                    self._pp_decode_mb = self._pp
                else:
                    # Surface the idle stages instead of silently running
                    # the unpipelined M=1 schedule (same policy as the sp
                    # divisibility warning below).
                    logger.warning(
                        "max_batch=%d does not divide by pp=%d: decode "
                        "runs unpipelined (one microbatch; %d of %d "
                        "stages idle each tick) — size max_batch to a "
                        "pp multiple", self.cfg.max_batch, self._pp,
                        self._pp - 1, self._pp)
                    self._pp_decode_mb = 1
                validate_pp_serve_config(mcfg, mesh, self._pp_decode_mb,
                                         self.cfg.max_batch)
            if self._sp > 1 and mcfg.page_size % self._sp != 0:
                # Chunk buckets are 2^k × page_size; a chunk shards only
                # when sp divides its bucket. sp ∤ page_size means short
                # chunks (and, for non-power-of-two sp, EVERY chunk) run
                # unsharded — surface it instead of silently idling chips.
                logger.warning(
                    "sp=%d does not divide page_size=%d: prefill chunks "
                    "whose bucketed length is not a multiple of sp run "
                    "unsharded (non-power-of-two sp never shards)",
                    self._sp, mcfg.page_size)
        if self.cfg.max_pages_per_seq * self.cfg.max_batch > self.cfg.num_pages:
            logger.warning("page pool smaller than worst-case demand; requests may stall")
        self.processor = ChunkedTokenDatabase(
            TokenProcessorConfig(
                block_size_tokens=mcfg.page_size, hash_seed=self.cfg.hash_seed
            )
        )
        # Hybrid (mixed full/SWA layers): two cache groups with separate
        # page pools and block managers; events carry group tags + specs so
        # the indexer's GroupCatalog and HybridAwareScorer see the real
        # layout (reference hma.go:32-66 from the producer side).
        self.hybrid = mcfg.is_hybrid
        self.params = params if params is not None else init_params(
            jax.random.PRNGKey(seed), mcfg
        )
        self.requests: dict[str, Request] = {}
        self._running: list[str] = []
        self.swa_manager: Optional[BlockManager] = None
        self.k_swa = self.v_swa = None
        kv_dtype = (mcfg.dtype if self.cfg.kv_cache_dtype is None
                    else _resolve_kv_dtype(self.cfg.kv_cache_dtype))
        self._kv_dtype = kv_dtype
        self._fp8_cache = jnp.dtype(kv_dtype).itemsize == 1
        if self._fp8_cache:
            if mcfg.is_mla:
                raise ValueError(
                    "kv_cache_dtype=f8_e4m3 does not support MLA latent "
                    "pools yet (absorbed-attention latents are more "
                    "quantization-sensitive; keep bf16)")
        if self.hybrid:
            num_swa = self.cfg.num_swa_pages or self.cfg.num_pages
            self.block_manager = BlockManager(
                self.cfg, self.processor, event_sink, group_idx=0,
                spec_kind=SPEC_FULL_ATTENTION, spec_window=None,
            )
            self.swa_manager = BlockManager(
                self.cfg, self.processor, event_sink, group_idx=1,
                num_pages=num_swa, spec_kind=SPEC_SLIDING_WINDOW,
                spec_window=mcfg.sliding_window,
            )
            self.k_cache, self.v_cache, self.k_swa, self.v_swa = (
                init_kv_cache_hybrid(mcfg, self.cfg.num_pages, num_swa,
                                     dtype=kv_dtype)
            )
        else:
            self.block_manager = BlockManager(self.cfg, self.processor, event_sink)
            self.k_cache, self.v_cache = init_kv_cache(
                mcfg, self.cfg.num_pages, dtype=kv_dtype)

        fuse = self.cfg.fuse_projections
        # Fusion composes with tp/dp/sp meshes via the per-rank
        # interleaved column layout (fused_interleave = tp below). Two
        # mesh modes stay unfused: MLA (the fused input block mixes
        # head-sharded and replicated columns — no uniform interleave
        # shards that) and pp (the stacked-layer pspec derivation only
        # covers the canonical layout).
        fuse_mesh_blocked = mesh is not None and (mcfg.is_mla
                                                  or self._pp > 1)
        if fuse is None:
            from .llama import fuse_profitable

            # Width-divisibility for the interleave needs no extra gate
            # here: validate_tp_config (above) already requires every
            # projection width to divide tp — the unfused Megatron
            # shards have the identical constraint. The profit gate sees
            # per-shard widths: tp divides each rank's matmul columns, so
            # a model above the crossover at tp=1 can sit below it here.
            fuse = (fuse_profitable(mcfg, tp=self._tp)
                    and not fuse_mesh_blocked)
        if fuse and fuse_mesh_blocked:
            raise ValueError(
                "fuse_projections=True is incompatible with "
                + ("MLA under a mesh (head-sharded and replicated "
                   "columns cannot interleave uniformly)"
                   if mcfg.is_mla else
                   "pp serving (stacked layers keep the canonical "
                   "layout)"))
        if fuse:
            from .llama import fuse_params, unfuse_params

            if self._tp > 1:
                # Interleave the fused columns per tp rank so the
                # Megatron uniform column split hands each shard its
                # local fused block; the forward's split sites consult
                # cfg.fused_interleave (checkpoint save canonicalizes
                # back to the unfused layout). A COPY of the engine
                # config carries it — the caller's object is not
                # mutated.
                if "w_qkv" in self.params["layers"][0]:
                    # A shared pre-fused tree (maybe_fuse_params) is in
                    # CANONICAL column order; re-layout it into this
                    # engine's interleaved order (fuse_params below is
                    # a no-op on fused keys and would leave the split
                    # sites silently permuting q/k/v).
                    self.params = unfuse_params(self.params, mcfg)
                mcfg = dataclasses.replace(mcfg,
                                           fused_interleave=self._tp)
                self.cfg = dataclasses.replace(self.cfg, model=mcfg)
            self.params = fuse_params(self.params, mcfg)

        if mesh is not None and self._pp > 1:
            from ..parallel.pp_serve import shard_pp_state

            # self.params becomes the STACKED layer tree (layer axis over
            # pp); checkpoint save unstacks back to the canonical layout.
            self.params, self.k_cache, self.v_cache = shard_pp_state(
                mesh, mcfg, self.params, self.k_cache, self.v_cache)
        elif mesh is not None:
            from ..parallel.serve import shard_engine_params, shard_kv_pool

            self.params = shard_engine_params(mesh, self.params)
            self.k_cache, self.v_cache = shard_kv_pool(
                mesh, self.k_cache, self.v_cache)
            if self.hybrid:
                self.k_swa, self.v_swa = shard_kv_pool(
                    mesh, self.k_swa, self.v_swa)

        # Resolve the decode attention backend once (the platform cannot
        # change over the engine's lifetime).
        use_pallas = self.cfg.use_pallas_decode
        on_tpu = jax.devices()[0].platform == "tpu"
        if use_pallas is None:
            use_pallas = on_tpu
        if self._pp > 1:
            if self.cfg.use_pallas_decode:
                logger.warning("pp serving v1 runs the XLA attention "
                               "backend; use_pallas_decode ignored")
            use_pallas = False
        # The kernels' per-page DMA width is the cache payload width:
        # head_dim for standard/GQA attention, the latent width
        # (rank + rope + latent_pad) for absorbed MLA — which runs as the
        # kernels' kv_heads=1 multi-query case. Sink masks apply in-kernel
        # (StreamingLLM first-S positions), so neither family gates Pallas
        # off anymore; only Mosaic's 128-lane alignment does.
        kernel_width = mcfg.kv_cache_head_dim
        if use_pallas and on_tpu and not _pallas_head_dim_supported(
                kernel_width):
            # Mosaic lane-tiling constraint (see ops.pallas_paged_attention
            # .head_dim_supported); interpreter-mode tests still cover such
            # shapes, on-chip serving falls back to XLA paged attention.
            if self.cfg.use_pallas_decode:
                hint = (" (set LlamaConfig.latent_pad to align the latent "
                        "width)" if mcfg.is_mla else "")
                logger.warning(
                    "cache payload width %d is not 128-aligned: Pallas "
                    "paged attention cannot compile on TPU, using XLA "
                    "paged attention%s", kernel_width, hint)
            use_pallas = False
        fp8_cache = self._fp8_cache
        if fp8_cache and use_pallas:
            # fp8 rides the merged-heads decode kernel's quant arm (flat
            # whole-page [kvh*ps, hd] DMAs + in-VMEM upcast), which needs
            # kv_heads > 1 and kv_heads*page_size % 32 == 0 for Mosaic's
            # 8-bit tiling; other shapes fall back to XLA attention.
            # Under tp the kernel runs per shard on kv_heads/tp local
            # heads (validate_tp_config guarantees divisibility), so the
            # gate must check the LOCAL shape — the kernel re-validates
            # per shard and would raise at serve time otherwise.
            local_kvh = mcfg.kv_cache_heads // self._tp
            if local_kvh <= 1 or (local_kvh * mcfg.page_size) % 32:
                if self.cfg.use_pallas_decode:
                    logger.warning(
                        "fp8 cache shape (kv_heads=%d/tp=%d, page_size=%d)"
                        " cannot ride the quantized flash-decode kernel; "
                        "using XLA attention",
                        mcfg.kv_cache_heads, self._tp, mcfg.page_size)
                use_pallas = False
        # Hybrid: fused bursts run the grouped two-pool scan
        # (forward_decode_steps_hybrid) with freeze-and-reclaim SWA paging,
        # and the flash-decode kernel applies there per layer (each layer
        # sees only its own group's table/window). The SINGLE-token hybrid
        # step stays on the XLA grouped forward — at one token per dispatch
        # the kernel win is noise next to dispatch cost, and keeping one
        # code path for it bounds the jit-cache footprint.
        hybrid_burst_pallas = use_pallas and self.hybrid
        if self.hybrid:
            if use_pallas and self.cfg.use_pallas_decode:
                if self.cfg.decode_burst > 1:
                    logger.info(
                        "hybrid model: Pallas decode applies to fused "
                        "bursts; single-token steps use XLA attention")
                else:
                    logger.warning(
                        "hybrid model with decode_burst=1: Pallas decode "
                        "only runs inside fused bursts, so every decode "
                        "uses XLA attention (set decode_burst>1 to engage "
                        "the kernel)")
            use_pallas = False
        rows = max(1, self.cfg.decode_batch_rows)
        if mcfg.kv_cache_heads == 1:
            # The multi-row path rides the merged-heads kernel, which the
            # wrapper only engages for kv_heads > 1 (MLA/MQA pools run the
            # per-head grid) — clamp instead of crashing, matching the
            # knob's documented ignore-when-unavailable behavior.
            rows = 1
        if use_pallas:
            # Under tp the kernels run per-shard over the kv-heads
            # sharding via shard_map (the decode grid is per-kv-head
            # independent, so no cross-shard traffic in attention itself).
            pallas_mesh = mesh if self._tp > 1 else None
            if pallas_mesh is not None:
                rows = 1  # sharded path keeps one row per program
            self._decode_forward = functools.partial(
                forward_decode_pallas, interpret=not on_tpu,
                mesh=pallas_mesh, batch_rows=rows,
            )
        else:
            pallas_mesh = None
            self._decode_forward = forward
        # Prefill backend is independent of decode: auto (None) follows
        # the Pallas backend's platform/head-dim gating — the flash
        # kernel measured 1.9× faster than XLA attention at production
        # chunks on a real v5e (see EngineConfig.use_pallas_prefill).
        # Auto engages only on real TPU: interpret-mode flash prefill on
        # CPU is orders slower than XLA with no fidelity gain (tests that
        # want it opt in with use_pallas_prefill=True).
        prefill_pallas = (use_pallas and on_tpu
                          if self.cfg.use_pallas_prefill is None
                          else self.cfg.use_pallas_prefill)
        if fp8_cache and prefill_pallas:
            # The prefill kernel's per-head grid DMAs [page_size, hd]
            # sub-slices, misaligned for 8-bit tiling — fp8 prefill runs
            # XLA attention (gathers 1-byte pages, upcasts on read). fp8
            # trades prefill kernel speed for decode bandwidth + 2x KV
            # capacity; TTFT-bound deployments should keep bf16.
            if self.cfg.use_pallas_prefill:
                logger.warning(
                    "kv_cache_dtype=f8_e4m3: flash prefill unavailable "
                    "(8-bit DMA tiling); using XLA prefill")
            prefill_pallas = False
        if prefill_pallas and use_pallas:
            self._prefill_forward = functools.partial(
                forward_prefill_pallas, interpret=not on_tpu, mesh=pallas_mesh
            )
        else:
            if self.cfg.use_pallas_prefill and not use_pallas:
                logger.warning(
                    "use_pallas_prefill=True ignored: the Pallas backend is "
                    "inactive (platform/head-dim/hybrid gating above); using "
                    "XLA prefill")
            self._prefill_forward = forward
        self._decode_multi = functools.partial(
            forward_decode_steps, use_pallas=use_pallas,
            interpret=use_pallas and not on_tpu, mesh=pallas_mesh,
            batch_rows=rows if use_pallas else 1,
        )
        hybrid_mesh = (mesh if hybrid_burst_pallas and self._tp > 1
                       else None)
        self._decode_multi_hybrid = functools.partial(
            forward_decode_steps_hybrid, use_pallas=hybrid_burst_pallas,
            interpret=hybrid_burst_pallas and not on_tpu,
            mesh=hybrid_mesh,
            batch_rows=(rows if hybrid_burst_pallas and hybrid_mesh is None
                        else 1),
        )
        if self._pp > 1:
            from ..parallel.pp_serve import make_pp_serve_forward

            # Prefill runs per request (batch 1 → the sequential M=1
            # schedule); decode pads to max_batch and streams pp
            # microbatches through the stages.
            pp_prefill_fn = make_pp_serve_forward(mesh, mcfg, self.params,
                                                  microbatches=1)
            pp_decode_fn = (pp_prefill_fn if self._pp_decode_mb == 1
                            else make_pp_serve_forward(
                                mesh, mcfg, self.params,
                                microbatches=self._pp_decode_mb))

            def pp_prefill(params, _cfg, tokens, k, v, table, ctx, new,
                           last_only=True):
                logits, k, v = pp_prefill_fn(params, k, v, tokens, table,
                                             ctx, new)
                return logits[:, None, :], k, v

            def pp_decode(params, _cfg, tokens, k, v, tables, ctx, new):
                logits, k, v = pp_decode_fn(params, k, v, tokens, tables,
                                            ctx, new)
                return logits[:, None, :], k, v

            self._prefill_forward = pp_prefill
            self._decode_forward = pp_decode
            if self.cfg.decode_burst > 1:
                logger.warning("pp serving v1 decodes single-token; "
                               "decode_burst=%d clamped to 1",
                               self.cfg.decode_burst)

        # Burst size: the power-of-two floor of cfg.decode_burst, fixed for
        # the engine's lifetime — ONE fused-decode program. Per-row budgets
        # freeze finished rows on-device, so ticks past every row's budget
        # cost ~a token's compute; shrinking the burst near a request's
        # tail instead (an earlier design) compiled a fresh program per
        # smaller bucket mid-serving — measured 2 s per compile on the v5e
        # tunnel, cratering steady-state decode on short generations.
        self._burst = 1
        while self._burst * 2 <= self.cfg.decode_burst and self._pp == 1:
            self._burst *= 2
        # Latched when the SWA pool proves too small for burst transients:
        # the engine then decodes single-token for its lifetime (warned
        # once) — deterministic behavior instead of a doomed per-step
        # allocation retry.
        self._burst_degraded = False

        # Ragged single-kernel scheduling (EngineConfig.ragged_attention):
        # resolve eligibility ONCE — the blockers are all engine-lifetime
        # facts, so the step path branches on a plain bool. Ineligible
        # configurations warn here and keep the padded two-kernel path.
        self._ragged = False
        self._ragged_interpret = not on_tpu
        if self.cfg.ragged_attention:
            blockers = []
            if self.hybrid:
                blockers.append("hybrid attention groups (two page pools)")
            if mesh is not None:
                blockers.append("mesh-sharded serving (tp/sp/pp)")
            if self._burst != 1:
                blockers.append(
                    f"decode_burst={self.cfg.decode_burst} (fused bursts "
                    "scan the padded decode program)")
            if on_tpu and not _pallas_head_dim_supported(kernel_width):
                blockers.append(
                    f"cache payload width {kernel_width} is not "
                    "128-aligned")
            if (self._fp8_cache and on_tpu
                    and (mcfg.kv_cache_heads * mcfg.page_size) % 32):
                blockers.append(
                    "fp8 page shape (kv_heads*page_size % 32 != 0 breaks "
                    "Mosaic's 8-bit tiling)")
            if blockers:
                logger.warning(
                    "ragged_attention=True unavailable (%s): using the "
                    "padded two-kernel path", "; ".join(blockers))
            else:
                self._ragged = True

        # Optional shared-storage offload tier (offload.SharedStorageOffloadSpec):
        # write-through on commit, restore on prefix miss at admission.
        self.offload_manager = None
        self.offload_handlers = None
        self._pending_store_jobs: dict[int, list[int]] = {}
        # Deferred-restore bookkeeping: results for these job ids must be
        # stashed by ANY drain (poll_offload's untargeted drain would
        # otherwise swallow a completion before the owning request polls).
        self._restore_job_ids: set[int] = set()
        self._restore_results: dict[int, Any] = {}
        self._offload_medium = ""
        if offload_spec is not None:
            # Works under pp too: the copier's gather/scatter programs
            # run SPMD over the layer-sharded pools (GSPMD inserts the
            # collectives; scatter preserves the pp sharding) — pinned by
            # tests/test_pp_serve.py's offload round-trip.
            if getattr(offload_spec, "attention_sinks", 0) != (
                    mcfg.attention_sinks):
                # The sink mask changes deeper layers' KV past the window;
                # a spec that disagrees would fingerprint to the wrong
                # store directory and resume byte-incompatible blocks.
                raise ValueError(
                    f"offload spec attention_sinks="
                    f"{getattr(offload_spec, 'attention_sinks', 0)} does "
                    f"not match the model's {mcfg.attention_sinks}")
            spec_dtype = getattr(offload_spec, "dtype", "bfloat16")
            cache_dtype_name = jnp.dtype(self._kv_dtype).name
            if spec_dtype != cache_dtype_name:
                # The dtype is a fingerprint field: a mismatched spec
                # would resume stores whose bytes are a different element
                # type (e.g. bf16 blocks into an fp8 pool).
                raise ValueError(
                    f"offload spec dtype={spec_dtype!r} does not match "
                    f"the engine's KV cache dtype {cache_dtype_name!r} "
                    f"(set OffloadSpec dtype accordingly)")
            self.offload_manager = offload_spec.get_manager()
            self.offload_handlers = offload_spec.get_handlers(
                self.k_cache, self.v_cache
            )
            if self.hybrid:
                # Hybrid: group 1 (SWA) gets its own copier bound to the
                # SWA pool; both groups store/restore, keyed by group_idx
                # into per-group store directories/key prefixes. Both the
                # POSIX and object-store backends route per-group copiers.
                if not hasattr(self.offload_handlers, "copiers"):
                    raise NotImplementedError(
                        "hybrid models need per-group offload copiers; the "
                        f"{offload_spec.backend!r} backend has none")
                from ..offload.tpu_copier import TPUBlockCopier

                self.offload_handlers.copiers[1] = TPUBlockCopier(
                    self.k_swa, self.v_swa
                )
            # Canonical medium label (matches KV-event medium strings).
            self._offload_medium = offload_spec.medium

        # Disaggregated serving (offload.handoff): a coordinator attached
        # via attach_handoff turns a "prefill"-role engine into the
        # transfer's producer (per-chunk write-through commits notify it)
        # and a "decode"-role engine into its consumer (handoff=True
        # enqueues wait on it). on_restore_latency is an optional tap fed
        # each successful deferred-restore's wall time — the serving
        # assembly wires it into the index's observe_tier_latency so
        # residency scoring learns the transfer tier's real restore cost.
        if self.cfg.role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"unknown engine role {self.cfg.role!r} "
                "(expected 'both', 'prefill', or 'decode')")
        if self.cfg.role != "both" and self.hybrid:
            raise ValueError(
                "prefill/decode disaggregation needs a non-hybrid model "
                "(hybrid restores are all-or-nothing, not chunk-granular)")
        if self.cfg.role != "both" and self.offload_manager is None:
            raise ValueError(
                f"role={self.cfg.role!r} needs an offload spec — the "
                "handoff moves KV through the shared transfer tier")
        self.handoff = None
        # store job id → (request_id, block hashes) for jobs the handoff
        # coordinator must hear about when they settle.
        self._handoff_store_jobs: dict[int, tuple[str, list[int]]] = {}
        self.on_restore_latency: Optional[Callable[[float], None]] = None
        # Streaming EMA of successful restore wall time (both restore
        # paths feed it): the deferred-restore deadline gate skips the
        # storage tier when the remaining budget is smaller than what a
        # restore typically costs — recompute is the faster path then.
        self._restore_latency_ema = 0.0

        # Admission overload shedding (CoDel over burst-admission delay).
        # None unless configured — the disabled path costs one attribute
        # load per enqueue/step.
        self.shedder: Optional[CoDelShedder] = None
        if self.cfg.shed_target_delay_s > 0:
            self.shedder = CoDelShedder(
                "engine.admission",
                target_delay_s=self.cfg.shed_target_delay_s,
                interval_s=self.cfg.shed_interval_s,
            )

        # Engine data-plane telemetry: request-lifecycle histograms
        # (TTFT/ITL/TPOT), decimated KV-pool gauge scrapes, per-request
        # flight-recorder events. None when the config leaves it off —
        # every hook site below guards on that, so the disabled step path
        # pays one attribute load + branch per site.
        self.telemetry = None
        # Working-set analytics: None until attach_workingset wires a
        # telemetry.workingset.WorkingSetTracker (same guard style).
        self.workingset = None
        # Ground-truth audit: None until attach_audit wires a
        # telemetry.audit.AuditLog (same guard style).
        self.audit = None
        self._telemetry_pools: list[tuple[str, BlockManager]] = []
        tcfg = self.cfg.telemetry
        if tcfg is not None and getattr(tcfg, "enabled", True):
            from ..telemetry.engine_telemetry import EngineTelemetry

            self.telemetry = EngineTelemetry(
                tcfg, group=self.cfg.pod_identifier)
            self._telemetry_pools = [("full", self.block_manager)]
            if self.hybrid:
                self._telemetry_pools.append(("swa", self.swa_manager))
            self.telemetry.scrape_pools(self._telemetry_pools)

    # -- admission --

    def attach_handoff(self, coordinator) -> None:
        """Wire a :class:`~..offload.handoff.HandoffCoordinator`.

        On a "prefill"-role engine every chunk-commit store job reports
        chunk start/landed/failed to it; on a "decode"-role engine
        ``enqueue(handoff=True)`` requests consult it to decide between
        waiting, pulling, and falling back to local prefill.
        """
        self.handoff = coordinator

    def set_role(self, role: str) -> str:
        """Re-role a running engine (the fleet controller's
        prefill↔decode flip); returns the previous role.

        Same invariants as construction: a non-"both" role needs a
        non-hybrid model and an offload spec. The flip affects requests
        admitted *after* it — in-flight requests finish under the role
        they were admitted with (their handoff state machine is already
        chosen), which is exactly the drain semantics the controller
        wants.
        """
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"unknown engine role {role!r} "
                "(expected 'both', 'prefill', or 'decode')")
        if role != "both" and self.hybrid:
            raise ValueError(
                "prefill/decode disaggregation needs a non-hybrid model "
                "(hybrid restores are all-or-nothing, not chunk-granular)")
        if role != "both" and self.offload_manager is None:
            raise ValueError(
                f"role={role!r} needs an offload spec — the handoff moves "
                "KV through the shared transfer tier")
        old = self.cfg.role
        self.cfg = dataclasses.replace(self.cfg, role=role)
        return old

    def attach_workingset(self, tracker) -> None:
        """Wire a telemetry.workingset.WorkingSetTracker into this
        engine's cache paths: admission feeds the "hbm" reuse stream
        (every request's block keys, hit count = resident prefix), the
        block manager's evictions feed the eviction-age histogram, and
        the offload manager's lookups/stores feed the storage-tier
        stream plus the written-never-read ledger. Also declares the
        real HBM pool capacity so the what-if table has its 1x anchor.
        """
        self.workingset = tracker
        self.block_manager.on_evict = tracker.record_eviction_age
        tracker.set_capacity("hbm", self.block_manager.num_pages)
        if self.offload_manager is not None:
            self.offload_manager.workingset = tracker

    def attach_audit(self, audit_log) -> None:
        """Wire a :class:`~..telemetry.audit.AuditLog`: every admitted
        request's realized prefix outcome (HBM hit vs restored vs
        recomputed blocks) is recorded at prefill finish, tagged with the
        request's traceparent and the :class:`ScoreFeedback` it was
        routed on, for the fleet collector's score-vs-reality join
        (``/debug/audit``)."""
        self.audit = audit_log

    def add_request(self, request_id: str, prompt: Sequence[int],
                    max_new_tokens: int = 16) -> Request:
        """Admit a request: acquire cached prefix pages, allocate the rest,
        and run the prefill step for the uncached suffix (synchronously —
        the request returns ready to decode)."""
        req = self._admit(request_id, prompt, max_new_tokens)
        self._prefill(req)
        self._finish_prefill(req)
        return req

    def _record_shed(self, outcome: str, priority: int) -> None:
        """Best-effort shed accounting: metric family + flight recorder.
        Never lets telemetry failures interfere with admission."""
        try:
            from ..metrics.collector import record_shed

            record_shed("engine.admission", outcome)
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
        try:
            from ..telemetry.flight_recorder import KIND_SHED, record

            record(KIND_SHED, {
                "site": "engine.admission",
                "outcome": outcome,
                "priority": priority,
            })
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass

    def enqueue(self, request_id: str, prompt: Sequence[int],
                max_new_tokens: int = 16,
                traceparent: Optional[str] = None,
                handoff: bool = False,
                deadline_s: Optional[float] = None,
                priority: int = PRIORITY_NORMAL,
                feedback=None) -> Request:
        """Admit a request for continuous batching: pages are acquired and
        the storage tier consulted from ``step()``, where prefill runs
        chunk-at-a-time interleaved with decode — a long prompt stalls
        running decodes by at most one chunk (``max_prefill_tokens``), not
        its whole prefill (vLLM chunked-prefill scheduling). The storage
        restore is likewise deferred and polled across steps, so a slow
        storage tier costs the restored request latency, never the
        running decodes'.

        ``traceparent`` (e.g. ``ScoreResponse.traceparent`` from the pod
        that scored this request) parents the engine's admission/prefill/
        decode-step spans under the scorer's trace — one trace covers
        score→serve. Requests without one create no spans at all.

        ``handoff=True`` (decode-role engines) marks this request as the
        receiving end of a prefill→decode handoff: ``step()`` holds its
        local prefill for up to ``cfg.handoff_wait_s``, re-arming the
        deferred-restore probe as the prefill peer's chunks land on the
        transfer tier — the KV pull overlaps queueing and the running
        decode batch. A failed or timed-out transfer falls back to local
        prefill (the request is never lost).

        ``deadline_s`` attaches an end-to-end budget (falls back to the
        ambient :func:`deadline_scope` when omitted): a deferred storage
        restore that cannot land inside the remaining budget is skipped
        in favor of recompute. When the admission shedder is configured
        (``cfg.shed_target_delay_s``), sustained admission delay sheds
        non-critical requests (:class:`OverloadShedError`) and browns out
        the rest — admitted, but without the storage-restore attempt.

        ``feedback`` (a ``services.indexer_service.ScoreFeedback``, or
        any object with its fields) is the prediction this request was
        routed on; with an :meth:`attach_audit` log it rides the realized
        outcome record so the fleet collector can score the prediction
        even when the scorer's own ring already evicted it.
        """
        brownout = False
        if self.shedder is not None:
            verdict = self.shedder.admit(priority)
            if verdict == SHED:
                self._record_shed("shed", priority)
                raise OverloadShedError(
                    "engine.admission", self.shedder.last_delay_s)
            if verdict == BROWNOUT:
                brownout = True
                self._record_shed("brownout", priority)
        if traceparent is not None:
            with tracer().span(
                "llm_d.kv_cache.engine.admission",
                parent_traceparent=traceparent,
                request_id=request_id,
                prompt_tokens=len(prompt),
                process=self.cfg.pod_identifier,
            ) as sp:
                req = self._admit(request_id, prompt, max_new_tokens,
                                  defer_restore=True)
                sp.set_attribute(
                    "prefix_hit_blocks",
                    req.cached_len // self.cfg.model.page_size)
            req.traceparent = traceparent
            if self.telemetry is not None:
                self.telemetry.set_traceparent(request_id, traceparent)
        else:
            req = self._admit(request_id, prompt, max_new_tokens,
                              defer_restore=True)
        req.deadline = (
            Deadline.after(deadline_s) if deadline_s is not None
            else current_deadline()
        )
        req.priority = priority
        req.feedback = feedback
        if brownout and req.restore_pending:
            # Brownout: admitted, but skip the storage-tier restore —
            # under queue pressure the offload round trip is the first
            # cost to drop (recompute keeps the scheduler moving).
            req.restore_pending = False
        # Burst-admission latency: with decode_burst > 1 the first prefill
        # chunk can only run once the in-flight burst drains — observed at
        # first schedule (kvcache_engine_admission_delay_seconds).
        req.enqueued_at = time.monotonic()
        if handoff:
            if self.hybrid or self.offload_manager is None:
                raise ValueError(
                    "handoff=True needs a non-hybrid engine with an "
                    "offload spec (the transfer arrives via the tier)")
            req.handoff_deadline = time.monotonic() + self.cfg.handoff_wait_s
        return req

    def _admit(self, request_id: str, prompt: Sequence[int],
               max_new_tokens: int, defer_restore: bool = False) -> Request:
        """Shared admission: prefix-cache acquisition, storage restore,
        page allocation, registration. No model compute."""
        prompt = list(prompt)
        if not prompt:
            raise ValueError("empty prompt")
        req = Request(request_id=request_id, prompt=prompt,
                      max_new_tokens=max_new_tokens)
        page_size = self.cfg.model.page_size
        total_needed = (req.total_len + max_new_tokens + page_size - 1) // page_size + 1
        if total_needed > self.cfg.max_pages_per_seq:
            raise ValueError(
                f"request needs {total_needed} pages "
                f"(prompt {len(prompt)} + {max_new_tokens} new tokens) but "
                f"max_pages_per_seq is {self.cfg.max_pages_per_seq}"
            )
        req.block_hashes = self.processor.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, prompt, self.cfg.model_name
        )

        cached_pages = self.block_manager.acquire_prefix(req.block_hashes)
        if self.hybrid:
            # A resume at depth d needs group 0's FULL chain [0, d) but
            # only group 1's trailing window — blocks covering the last
            # ``sliding_window`` tokens (earlier SWA blocks are dropped
            # out-of-window and never needed again). Find the deepest d
            # whose trailing SWA window is resident; out-of-window slots
            # map to the garbage page (attention masks them anyway).
            page_sz = self.cfg.model.page_size
            window = self.cfg.model.sliding_window
            d = len(cached_pages)
            swa_map: dict[int, int] = {}
            start_blk = 0
            while d > 0:
                start_blk = max(0, (d * page_sz - window) // page_sz)
                pages = self.swa_manager.try_acquire_blocks(
                    req.block_hashes[start_blk:d])
                if pages is not None:
                    swa_map = dict(zip(range(start_blk, d), pages))
                    break
                d -= 1
            if d < len(cached_pages):
                self.block_manager.release(req.block_hashes[d:len(cached_pages)], [])
            cached_pages = cached_pages[:d]
            req.swa_pages = [swa_map.get(i, 0) for i in range(d)]
            req.swa_acquired_from = start_blk if d > 0 else 0
        req.pages = list(cached_pages)
        req.cached_len = len(cached_pages) * page_size
        req.computed_len = req.cached_len
        req.hbm_hit_blocks = len(cached_pages)
        if self.workingset is not None:
            # Admission is the HBM tier's reuse stream: one access per
            # prompt block, hits = the resident prefix length.
            self.workingset.record_accesses(
                "hbm", req.block_hashes, hits=len(cached_pages))

        # Storage tier: extend the HBM prefix hit with blocks resident on
        # shared storage. add_request (synchronous serving) restores here —
        # one high-priority read, far below a prefill. enqueue (continuous
        # batching) defers: the lookup+load start inside step() and the job
        # is polled across steps, because a restore blocking _admit would
        # stall every running decode for up to the load deadline (the
        # hybrid two-pool restore is all-or-nothing and stays synchronous —
        # its window coupling makes a half-restored resume unusable).
        if self.offload_manager is not None:
            if defer_restore and not self.hybrid:
                req.restore_pending = True
            else:
                self._restore_from_storage(req)

        # Pages for the uncached remainder (incl. partial tail + decode
        # room). Group 1 (SWA) pages are NOT pre-allocated: _prefill and
        # decode allocate them lazily per chunk and reclaim out-of-window
        # slots as the context advances, so peak SWA-pool demand stays
        # window-bounded instead of prompt-length-bounded.
        new_pages: list[int] = []

        def rollback():
            # Return popped pages and drop the refs on every block this
            # request holds — the HBM prefix AND any blocks just restored
            # from storage — so a failed admission cannot shrink the pool
            # or pin blocks against eviction.
            n_cached = req.cached_len // page_size
            self.block_manager.free_pages.extend(new_pages)
            self.block_manager.release(req.block_hashes[:n_cached], [])
            if self.hybrid:
                self.swa_manager.release(
                    req.block_hashes[req.swa_acquired_from:n_cached], [])

        while len(req.pages) + len(new_pages) < total_needed:
            page = self.block_manager.allocate_page()
            if page is None:
                rollback()
                raise RuntimeError("out of KV pages")
            new_pages.append(page)
        req.pages.extend(new_pages)

        # Everything acquired/restored so far is registered+refcounted in
        # the block manager; later pages stay private until commit.
        req.committed_blocks = req.cached_len // page_size
        # Prefill cursor (a full-prefix hit still recomputes the last
        # prompt token for logits, hence the min with len-1); add_request
        # drains it synchronously, enqueue leaves it for step().
        req.prefill_pos = min(req.cached_len, len(req.prompt) - 1)
        self.requests[request_id] = req
        self._running.append(request_id)
        if self.telemetry is not None:
            self.telemetry.on_admitted(
                request_id, req.cached_len // page_size)
        return req

    def _finish_prefill(self, req: Request) -> None:
        """Prefill done: register the prompt's full blocks in the prefix
        cache and bootstrap decoding with the first generated token (from
        the prefill step's final logits — vLLM semantics: even a
        full-prefix hit recomputes the last prompt token for logits)."""
        req.table_dev = None  # pages may swap to canonical at commit
        self._commit_full_blocks(req)
        first_token = int(np.argmax(req.last_logits))
        req.output.append(first_token)
        if self.telemetry is not None:
            self.telemetry.on_first_token(req.request_id)
        if self.audit is not None:
            self._emit_audit_outcome(req)
        if self.cfg.role == "prefill" and self.handoff is not None:
            # Prefill pod: the request's life here ends at first token —
            # every full block is now committed (the final chunk's store
            # job just entered the plane), the decode pod recomputes the
            # partial tail and the bootstrap token itself, so this token
            # is discarded. Mark the transfer complete-when-settled before
            # finishing so the coordinator flips ``done`` as the last
            # store job lands.
            self.handoff.prefill_finished(req.request_id)
            req.done = True
            self._finish(req)
            return
        if req.max_new_tokens <= 1:
            req.done = True
            self._finish(req)

    def _emit_audit_outcome(self, req: Request) -> None:
        """Best-effort ground-truth emission at prefill finish: the
        realized prefix decomposition (HBM hit at admission, restored
        since, recomputed remainder) into the attached AuditLog plus a
        KIND_AUDIT flight record. Never interferes with serving."""
        page_size = self.cfg.model.page_size
        total = len(req.block_hashes)
        realized = min(req.cached_len // page_size, total)
        hbm = min(req.hbm_hit_blocks, realized)
        restored = min(req.restored_blocks, realized - hbm)
        recomputed = max(total - realized, 0)
        try:
            self.audit.record_outcome(
                traceparent=req.traceparent,
                request_id=req.request_id,
                pod=self.cfg.pod_identifier,
                total_blocks=total,
                hbm_blocks=hbm,
                restored_blocks=restored,
                recomputed_blocks=recomputed,
                feedback=req.feedback,
            )
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
        try:
            from ..telemetry.flight_recorder import KIND_AUDIT, record

            record(KIND_AUDIT, {
                "op": "outcome",
                "request_id": req.request_id,
                "pod": self.cfg.pod_identifier,
                "total_blocks": total,
                "hbm_blocks": hbm,
                "restored_blocks": restored,
                "recomputed_blocks": recomputed,
            })
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass

    def _sync_caches_to_copier(self) -> None:
        """Hand the current (possibly donated-and-replaced) cache arrays to
        the offload copiers; forward() replaces the cache arrays every
        step, so the copiers must never hold stale references."""
        self.offload_handlers.copier.k_cache = self.k_cache
        self.offload_handlers.copier.v_cache = self.v_cache
        if self.hybrid:
            self.offload_handlers.copiers[1].k_cache = self.k_swa
            self.offload_handlers.copiers[1].v_cache = self.v_swa

    def _sync_caches_from_copier(self) -> None:
        self.k_cache = self.offload_handlers.copier.k_cache
        self.v_cache = self.offload_handlers.copier.v_cache
        if self.hybrid:
            self.k_swa = self.offload_handlers.copiers[1].k_cache
            self.v_swa = self.offload_handlers.copiers[1].v_cache

    def _restore_from_storage(self, req: Request) -> None:
        """Load storage-resident blocks that extend the HBM prefix hit."""
        if self.hybrid:
            self._restore_from_storage_hybrid(req)
            return
        page_size = self.cfg.model.page_size
        first_missing = req.cached_len // page_size
        remaining = req.block_hashes[first_missing:]
        if not remaining:
            return
        n_stored = self.offload_manager.lookup(remaining)
        if n_stored == 0:
            return
        restore_hashes = remaining[:n_stored]
        pages: list[int] = []
        for _ in restore_hashes:
            page = self.block_manager.allocate_page()
            if page is None:
                break
            pages.append(page)
        if not pages:
            return
        restore_hashes = restore_hashes[: len(pages)]

        from ..metrics.collector import (
            record_engine_restore,
            record_offload_restore,
        )

        self._sync_caches_to_copier()
        started = time.monotonic()
        job = self.offload_handlers.async_load_blocks(
            [(h, [p]) for h, p in zip(restore_hashes, pages)]
        )
        result = None
        deadline = started + 30.0
        while result is None and time.monotonic() < deadline:
            result = self._drain_offload(target_job=job)
            if result is None:
                time.sleep(0.002)

        if result is None:
            # Timed out: cancel so a late completion can never scatter into
            # pages we are about to recycle.
            self.offload_handlers.wait_job(job, timeout_s=5.0)
        if result is None or not result.success:
            record_engine_restore("timeout" if result is None else "failure")
            logger.warning("storage restore failed for %d blocks", len(pages))
            self.block_manager.free_pages.extend(pages)
            return
        elapsed = time.monotonic() - started
        record_engine_restore("success", elapsed)
        record_offload_restore(self._offload_medium, elapsed)
        self._observe_restore_latency(elapsed)
        if self.on_restore_latency is not None:
            try:
                self.on_restore_latency(elapsed)
            except Exception:  # pragma: no cover  # lint: allow-swallow
                pass

        # Register restored blocks in the prefix cache (no re-store event:
        # the blocks are already on the storage tier; the HBM BlockStored
        # is emitted through commit so the index learns the HBM copy).
        canonical = self._commit_restored_blocks(
            req, first_missing, restore_hashes, pages
        )
        req.pages.extend(canonical)
        req.cached_len += len(canonical) * page_size
        req.computed_len = req.cached_len
        req.restored_blocks += len(canonical)

    def _observe_restore_latency(self, elapsed: float) -> None:
        """Fold a successful restore's wall time into the EMA the
        deadline gate consults (first sample seeds it directly)."""
        ema = self._restore_latency_ema
        self._restore_latency_ema = (
            elapsed if ema == 0.0 else ema + 0.2 * (elapsed - ema))

    def _commit_restored_blocks(self, req: Request, first_missing: int,
                                hashes: list, pages: list[int]) -> list[int]:
        """Adopt storage-restored blocks into the prefix cache — the shared
        commit tail of the synchronous and deferred restore paths. Returns
        the canonical pages (``commit_blocks`` may swap duplicates)."""
        page_size = self.cfg.model.page_size
        tokens_per_block = [
            req.prompt[(first_missing + i) * page_size:
                       (first_missing + i + 1) * page_size]
            for i in range(len(hashes))
        ]
        parent = (
            req.block_hashes[first_missing - 1] if first_missing > 0
            else EMPTY_BLOCK_HASH
        )
        return self.block_manager.commit_blocks(
            hashes, pages, tokens_per_block, parent
        )

    def _start_deferred_restore(self, req: Request) -> None:
        """Kick off the enqueue-path storage restore (non-hybrid).

        Unlike the synchronous path, the load lands in the pages the
        request already owns for those blocks (allocated at admission for
        the uncached remainder), so no extra pages are taken; on success
        ``commit_blocks`` adopts canonical pages and frees duplicates.

        Deadline gate: a request whose budget has expired — or whose
        remaining budget is smaller than what a restore typically costs
        (streaming EMA of past successes) — skips the storage tier and
        recomputes. A restore that lands after the caller stopped
        waiting is pure waste; prefill compute at least keeps the pages
        warm for the next caller.
        """
        req.restore_pending = False
        dl = req.deadline
        if dl is not None:
            remaining = dl.remaining_s()
            if remaining <= 0 or (0 < self._restore_latency_ema
                                  and remaining < self._restore_latency_ema):
                from ..metrics.collector import record_engine_restore

                record_engine_restore("deadline_skip")
                self._record_shed("restore_skip", req.priority)
                logger.debug(
                    "skipping storage restore for %s: %.3fs budget left, "
                    "restores take ~%.3fs", req.request_id,
                    max(0.0, remaining), self._restore_latency_ema)
                return
        page_size = self.cfg.model.page_size
        first_missing = req.cached_len // page_size
        remaining = req.block_hashes[first_missing:]
        if not remaining:
            return
        n_stored = self.offload_manager.lookup(remaining)
        if n_stored == 0:
            return
        restore_hashes = remaining[:n_stored]
        pages = req.pages[first_missing:first_missing + len(restore_hashes)]
        self._sync_caches_to_copier()
        job = self.offload_handlers.async_load_blocks(
            [(h, [p]) for h, p in zip(restore_hashes, pages)]
        )
        self._restore_job_ids.add(job)
        started = time.monotonic()
        req.restore_job = (job, first_missing, restore_hashes, pages,
                           started + 30.0, started)

    def _poll_deferred_restore(self, req: Request) -> bool:
        """Advance an in-flight deferred restore. Returns True once settled
        (success, failure, or timeout) — prefill may proceed; False while
        the load is still in flight (the step goes on decoding)."""
        from ..metrics.collector import (
            record_engine_restore,
            record_offload_restore,
        )

        job, first_missing, hashes, pages, deadline, started = req.restore_job
        result = self._restore_results.pop(job, None)
        if result is None:
            result = self._drain_offload(target_job=job)
        if result is not None:
            self._restore_job_ids.discard(job)
        if result is None:
            if time.monotonic() < deadline:
                return False
            # Timed out: non-blocking cancel (timeout 0) — kvio marks the
            # job cancelled so it can never scatter, and parks its staging
            # buffers; blocking here would stall every running decode for
            # exactly the degraded-storage case deferral exists to absorb.
            self.offload_handlers.wait_job(job, timeout_s=0.0)
            self._restore_job_ids.discard(job)
            self._restore_results.pop(job, None)
            req.restore_job = None
            record_engine_restore("timeout")
            logger.warning("deferred storage restore timed out; recomputing")
            return True
        req.restore_job = None
        if not result.success:
            record_engine_restore("failure", time.monotonic() - started)
            logger.warning("deferred storage restore failed; recomputing")
            return True
        elapsed = time.monotonic() - started
        record_engine_restore("success", elapsed)
        record_offload_restore(self._offload_medium, elapsed)
        self._observe_restore_latency(elapsed)
        if self.on_restore_latency is not None:
            # Residency scoring's tier-discount feed (index.cost_aware
            # .observe_tier_latency when the serving assembly wired it).
            try:
                self.on_restore_latency(elapsed)
            except Exception:  # pragma: no cover  # lint: allow-swallow
                pass
        page_size = self.cfg.model.page_size
        canonical = self._commit_restored_blocks(
            req, first_missing, hashes, pages
        )
        req.pages[first_missing:first_missing + len(canonical)] = canonical
        req.cached_len = (first_missing + len(canonical)) * page_size
        req.computed_len = max(req.computed_len, req.cached_len)
        req.restored_blocks += len(canonical)
        req.committed_blocks = max(req.committed_blocks,
                                   first_missing + len(canonical))
        req.prefill_pos = min(req.cached_len, len(req.prompt) - 1)
        req.table_dev = None  # pages may have swapped to canonical
        return True

    def _commit_prefill_chunk(self, req: Request) -> None:
        """Prefill-role mid-prefill commit: push the blocks this chunk
        completed into the prefix cache and the transfer tier."""
        before = req.committed_blocks
        self._commit_full_blocks(
            req, upto=req.computed_len // self.cfg.model.page_size)
        if req.committed_blocks != before:
            # commit_blocks may have swapped duplicate pages to canonical;
            # the cached device table would keep scattering into the
            # abandoned copies.
            req.table_dev = None

    def _handoff_gate(self, req: Request) -> bool:
        """Decide whether a handoff-admitted request may prefill locally.

        True once the handoff settled — transfer complete, peer failed
        (fallback), or deadline hit (timeout) — and prefill proceeds from
        whatever prefix is resident; False while the wait is live, in
        which case this step skips the prefill and keeps decoding.
        """
        target = len(req.prompt) // self.cfg.model.page_size
        if req.cached_len // self.cfg.model.page_size >= target:
            # Every full prompt block is resident; only the partial tail
            # and the last prompt token remain, and those always
            # recompute locally.
            self._handoff_settle(req, "complete")
            return True
        st = (self.handoff.state(req.request_id)
              if self.handoff is not None else None)
        if st is not None and st.failed:
            # Prefill peer died mid-handoff (PR 4 recovery semantics):
            # fall back to local prefill — landed blocks still count,
            # the request is re-prefilled here, never lost.
            self._handoff_settle(req, "fallback")
            return True
        if time.monotonic() >= req.handoff_deadline:
            self._handoff_settle(req, "timeout")
            return True
        # Re-arm the transfer probe: more peer chunks may have landed
        # since the last round. The lookup is cheap and a load job starts
        # only when the stored prefix actually grew.
        if req.restore_job is None:
            self._start_deferred_restore(req)
        if req.restore_job is not None:
            return False  # pull in flight — polled next step
        if st is not None and st.done:
            # Transfer settled and everything restorable was pulled; any
            # remainder (shed chunks) recomputes locally.
            self._handoff_settle(req, "complete")
            return True
        return False

    def _handoff_settle(self, req: Request, outcome: str) -> None:
        req.handoff_deadline = None
        if self.handoff is not None:
            self.handoff.decode_settled(req.request_id, outcome)

    def _restore_from_storage_hybrid(self, req: Request) -> None:
        """Storage restore for hybrid models.

        A valid resume state needs group 0's full chain [0, d) AND group
        1's trailing window of d — and ONLY the window: earlier SWA blocks
        are masked for every future position, so recomputation cannot be
        avoided anywhere the window is incomplete (SWA KV depends on
        activations that depend on the missing keys). Group 1 stores are
        exactly the in-window-at-commit blocks, so a full-chain resume
        normally finds its window; anything less skips the restore
        conservatively (all-or-nothing, no partial hybrid restores).
        """
        page_size = self.cfg.model.page_size
        window = self.cfg.model.sliding_window
        wb = -(-window // page_size)
        d = req.cached_len // page_size  # HBM-resident depth
        remaining = req.block_hashes[d:]
        if not remaining:
            return
        n_stored = self.offload_manager.lookup(remaining)
        if n_stored == 0:
            return
        depth_end = d + n_stored
        win_start = max(0, depth_end - wb)
        # Window slots below d are already HBM-resident (trailing-window
        # acquisition guaranteed them); only [load_from, depth_end) loads.
        load_from = max(win_start, d)
        win_hashes = req.block_hashes[load_from:depth_end]
        if self.offload_manager.lookup(win_hashes, group_idx=1) < len(win_hashes):
            logger.info(
                "hybrid restore skipped: SWA window of depth %d not fully "
                "stored", depth_end)
            return

        g0_hashes = req.block_hashes[d:depth_end]
        g0_pages = [self.block_manager.allocate_page() for _ in g0_hashes]
        g1_pages = [self.swa_manager.allocate_page() for _ in win_hashes]
        if any(p is None for p in g0_pages) or any(p is None for p in g1_pages):
            self.block_manager.free_pages.extend(p for p in g0_pages if p)
            self.swa_manager.free_pages.extend(p for p in g1_pages if p)
            return

        self._sync_caches_to_copier()
        job0 = self.offload_handlers.async_load_blocks(
            [(h, [p]) for h, p in zip(g0_hashes, g0_pages)])
        job1 = self.offload_handlers.async_load_blocks(
            [(h, [p]) for h, p in zip(win_hashes, g1_pages)], group_idx=1)
        targets = {job0, job1}
        results: dict = {}
        deadline = time.monotonic() + 30.0
        while len(results) < 2 and time.monotonic() < deadline:
            results.update(self._drain_offload_multi(targets))
            if len(results) < 2:
                time.sleep(0.002)
        for job in targets - set(results):
            # Timed out: cancel so a late completion can never scatter
            # into pages we are about to recycle.
            self.offload_handlers.wait_job(job, timeout_s=5.0)
            results[job] = None
        if any(r is None or not r.success for r in results.values()):
            logger.warning("hybrid storage restore failed; recomputing")
            self.block_manager.free_pages.extend(g0_pages)
            self.swa_manager.free_pages.extend(g1_pages)
            return

        def toks(i):
            return req.prompt[i * page_size:(i + 1) * page_size]

        g0_parent = req.block_hashes[d - 1] if d > 0 else EMPTY_BLOCK_HASH
        canonical0 = self.block_manager.commit_blocks(
            g0_hashes, g0_pages, [toks(d + i) for i in range(n_stored)],
            g0_parent,
        )
        req.pages.extend(canonical0)
        g1_parent = (
            req.block_hashes[load_from - 1] if load_from > 0 else EMPTY_BLOCK_HASH
        )
        canonical1 = self.swa_manager.commit_blocks(
            win_hashes, g1_pages,
            [toks(load_from + i) for i in range(len(win_hashes))],
            g1_parent,
        )
        req.swa_pages.extend([0] * (load_from - len(req.swa_pages)))
        req.swa_pages.extend(canonical1)
        req.cached_len = depth_end * page_size
        req.computed_len = req.cached_len
        req.restored_blocks += len(canonical0)
        # Blocks acquired for the OLD depth that now sit out of window
        # return to the pool (refs drop; table slots go to garbage).
        self._swa_reclaim(req)

    def _page_table_for(self, req: Request) -> np.ndarray:
        table = np.zeros((self.cfg.max_pages_per_seq,), np.int32)
        table[: len(req.pages)] = req.pages
        return table

    def _release_burst_transients(self, chunk: list[Request]) -> None:
        """Hand back SWA pages pre-extended for a burst that cannot run.

        Slots beyond each request's current decode block exist only
        because of this burst attempt (after a completed burst,
        ``computed_len`` has advanced past every written slot), so they
        are private, uncommitted, and safe to free directly.
        """
        page_size = self.cfg.model.page_size
        for req in chunk:
            keep = req.computed_len // page_size + 1
            while len(req.swa_pages) > keep:
                page = req.swa_pages.pop()
                if page:
                    self.swa_manager.free_pages.append(page)

    def _swa_table_for(self, req: Request) -> np.ndarray:
        table = np.zeros((self.cfg.max_pages_per_seq,), np.int32)
        table[: len(req.swa_pages)] = req.swa_pages
        return table

    def _swa_ensure(self, req: Request, upto_block: int) -> None:
        """Lazily extend the request's SWA page list through ``upto_block``
        (inclusive). SWA pages are allocated just-in-time so peak pool
        demand is window + chunk, not prompt length."""
        while len(req.swa_pages) <= upto_block:
            page = self.swa_manager.allocate_page()
            if page is None:
                raise RuntimeError("out of SWA KV pages")
            req.swa_pages.append(page)

    def _swa_reclaim(self, req: Request) -> None:
        """Return the request's out-of-window SWA pages to the pool.

        Slots below the current window start are never read again by this
        request (attention masks them). Private not-yet-committed pages
        free directly. Committed blocks drop this request's reference but
        STAY CACHED: a committed SWA block i always serves a resume at
        block boundary i+1 (whose trailing window covers it), so no
        committed block is ever resume-worthless — the live window slides
        past it, cache value does not. Space comes back through normal
        LRU pressure eviction (which emits BlockRemoved, keeping the
        index honest), exactly as for full-attention blocks. Reclaimed
        slots map to the garbage page.
        """
        page_size = self.cfg.model.page_size
        window = self.cfg.model.sliding_window
        first_in_window = max(0, req.computed_len - window) // page_size
        start = req.swa_acquired_from
        limit = min(first_in_window, len(req.swa_pages))
        if limit <= start:
            return
        committed: list[int] = []
        for i in range(start, limit):
            page = req.swa_pages[i]
            if not page:
                continue
            h = req.block_hashes[i] if i < len(req.block_hashes) else None
            info = self.swa_manager.blocks.get(h) if h is not None else None
            if info is not None and info.page == page:
                committed.append(h)
            else:
                self.swa_manager.free_pages.append(page)
            req.swa_pages[i] = 0
        if committed:
            self.swa_manager.release(committed, [])
        req.swa_acquired_from = limit

    def _prefill(self, req: Request) -> None:
        """Run the model over the whole uncached prompt suffix, chunked.

        Chunks of at most ``max_prefill_tokens`` bound activation memory on
        long prompts (vLLM-style chunked prefill); each chunk's KV lands in
        the paged cache so the next chunk attends over it.
        """
        while req.prefill_pos is not None:
            self._prefill_chunk(req)

    def _prefill_chunk(self, req: Request) -> None:
        """One prefill chunk at ``req.prefill_pos``; advances it (None once
        the prompt is fully prefilled, with ``last_logits`` populated —
        only the final chunk's logits are downloaded: each host transfer
        is a full round trip on a remote-tunneled device)."""
        page_size = self.cfg.model.page_size
        chunk_cap = max(page_size, self.cfg.max_prefill_tokens
                        // page_size * page_size)
        if req.table_dev is None:
            req.table_dev = jnp.asarray(self._page_table_for(req))[None, :]
        table = req.table_dev

        pos = req.prefill_pos
        chunk = req.prompt[pos:pos + chunk_cap]
        # Bucket the padded length to powers of two (in pages) so the
        # jit cache holds O(log max_prefill) shapes instead of one per
        # suffix length — compiles are 20-40 s each on TPU.
        pages_needed = max(1, (len(chunk) + page_size - 1) // page_size)
        bucket = 1
        while bucket < pages_needed:
            bucket *= 2
        seq = bucket * page_size
        tokens = np.zeros((1, seq), np.int32)
        tokens[0, : len(chunk)] = chunk
        if self._sp > 1 and seq % self._sp == 0:
            # Sequence-parallel prefill: place the chunk sharded on seq
            # in ONE host→device transfer; XLA splits the per-token
            # compute sp-ways (see __init__).
            from jax.sharding import NamedSharding, PartitionSpec as P

            tokens_dev = jax.device_put(
                tokens, NamedSharding(self.mesh, P(None, "sp")))
        else:
            tokens_dev = jnp.asarray(tokens)

        if self.hybrid:
            # SWA pages arrive just-in-time for this chunk's blocks and
            # out-of-window slots return to the pool after it, so a
            # long prompt's peak SWA demand is window + chunk.
            self._swa_ensure(req, (pos + len(chunk) - 1) // page_size)
            swa_table = jnp.asarray(self._swa_table_for(req))[None, :]
            (logits, self.k_cache, self.v_cache,
             self.k_swa, self.v_swa) = forward_hybrid(
                self.params, self.cfg.model,
                tokens_dev,
                self.k_cache, self.v_cache, self.k_swa, self.v_swa,
                table, swa_table,
                jnp.asarray([pos], jnp.int32),
                jnp.asarray([len(chunk)], jnp.int32),
                last_only=True,
            )
            req.computed_len = pos + len(chunk)  # _swa_reclaim reads it
            self._swa_reclaim(req)
        else:
            logits, self.k_cache, self.v_cache = self._prefill_forward(
                self.params, self.cfg.model,
                tokens_dev,
                self.k_cache, self.v_cache,
                table,
                jnp.asarray([pos], jnp.int32),
                jnp.asarray([len(chunk)], jnp.int32),
                last_only=True,
            )
        req.computed_len = pos + len(chunk)
        if self.telemetry is not None:
            # Padding-waste accounting: len(chunk) real tokens rode a
            # seq-token padded dispatch (the power-of-two page bucket).
            self.telemetry.on_dispatch_tokens(len(chunk), seq)
        if pos + len(chunk) >= len(req.prompt):
            # last_only: logits row 0 is the chunk's final valid position.
            req.last_logits = np.asarray(logits[0, 0])
            req.prefill_pos = None
        else:
            req.prefill_pos = pos + len(chunk)

    def _commit_full_blocks(self, req: Request,
                            upto: Optional[int] = None) -> None:
        """Register newly computed full prompt blocks in the prefix cache.

        ``upto`` (prefill-role chunk commits) caps the commit at that many
        leading blocks: each prefill chunk's full blocks enter the prefix
        cache and the write-through store as they are computed instead of
        at prefill end, so a decode peer can start pulling chunk 1 while
        chunk 2 is still on the device.
        """
        page_size = self.cfg.model.page_size
        n_full = len(req.prompt) // page_size
        if upto is not None:
            n_full = min(n_full, upto)
        # committed_blocks, not cached_len: incremental chunk commits
        # advance it past the admission prefix (they never touch
        # cached_len — prefill_pos still walks the raw prompt).
        first_new = max(req.committed_blocks, req.cached_len // page_size)
        if n_full <= first_new:
            return
        new_hashes = req.block_hashes[first_new:n_full]
        new_pages = req.pages[first_new:n_full]
        tokens_per_block = [
            req.prompt[i * page_size:(i + 1) * page_size]
            for i in range(first_new, n_full)
        ]
        parent = (
            req.block_hashes[first_new - 1] if first_new > 0 else EMPTY_BLOCK_HASH
        )
        canonical = self.block_manager.commit_blocks(
            new_hashes, new_pages, tokens_per_block, parent
        )
        # Adopt canonical pages (duplicates swapped to the resident copy).
        req.pages[first_new:n_full] = canonical
        req.committed_blocks = max(req.committed_blocks, n_full)
        if self.hybrid:
            # Commit only slots still holding pages: blocks that already
            # fell out of the window were reclaimed mid-prefill and are
            # gone from group 1 by design.
            swa_first = max(first_new, req.swa_acquired_from)
            if swa_first < n_full:
                swa_parent = (
                    req.block_hashes[swa_first - 1] if swa_first > 0
                    else EMPTY_BLOCK_HASH
                )
                swa_canonical = self.swa_manager.commit_blocks(
                    req.block_hashes[swa_first:n_full],
                    req.swa_pages[swa_first:n_full],
                    [req.prompt[i * page_size:(i + 1) * page_size]
                     for i in range(swa_first, n_full)],
                    swa_parent,
                )
                req.swa_pages[swa_first:n_full] = swa_canonical

        # Write-through to the storage tier (async; writes may be shed under
        # pressure, degrading to future cache misses).
        if self.offload_handlers is not None:
            self._sync_caches_to_copier()
            to_store = self.offload_manager.prepare_store(new_hashes)
            if to_store:
                page_of = dict(zip(new_hashes, canonical))
                job = self.offload_handlers.async_store_blocks(
                    [(h, [page_of[h]]) for h in to_store]
                )
                self._pending_store_jobs[job] = list(to_store)
                if self.handoff is not None and self.cfg.role == "prefill":
                    # One handoff chunk per store job: the coordinator
                    # hears landed/failed from the drain that settles it.
                    self._handoff_store_jobs[job] = (
                        req.request_id, list(to_store))
                    self.handoff.on_chunk_start(req.request_id, to_store)
            if self.hybrid and swa_first < n_full:
                # Group 1: only the in-window committed blocks exist; they
                # are exactly what a trailing-window restore needs.
                # Deliberately NOT registered in _pending_store_jobs: the
                # storage BlockStored advertisement is group-untagged and
                # must assert a RESTORABLE state, which for hybrid means
                # the group-0 chain — whose own store job publishes it.
                # A group-1 file without its group-0 chain (e.g. the
                # group-0 write shed) must not be advertised.
                swa_hashes = req.block_hashes[swa_first:n_full]
                to_store1 = self.offload_manager.prepare_store(
                    swa_hashes, group_idx=1)
                if to_store1:
                    spage_of = dict(
                        zip(swa_hashes, req.swa_pages[swa_first:n_full]))
                    self.offload_handlers.async_store_blocks(
                        [(h, [spage_of[h]]) for h in to_store1], group_idx=1,
                    )

    # -- decode --

    def step(self) -> dict[str, int]:
        """One scheduling step: advance at most one prefill chunk, then one
        decode step for every decoding request.

        Returns {request_id: newest_token}. Decode is batched into a single
        jit call with padding up to max_batch; when ``decode_burst > 1``
        each call may emit a power-of-two burst of tokens per request (all
        of a request's burst tokens land in ``req.output``; the returned
        dict carries the newest). ``enqueue``d requests prefill here,
        chunk-at-a-time — a long prompt delays running decodes by one
        chunk per step, never its whole prefill.
        """
        tel = self.telemetry
        step_t0 = time.monotonic() if tel is not None else 0.0
        self.poll_offload()
        emitted: dict[str, int] = {}
        # Continuous batching: one prefill chunk for the oldest admitted-
        # but-not-yet-decoding request (FIFO — finish one prefill before
        # starting the next so TTFTs don't all pay for each other).
        # Snapshot: _prefill_chunk → _finish_prefill → _finish mutates
        # self._running for 1-token requests.
        just_prefilled: Optional[str] = None
        # Start every pending deferred restore up front, not just the FIFO
        # head's: the loads are independent DMA jobs, so a younger request's
        # storage fetch overlaps the older request's restore+prefill instead
        # of paying for it serially (kvio tracks multiple outstanding jobs).
        for rid in list(self._running):
            req = self.requests[rid]
            if req.prefill_pos is not None and req.restore_pending:
                self._start_deferred_restore(req)
        prefill_req: Optional[Request] = None
        for rid in list(self._running):
            req = self.requests[rid]
            if req.prefill_pos is not None:
                if req.enqueued_at is not None:
                    # First scheduler pick: the wait is the burst-admission
                    # latency (plus queueing behind older prefills). A
                    # deferred storage restore may still follow — that wait
                    # is a storage cost (kv_offload_*), deliberately not
                    # part of this scheduling metric.
                    from ..metrics.collector import record_admission_delay

                    admission_delay = time.monotonic() - req.enqueued_at
                    record_admission_delay(admission_delay)
                    if self.shedder is not None:
                        # CoDel signal: sustained admission delay above
                        # the target trips brownout/shed at enqueue.
                        self.shedder.observe_delay(admission_delay)
                    req.enqueued_at = None
                    if tel is not None:
                        tel.on_first_schedule(rid)
                # Deferred storage restore (enqueue path): started above on
                # the request's first step, polled here across steps —
                # decodes keep running below while the load is in flight.
                if req.restore_job is not None:
                    if not self._poll_deferred_restore(req):
                        break
                # Handoff wait (decode role): hold this request's local
                # prefill while the prefill peer's transfer is live,
                # re-arming the restore probe as chunks land. Decodes
                # below keep running the whole time.
                if req.handoff_deadline is not None:
                    if not self._handoff_gate(req):
                        break
                prefill_req = req
                break
        if self._ragged:
            # Ragged scheduling: the prefill chunk and every active decode
            # row pack into one flat-axis dispatch (the prefill bootstrap
            # token still lands next step, exactly as on the padded path).
            emitted.update(self._ragged_step(prefill_req))
        else:
            if prefill_req is not None:
                req = prefill_req
                if req.traceparent is not None:
                    with tracer().span(
                        "llm_d.kv_cache.engine.prefill_chunk",
                        parent_traceparent=req.traceparent,
                        request_id=req.request_id,
                        prefill_pos=req.prefill_pos,
                        process=self.cfg.pod_identifier,
                    ):
                        self._prefill_chunk(req)
                else:
                    self._prefill_chunk(req)
                if (req.prefill_pos is not None and self.handoff is not None
                        and self.cfg.role == "prefill"):
                    # Prefill pod: commit this chunk's full blocks NOW so
                    # the transfer streams chunk-granular (the final
                    # chunk commits in _finish_prefill as usual).
                    self._commit_prefill_chunk(req)
                if req.prefill_pos is None:
                    self._finish_prefill(req)
                    if req.output:
                        emitted[req.request_id] = req.output[-1]
                        # Its decode starts next step: including it in this
                        # step's decode batch would overwrite the prefill
                        # bootstrap token just emitted (a streaming caller
                        # would lose one token).
                        just_prefilled = req.request_id
            active = [self.requests[rid] for rid in self._running
                      if not self.requests[rid].done
                      and self.requests[rid].prefill_pos is None
                      and rid != just_prefilled]
            for chunk_start in range(0, len(active), self.cfg.max_batch):
                chunk = active[chunk_start:chunk_start + self.cfg.max_batch]
                burst = self._burst
                if burst > 1:
                    emitted.update(self._decode_chunk_burst(chunk, burst))
                else:
                    emitted.update(self._decode_chunk(chunk))
        for rid in list(self._running):
            req = self.requests[rid]
            if req.done:
                self._finish(req)
        if tel is not None:
            tel.on_step(time.monotonic() - step_t0, bool(emitted),
                        self._telemetry_pools)
        return emitted

    def _drain_offload(self, target_job: Optional[int] = None):
        results = self._drain_offload_multi(
            {target_job} if target_job is not None else frozenset())
        return results.get(target_job)

    def _drain_offload_multi(self, targets) -> dict:
        """Single dispatcher for offload completions.

        Every finished job is routed here exactly once: store jobs publish
        their storage events (minus shed blocks); results of awaited jobs
        (ids in ``targets``) are returned — a multi-job await must pass
        ALL its ids in one set, or the drain that surfaces one job drops
        the others' results. Cache references are re-synced after the
        drain because load scatters donate-and-replace the pools.
        """
        from ..metrics.collector import (
            record_io_pool_placement,
            record_offload_result,
        )

        results: dict = {}
        # Placement gauges exist only for backends with a native I/O pool
        # (the object-store backend transfers through its client library).
        io_pool = getattr(self.offload_handlers, "io", None)
        if io_pool is not None:
            record_io_pool_placement(io_pool)
        self._sync_caches_to_copier()
        try:
            for res in self.offload_handlers.get_finished():
                record_offload_result(self._offload_medium, res)
                hashes = self._pending_store_jobs.pop(res.job_id, None)
                if hashes is not None:
                    if res.success:
                        shed = set(res.shed_hashes)
                        stored = [h for h in hashes if h not in shed]
                        if stored:
                            self.offload_manager.complete_store(stored)
                    else:
                        logger.warning("write-through store job %d failed", res.job_id)
                ho = self._handoff_store_jobs.pop(res.job_id, None)
                if ho is not None and self.handoff is not None:
                    # Prefill-role chunk commit settled: stream the chunk
                    # completion (or its failure) to the coordinator so the
                    # decode peer's next probe sees the landed blocks.
                    ho_rid, ho_hashes = ho
                    if res.success:
                        shed = set(res.shed_hashes)
                        landed = [h for h in ho_hashes if h not in shed]
                        if landed:
                            self.handoff.on_chunk_landed(
                                ho_rid, landed,
                                shed=[h for h in ho_hashes if h in shed])
                        else:
                            self.handoff.on_chunk_failed(ho_rid, ho_hashes)
                    else:
                        self.handoff.on_chunk_failed(ho_rid, ho_hashes)
                if res.corrupt_hashes and self.offload_manager is not None:
                    # Checksum-failed files are already quarantined by the
                    # worker; de-advertise the blocks so no index view keeps
                    # routing to the storage tier for them.
                    self.offload_manager.complete_load_failure(res.corrupt_hashes)
                if res.job_id in targets:
                    results[res.job_id] = res
                elif res.job_id in self._restore_job_ids:
                    self._restore_results[res.job_id] = res
        finally:
            self._sync_caches_from_copier()
        return results

    def poll_offload(self) -> None:
        """Reap finished offload jobs (called each step)."""
        if self.offload_handlers is None:
            return
        self._drain_offload()

    def flush_offload(self, timeout_s: float = 30.0) -> None:
        """Block until all pending store jobs complete (testing/shutdown)."""
        deadline = time.monotonic() + timeout_s
        while self._pending_store_jobs and time.monotonic() < deadline:
            self.poll_offload()
            time.sleep(0.005)

    def _finish(self, req: Request, outcome: str = "finished") -> None:
        if self.telemetry is not None:
            self.telemetry.on_finish(req.request_id, outcome)
        if req.handoff_deadline is not None:
            # Aborted while waiting on a transfer: settle the ledger so
            # the coordinator never holds a ghost entry.
            self._handoff_settle(req, "failed")
        if (self.handoff is not None and self.cfg.role == "prefill"
                and req.prefill_pos is not None):
            # Prefill-role death/abort mid-prefill: no more chunks will
            # ever commit — flip the transfer failed so the decode peer
            # stops waiting and re-prefills the remainder itself.
            self.handoff.fail(req.request_id, outcome)
        if req.restore_job is not None:
            # Abort with a deferred restore in flight: non-blocking cancel —
            # kvio marks the job cancelled (never scatters) and parks its
            # staging buffers, so recycling the pages is safe immediately.
            self.offload_handlers.wait_job(req.restore_job[0], timeout_s=0.0)
            self._restore_job_ids.discard(req.restore_job[0])
            self._restore_results.pop(req.restore_job[0], None)
            req.restore_job = None
        if req.request_id in self._running:
            self._running.remove(req.request_id)
        self._release(req)
        # Drop the bookkeeping entry: callers keep the Request object they
        # got from add_request; retaining every finished request would grow
        # host memory unboundedly on a serving pod.
        self.requests.pop(req.request_id, None)

    def _ragged_step(self, prefill_req: Optional[Request]) -> dict[str, int]:
        """One scheduling step on the ragged single-kernel path.

        Active decode rows still group into chunks of ``max_batch`` (the
        same per-dispatch activation bound as the padded path); the FIFO
        head's prefill chunk rides the first dispatch as one extra long
        row. A request that finishes prefill here was assembled BEFORE
        its bootstrap token existed, so it cannot also decode this step —
        the padded path's ``just_prefilled`` exclusion, structurally.
        """
        emitted: dict[str, int] = {}
        active = [self.requests[rid] for rid in self._running
                  if not self.requests[rid].done
                  and self.requests[rid].prefill_pos is None]
        b = self.cfg.max_batch
        chunks = [active[i:i + b] for i in range(0, len(active), b)]
        if not chunks:
            chunks = [[]]
        for ci, chunk in enumerate(chunks):
            p_req = prefill_req if ci == 0 else None
            if not chunk and p_req is None:
                continue
            emitted.update(self._ragged_dispatch(chunk, p_req))
        return emitted

    def _ragged_dispatch(self, decode_rows: list[Request],
                         prefill_req: Optional[Request]) -> dict[str, int]:
        """One mixed prefill+decode dispatch over the flat ragged axis.

        Decode rows are 1-token rows; the prefill chunk (when present) is
        the last, longer row. The flat token axis buckets to a power of
        two (min 8 — the ragged q tile) and the row axis to a power of
        two, so the jit cache stays O(log max_batch · log tokens); padding
        rows are empty (``row_starts[r] == row_starts[r+1]``) and never
        enter the kernel's row loop — the per-token waste the pool
        counters measure is the bucket tail, not ``max_batch`` dead rows.
        """
        page_size = self.cfg.model.page_size
        q_lens: list[int] = []
        ctxs: list[int] = []
        tables_list: list[np.ndarray] = []
        flat_tokens: list[int] = []
        for req in decode_rows:
            flat_tokens.append(
                req.output[-1] if req.output else req.prompt[-1])
            q_lens.append(1)
            ctxs.append(req.computed_len)
            tables_list.append(self._page_table_for(req))
        p_chunk: list[int] = []
        p_pos = 0
        if prefill_req is not None:
            chunk_cap = max(page_size, self.cfg.max_prefill_tokens
                            // page_size * page_size)
            p_pos = prefill_req.prefill_pos
            p_chunk = list(prefill_req.prompt[p_pos:p_pos + chunk_cap])
            flat_tokens.extend(p_chunk)
            q_lens.append(len(p_chunk))
            ctxs.append(p_pos)
            tables_list.append(self._page_table_for(prefill_req))

        rows = len(q_lens)
        t_real = len(flat_tokens)
        t_pad = 8
        while t_pad < t_real:
            t_pad *= 2
        rows_pad = 1
        while rows_pad < rows:
            rows_pad *= 2

        tokens = np.zeros((1, t_pad), np.int32)
        tokens[0, :t_real] = flat_tokens
        # Padding rows are empty: start == end == t_real, zero tables,
        # ctx 0 — the kernel's block metadata never reaches them.
        row_starts = np.full((rows_pad + 1,), t_real, np.int32)
        row_starts[:rows + 1] = np.concatenate(
            [[0], np.cumsum(q_lens)]).astype(np.int32)
        ctx = np.zeros((rows_pad,), np.int32)
        ctx[:rows] = ctxs
        tables = np.zeros((rows_pad, self.cfg.max_pages_per_seq), np.int32)
        for i, t in enumerate(tables_list):
            tables[i] = t

        span_cm = None
        if prefill_req is not None and prefill_req.traceparent is not None:
            span_cm = tracer().span(
                "llm_d.kv_cache.engine.prefill_chunk",
                parent_traceparent=prefill_req.traceparent,
                request_id=prefill_req.request_id,
                prefill_pos=p_pos,
                process=self.cfg.pod_identifier,
            )
        try:
            if span_cm is not None:
                span_cm.__enter__()
            logits, self.k_cache, self.v_cache = forward_ragged(
                self.params, self.cfg.model,
                jnp.asarray(tokens),
                self.k_cache, self.v_cache,
                jnp.asarray(tables),
                jnp.asarray(row_starts),
                jnp.asarray(ctx, jnp.int32),
                interpret=self._ragged_interpret,
            )
        finally:
            if span_cm is not None:
                span_cm.__exit__(None, None, None)

        tel = self.telemetry
        if tel is not None:
            tel.on_dispatch_tokens(t_real, t_pad)

        out: dict[str, int] = {}
        if decode_rows:
            next_tokens = np.asarray(
                jnp.argmax(logits[:len(decode_rows)], axis=-1))
            now = time.monotonic() if tel is not None else 0.0
            for i, req in enumerate(decode_rows):
                req.computed_len += 1
                tok = int(next_tokens[i])
                req.output.append(tok)
                out[req.request_id] = tok
                if tel is not None:
                    tel.on_decode_tokens(req.request_id, 1, now)
                if req.traceparent is not None:
                    with tracer().span(
                        "llm_d.kv_cache.engine.decode_step",
                        parent_traceparent=req.traceparent,
                        request_id=req.request_id,
                        tokens=1,
                        computed_len=req.computed_len,
                        process=self.cfg.pod_identifier,
                    ):
                        pass  # event-style span: marks the emission point
                if len(req.output) >= req.max_new_tokens:
                    req.done = True

        if prefill_req is not None:
            req = prefill_req
            req.computed_len = p_pos + len(p_chunk)
            if p_pos + len(p_chunk) >= len(req.prompt):
                # The prefill row's logit IS its final valid token's (the
                # ragged forward returns one row per ragged row).
                req.last_logits = np.asarray(logits[rows - 1])
                req.prefill_pos = None
                self._finish_prefill(req)
                if req.output:
                    out[req.request_id] = req.output[-1]
            else:
                req.prefill_pos = p_pos + len(p_chunk)
                if self.handoff is not None and self.cfg.role == "prefill":
                    self._commit_prefill_chunk(req)
        return out

    def _decode_batch_arrays(self, chunk: list[Request], rows: int = 0):
        """Padded per-row decode inputs shared by the single-step and burst
        paths: (last tokens, computed context, page tables). The last
        token may have come from sampling with its KV not yet computed —
        that is why positions derive from ``computed_len``, and both paths
        must keep doing so. ``rows`` overrides the ``max_batch`` padding
        target (the unpipelined-pp decode bucket)."""
        b = rows or self.cfg.max_batch
        last = np.zeros((b,), np.int32)
        ctx = np.zeros((b,), np.int32)
        tables = np.zeros((b, self.cfg.max_pages_per_seq), np.int32)
        for i, req in enumerate(chunk):
            last[i] = req.output[-1] if req.output else req.prompt[-1]
            ctx[i] = req.computed_len
            tables[i] = self._page_table_for(req)
        return last, ctx, tables


    def _decode_chunk_burst(self, chunk: list[Request], steps: int) -> dict[str, int]:
        """Fused multi-token decode: one dispatch emits up to ``steps``
        greedy tokens per row; each row decodes until its own remaining
        budget and freezes after.

        Hybrid models run the two-pool scan with freeze-and-reclaim SWA
        paging (VERDICT r2 #4): the SWA table is pre-extended through every
        page the burst will touch, frozen for the scan, and slots that
        slid out of the window are reclaimed once per burst on the host —
        so SWA families keep the burst's dispatch-amortization win at the
        cost of up to ``steps`` tokens of extra transient window pages."""
        page_size = self.cfg.model.page_size
        if self.hybrid and self._burst_degraded:
            return self._decode_chunk(chunk)
        last, ctx, tables = self._decode_batch_arrays(chunk)
        budgets = np.zeros((self.cfg.max_batch,), np.int32)
        swa_tables = (np.zeros((self.cfg.max_batch, self.cfg.max_pages_per_seq),
                               np.int32) if self.hybrid else None)
        for i, req in enumerate(chunk):
            budgets[i] = req.max_new_tokens - len(req.output)
            if self.hybrid:
                taken = min(steps, int(budgets[i]))
                # The burst writes KV at positions computed_len ..
                # computed_len+taken-1; every SWA slot it touches needs a
                # live page before the tables freeze. If the pool cannot
                # cover the whole batch's burst transient (pool sized to
                # the single-step bound), latch single-token decoding for
                # this engine instead of dying mid-decode: the transients
                # already taken for the chunk are handed back first, so
                # the single-step path's own page needs are met.
                try:
                    self._swa_ensure(
                        req,
                        (req.computed_len + max(taken, 1) - 1) // page_size)
                except RuntimeError:
                    self._release_burst_transients(chunk)
                    self._burst_degraded = True
                    logger.warning(
                        "SWA pool cannot cover a %d-token burst transient; "
                        "decoding single-token from now on (size "
                        "num_swa_pages for window + decode_burst to keep "
                        "bursts)", steps)
                    return self._decode_chunk(chunk)
                swa_tables[i] = self._swa_table_for(req)

        if self.hybrid:
            (toks, self.k_cache, self.v_cache,
             self.k_swa, self.v_swa) = self._decode_multi_hybrid(
                self.params, self.cfg.model,
                jnp.asarray(last),
                self.k_cache, self.v_cache, self.k_swa, self.v_swa,
                jnp.asarray(tables), jnp.asarray(swa_tables),
                jnp.asarray(ctx, jnp.int32),
                jnp.asarray(budgets), steps=steps,
            )
        else:
            toks, self.k_cache, self.v_cache = self._decode_multi(
                self.params, self.cfg.model,
                jnp.asarray(last), self.k_cache, self.v_cache,
                jnp.asarray(tables), jnp.asarray(ctx, jnp.int32),
                jnp.asarray(budgets), steps=steps,
            )
        toks_host = np.asarray(toks)
        out = {}
        tel = self.telemetry
        now = time.monotonic() if tel is not None else 0.0
        for i, req in enumerate(chunk):
            taken = min(steps, int(budgets[i]))
            burst = [int(t) for t in toks_host[i, :taken]]
            req.output.extend(burst)
            req.computed_len += taken
            out[req.request_id] = burst[-1]
            if tel is not None:
                tel.on_decode_tokens(req.request_id, taken, now)
            if req.traceparent is not None:
                with tracer().span(
                    "llm_d.kv_cache.engine.decode_step",
                    parent_traceparent=req.traceparent,
                    request_id=req.request_id,
                    tokens=taken,
                    computed_len=req.computed_len,
                    process=self.cfg.pod_identifier,
                ):
                    pass  # event-style span: marks the emission point
            if len(req.output) >= req.max_new_tokens:
                req.done = True
            if self.hybrid:
                self._swa_reclaim(req)
        return out

    def _decode_chunk(self, chunk: list[Request]) -> dict[str, int]:
        # Pad to max_batch so decode compiles exactly once regardless of the
        # active-request count; padded rows have new_lens=0 (all writes go
        # to the garbage page, logits ignored).
        b = self.cfg.max_batch
        if self._pp > 1 and self._pp_decode_mb == 1:
            # Unpipelined pp decode (max_batch % pp != 0 — warned at
            # construction): the M=1 schedule accepts ANY batch size, so
            # padding dead rows to max_batch only burns per-stage FLOPs.
            # Pad to the power-of-two bucket instead (O(log max_batch)
            # compiled shapes); the pipelined schedule keeps the fixed
            # max_batch shape its microbatch split requires.
            b = 1
            while b < len(chunk):
                b *= 2
            b = min(b, self.cfg.max_batch)
        last, ctx, tables = self._decode_batch_arrays(chunk, rows=b)
        tokens = last[:, None].copy()
        new_lens = np.zeros((b,), np.int32)
        swa_tables = np.zeros((b, self.cfg.max_pages_per_seq), np.int32)
        for i, req in enumerate(chunk):
            new_lens[i] = 1
            if self.hybrid:
                # The new token's KV writes at block computed_len//page —
                # make sure that SWA slot has a live page.
                self._swa_ensure(
                    req, req.computed_len // self.cfg.model.page_size)
                swa_tables[i] = self._swa_table_for(req)

        if self.hybrid:
            (logits, self.k_cache, self.v_cache,
             self.k_swa, self.v_swa) = forward_hybrid(
                self.params, self.cfg.model,
                jnp.asarray(tokens),
                self.k_cache, self.v_cache, self.k_swa, self.v_swa,
                jnp.asarray(tables), jnp.asarray(swa_tables),
                jnp.asarray(ctx, jnp.int32),
                jnp.asarray(new_lens),
            )
        else:
            logits, self.k_cache, self.v_cache = self._decode_forward(
                self.params, self.cfg.model,
                jnp.asarray(tokens), self.k_cache, self.v_cache,
                jnp.asarray(tables),
                jnp.asarray(ctx, jnp.int32),
                jnp.asarray(new_lens),
            )
        out = {}
        next_tokens = np.asarray(jnp.argmax(logits[:, 0], axis=-1))
        tel = self.telemetry
        if tel is not None:
            # Padding-waste accounting for the padded path: len(chunk)
            # real tokens ride a b-row dispatch. The same counters feed
            # from the ragged path, so the waste ratio directly compares
            # the two schedulers.
            tel.on_dispatch_tokens(len(chunk), b)
        now = time.monotonic() if tel is not None else 0.0
        for i, req in enumerate(chunk):
            req.computed_len += 1
            tok = int(next_tokens[i])
            req.output.append(tok)
            out[req.request_id] = tok
            if tel is not None:
                tel.on_decode_tokens(req.request_id, 1, now)
            if req.traceparent is not None:
                with tracer().span(
                    "llm_d.kv_cache.engine.decode_step",
                    parent_traceparent=req.traceparent,
                    request_id=req.request_id,
                    tokens=1,
                    computed_len=req.computed_len,
                    process=self.cfg.pod_identifier,
                ):
                    pass  # event-style span: marks the emission point
            if len(req.output) >= req.max_new_tokens:
                req.done = True
            if self.hybrid:
                self._swa_reclaim(req)
        return out

    def _release(self, req: Request) -> None:
        page_size = self.cfg.model.page_size
        n_hashed = min(len(req.prompt) // page_size, len(req.block_hashes))
        # Only blocks up to the committed watermark are registered in the
        # block manager; an aborted mid-prefill request's later pages are
        # private and must be freed directly (releasing their hashes would
        # no-op on the unknown keys and leak the pages).
        n_comm = min(req.committed_blocks, n_hashed)
        committed_pages = set(req.pages[:n_comm])
        orphans = [p for p in req.pages[n_comm:] if p not in committed_pages]
        self.block_manager.release(req.block_hashes[:n_comm], orphans)
        if self.hybrid:
            # SWA group: this request references blocks from
            # swa_acquired_from onward (earlier slots were reclaimed as
            # the window slid, their refs already dropped). Committed
            # blocks stay cached — a committed SWA block i always serves
            # a resume at boundary i+1, so none is resume-worthless (see
            # _swa_reclaim); LRU pressure eviction reclaims space and
            # emits BlockRemoved.
            start = req.swa_acquired_from
            swa_committed_pages = set(req.swa_pages[:n_comm])
            swa_orphans = [p for p in req.swa_pages[n_comm:]
                           if p and p not in swa_committed_pages]
            self.swa_manager.release(
                req.block_hashes[start:n_comm], swa_orphans)

    # -- lifecycle --

    def abort_request(self, request_id: str) -> bool:
        """Preempt a running request: release its pages and references.

        The offload analogue of the reference's wait_job cancellation path
        (request aborted mid-transfer): pending write-through stores for
        its blocks are harmless (content-addressed, idempotent) and are
        left to complete; an in-flight deferred restore is cancelled-and-
        waited in ``_finish`` before its pages are released.
        Returns False for unknown/finished requests.
        """
        req = self.requests.get(request_id)
        if req is None or req.done:
            return False
        req.done = True
        self._finish(req, outcome="aborted")
        return True

    def reset_cache(self) -> None:
        """Drop all KV state (e.g. after a weight update).

        In-flight requests are aborted and *released* first so their
        unhashed pages (partial tail + decode room) return to the pool —
        ``clear()`` only frees pages registered in the block map.
        """
        for rid in list(self._running):
            req = self.requests[rid]
            req.done = True
            self._finish(req, outcome="aborted")
        self.block_manager.clear()
        if self.hybrid:
            self.swa_manager.clear(emit=False)

    def generate(self, request_id: str, prompt: Sequence[int],
                 max_new_tokens: int = 16) -> list[int]:
        """Convenience: admit one request and run it to completion."""
        req = self.add_request(request_id, prompt, max_new_tokens)
        while not req.done:
            self.step()
        return req.output
