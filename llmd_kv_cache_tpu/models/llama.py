"""Llama-family transformer with a paged KV cache, TPU-first.

Pure-functional JAX: parameters are a pytree, the forward step is a single
jit with static shapes (padded token blocks + masks, no data-dependent
Python control flow), bfloat16 activations/weights with float32 softmax and
norms. RoPE, RMSNorm, SwiGLU, grouped-query attention.

One ``forward`` serves prefill and decode: queries at logical positions
``ctx_lens + i`` attend to everything already in the paged cache plus
themselves. The cache update (scatter) happens inside the jit so the whole
token step is one XLA program; donate the caches for in-place updates.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from ..ops.kv_pages import scatter_kv_pages, scatter_kv_pages_ragged
from ..ops.paged_attention import paged_attention

Params = dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 512
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: int = 4
    head_dim: int = 64
    intermediate_size: int = 1408
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    page_size: int = 16
    dtype: Any = jnp.bfloat16
    # Hybrid attention: layers listed in ``swa_layers`` use a sliding
    # window of ``sliding_window`` keys (Mistral/Gemma-style); the rest are
    # full attention. Both unset → pure full attention.
    sliding_window: Any = None  # Optional[int]
    swa_layers: tuple = ()
    # Per-head RMSNorm on Q and K before RoPE (Qwen3-style QK-norm).
    # With GQA this makes the family cover Qwen3; False = plain Llama.
    qk_norm: bool = False
    # Mixture-of-experts MLP (Mixtral-style): 0 → dense. Experts shard over
    # the ``ep`` mesh axis.
    num_experts: int = 0
    num_experts_per_token: int = 2
    # Expert compute: "capacity" = GShard-style top-k dispatch into fixed
    # per-expert buffers of ceil(T·k/E · capacity_factor) tokens — compute
    # scales with tokens, not num_experts; overflow tokens lose their MoE
    # contribution (residual passes through). "dense" = every expert over
    # every token with a one-hot mix (exact, O(E) compute; useful as the
    # reference formulation and for tiny models).
    moe_dispatch: str = "capacity"
    moe_capacity_factor: float = 2.0
    # DeepSeek-style MoE extensions (all () /0 for the classic Mixtral
    # family): ``moe_layers`` lists the MoE layer indices (empty = every
    # layer when num_experts > 0 — dense-first_k layouts list the rest);
    # ``n_shared_experts``/``moe_intermediate_size`` size the always-on
    # shared expert and the routed experts' inner dim; ``moe_router`` =
    # ("deepseek_v3", n_group, topk_group, norm_topk_prob, 
    # routed_scaling_factor) selects the sigmoid scoring +
    # bias-corrected group-limited top-k router (weights from unbiased
    # sigmoid scores; the e_score_correction bias is a parameter,
    # ``router_bias``).
    moe_layers: tuple = ()
    n_shared_experts: int = 0
    moe_intermediate_size: int = 0
    moe_router: tuple = ()
    # Multi-head latent attention (DeepSeek-V2/V3): KV is cached as one
    # per-token latent of ``kv_lora_rank`` dims plus a decoupled-RoPE key
    # of ``qk_rope_head_dim`` dims SHARED across heads — ~an order of
    # magnitude less KV memory/bandwidth than GQA, which is the TPU-first
    # reason to run MLA in its absorbed form (see _forward_impl_grouped):
    # attention becomes multi-query over the latent itself (kv_heads=1,
    # head_dim=rank+rope), so the paged cache, offload, and event
    # machinery apply unchanged with the latent as the block payload.
    # 0 → standard attention. Events tag blocks ``mla_attention``
    # (reference events.go:34 KVCacheSpecKindMlaAttention).
    kv_lora_rank: int = 0
    qk_rope_head_dim: int = 0
    # Zero-padding appended to the MLA latent cache payload so its width
    # (rank + rope + pad) hits the Mosaic 128-lane alignment the Pallas
    # kernels need on real TPU — DeepSeek-V2 shapes set 64 (512+64+64=640).
    # The pad is part of the cache layout everywhere (pool, offload files,
    # fingerprints), so padded and unpadded engines never share a store;
    # attention math is invariant to it up to fp rounding of the scale
    # factor (zero key dims score zero, value reads slice [:rank]).
    latent_pad: int = 0
    # How MLA flash-decode feeds the shared latent to its two matmuls:
    # "copy" (default) DMAs each page once and mirrors it VMEM->VMEM so
    # score and output matmuls get independent buffers; "reuse" aliases
    # them (half the VMEM, but measured 2x slower at b8/ctx4k on v5e —
    # benchmarking/r5-tpu --mla probe). Pallas decode path only.
    mla_decode_stream: str = "copy"
    # Fused-projection column layout (serving-time, set by the engine —
    # not a checkpoint property; save canonicalizes it back to 1). 1 =
    # canonical [q|k|v] / [gate|up] column order. t > 1 = per-rank
    # interleaved order [q_0|k_0|v_0 | q_1|k_1|v_1 | ...] where part_i
    # is rank i's contiguous column slice: a uniform tp split of the
    # fused axis then hands every shard exactly its own fused block, so
    # fused projections compose with Megatron column sharding (the
    # canonical order cannot — uniform chunks straddle the q/k/v
    # boundaries). The forward's split sites consult this.
    fused_interleave: int = 1
    # RoPE scaling: () = plain RoPE; ("llama3", factor, low_freq_factor,
    # high_freq_factor, original_max_position_embeddings) — Llama-3.1's
    # frequency-band NTK scheme; or ("yarn", factor, beta_fast, beta_slow,
    # original_max, attention_factor) — NTK-by-parts with cos/sin scaling
    # (see _rope). Tuples so the frozen config stays hashable for jit
    # static args.
    rope_scaling: tuple = ()
    # DeepSeek yarn couples mscale into the ATTENTION SCALE (in-tree
    # transformers: scaling = qk_head_dim^-0.5 * mscale(factor,
    # mscale_all_dim)^2) on top of the generic cos/sin factor; this
    # multiplier carries that term. 1.0 everywhere else.
    softmax_scale_mult: float = 1.0
    # Attention sinks (StreamingLLM): with a sliding window, the first
    # ``attention_sinks`` positions stay attendable past the window — the
    # reference's ``sink_full_attention`` spec kind (events.go:40).
    # Supported for uniform-SWA models (every layer in swa_layers); the
    # hybrid two-pool reclamation would free sink blocks.
    attention_sinks: int = 0

    def __post_init__(self):
        if self.num_experts > 0 and self.num_experts_per_token > self.num_experts:
            raise ValueError(
                f"num_experts_per_token ({self.num_experts_per_token}) exceeds "
                f"num_experts ({self.num_experts})"
            )
        if self.kv_lora_rank > 0:
            if self.qk_rope_head_dim <= 0 or self.qk_rope_head_dim % 2:
                raise ValueError(
                    "MLA needs an even qk_rope_head_dim > 0 (decoupled-RoPE "
                    f"key dims), got {self.qk_rope_head_dim}")
            if self.sliding_window is not None or self.swa_layers:
                raise ValueError(
                    "sliding_window_mla is not implemented: MLA configs "
                    "cannot set sliding_window/swa_layers")
            if self.qk_norm:
                raise ValueError("qk_norm is not defined for MLA configs")
        if self.moe_router:
            kind = self.moe_router[0]
            if kind == "deepseek_v3" and len(self.moe_router) == 5:
                if self.moe_dispatch != "dense":
                    raise ValueError(
                        "the deepseek_v3 router is implemented for the "
                        "exact 'dense' dispatch only")
                n_group = self.moe_router[1]
                if n_group < 1 or self.num_experts % n_group != 0:
                    raise ValueError(
                        "num_experts must divide by n_group >= 1")
                if self.num_experts // n_group < 2:
                    raise ValueError(
                        "deepseek_v3 group scoring sums each group's "
                        "top-2 corrected scores: groups need >= 2 experts")
            elif kind == "softmax_topk" and len(self.moe_router) == 2:
                pass  # Qwen3-MoE: classic router, norm_topk_prob in [1]
            else:
                raise ValueError(
                    "moe_router must be ('deepseek_v3', n_group, "
                    "topk_group, norm_topk_prob, factor) or "
                    f"('softmax_topk', norm_topk_prob); got "
                    f"{self.moe_router!r}")
        if self.moe_layers and not all(
                0 <= i < self.num_layers for i in self.moe_layers):
            raise ValueError("moe_layers indices out of range")
        if self.rope_scaling:
            ok = (self.rope_scaling[0] == "llama3"
                  and len(self.rope_scaling) == 5) or (
                 self.rope_scaling[0] == "yarn"
                 and len(self.rope_scaling) == 6)
            if not ok:
                raise ValueError(
                    "rope_scaling must be ('llama3', factor, low_freq_factor,"
                    " high_freq_factor, original_max) or ('yarn', factor, "
                    "beta_fast, beta_slow, original_max, attention_factor); "
                    f"got {self.rope_scaling!r}")
        if self.softmax_scale_mult != 1.0 and not self.is_mla:
            raise ValueError(
                "softmax_scale_mult is a DeepSeek-yarn (MLA) knob")
        if self.mla_decode_stream not in ("copy", "reuse"):
            raise ValueError(
                "mla_decode_stream must be 'copy' or 'reuse', got "
                f"{self.mla_decode_stream!r}")
        if self.mla_decode_stream != "copy" and not self.is_mla:
            raise ValueError("mla_decode_stream is an MLA knob")
        if self.latent_pad:
            if not self.is_mla:
                raise ValueError("latent_pad only applies to MLA configs")
            if self.latent_pad < 0:
                raise ValueError("latent_pad must be >= 0")
        if self.fused_interleave < 1:
            raise ValueError("fused_interleave must be >= 1")
        if self.fused_interleave > 1 and self.is_mla:
            # The MLA fused block mixes head-sharded (wq/w_dq) and
            # replicated (w_dkv/w_kr) columns — no uniform interleave
            # makes that shardable; MLA serves unfused under tp.
            raise ValueError(
                "fused_interleave > 1 is not supported for MLA configs")
        if self.attention_sinks:
            if self.sliding_window is None:
                raise ValueError("attention_sinks requires sliding_window")
            if self.is_hybrid:
                raise ValueError(
                    "attention sinks need a uniform-SWA model "
                    "(sink_full_attention); hybrid layouts would reclaim "
                    "sink blocks from the window-bounded SWA pool")

    def layer_window(self, layer_idx: int):
        if self.sliding_window is not None and layer_idx in self.swa_layers:
            return self.sliding_window
        return None

    @property
    def is_hybrid(self) -> bool:
        """Mixed full-attention and SWA layers → two KV-cache groups with
        separate page pools (vLLM's hybrid memory allocator model,
        reference ``hma.go:32-66``)."""
        if self.sliding_window is None or not self.swa_layers:
            return False
        swa = set(self.swa_layers) & set(range(self.num_layers))
        return bool(swa) and swa != set(range(self.num_layers))

    def group_layers(self, group_idx: int) -> tuple:
        """Layer indices of a cache group: group 0 = full attention,
        group 1 = sliding window (hybrid models only)."""
        swa = set(self.swa_layers) if self.sliding_window is not None else set()
        if group_idx == 0:
            return tuple(li for li in range(self.num_layers) if li not in swa)
        return tuple(li for li in range(self.num_layers) if li in swa)

    def layer_group(self, layer_idx: int) -> int:
        return 1 if (self.is_hybrid and layer_idx in self.swa_layers) else 0

    @property
    def is_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def kv_cache_heads(self) -> int:
        """Head count of the paged cache layout (MLA: the latent is one
        shared 'head' — multi-query over the compressed KV)."""
        return 1 if self.is_mla else self.num_kv_heads

    @property
    def kv_cache_head_dim(self) -> int:
        """Per-token width of the paged cache payload (MLA: latent rank +
        decoupled-RoPE key + alignment pad; offload specs must use this,
        not head_dim)."""
        if self.is_mla:
            return self.kv_lora_rank + self.qk_rope_head_dim + self.latent_pad
        return self.head_dim

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """Test-sized config (fast CPU compile)."""
        return cls(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128, page_size=4,
        )

    @classmethod
    def qwen3_tiny(cls) -> "LlamaConfig":
        """Test-sized Qwen3-family config (GQA + QK-norm — the
        architecture of the reference's headline benchmark model,
        ``benchmarking/73-capacity`` Qwen3-32B)."""
        return cls(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128, page_size=4,
            qk_norm=True,
        )

    @classmethod
    def gemma_tiny(cls) -> "LlamaConfig":
        """Test-sized Gemma-2/3-style hybrid config: sliding-window and
        full-attention layers interleaved 1:1 — the layout that drives
        the two-group HMA path (separate window-bounded SWA page pool,
        group-tagged events; reference ``hma.go:32-66`` consumer side)."""
        return cls(
            vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128, page_size=4,
            sliding_window=8, swa_layers=(0, 2),
        )

    @classmethod
    def sink_tiny(cls) -> "LlamaConfig":
        """Test-sized StreamingLLM-style config: every layer SWA with
        attention sinks — the ``sink_full_attention`` spec kind."""
        return cls(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128, page_size=4,
            sliding_window=8, swa_layers=(0, 1), attention_sinks=4,
        )

    @classmethod
    def deepseek_tiny(cls) -> "LlamaConfig":
        """Test-sized DeepSeek-family config (MLA: latent KV cache with
        decoupled-RoPE keys, served in absorbed form). Cache payload is
        16+8=24 dims/token vs GQA-tiny's 2×2×16=64 — the memory ratio is
        the point of the family."""
        return cls(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=4, head_dim=16, intermediate_size=128, page_size=4,
            kv_lora_rank=16, qk_rope_head_dim=8,
        )

    @classmethod
    def mixtral_tiny(cls) -> "LlamaConfig":
        """Test-sized Mixtral-style MoE config (top-2 of 4 experts,
        GShard capacity dispatch)."""
        return cls(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, head_dim=16, intermediate_size=128, page_size=4,
            num_experts=4, num_experts_per_token=2,
        )


def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialize parameters (truncated-normal projections, ones norms).

    Jitted per config: the eager form dispatches one device op per weight
    (~8 per layer), which on a remote-tunneled TPU turns engine startup
    into minutes; one compiled program collapses it to a single dispatch.
    """
    return _init_params_jit(key, cfg)


@partial(jax.jit, static_argnames=("cfg",))
def _init_params_jit(key: jax.Array, cfg: LlamaConfig) -> Params:
    n_keys = 2 + cfg.num_layers
    keys = jax.random.split(key, n_keys)
    dt = cfg.dtype
    h, hd = cfg.hidden_size, cfg.head_dim

    def dense(k, shape, scale=0.02):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) * scale).astype(dt)

    layers = []
    for i in range(cfg.num_layers):
        lk = jax.random.split(keys[2 + i], 10)
        layer = {
            "attn_norm": jnp.ones((h,), jnp.float32),
            "wo": dense(lk[3], (cfg.num_heads * hd, h)),
            "mlp_norm": jnp.ones((h,), jnp.float32),
        }
        if cfg.is_mla:
            r, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
            layer.update({
                # q carries nope (head_dim) + decoupled-rope dims per head;
                # KV is down-projected to the shared latent, with per-head
                # up-projections absorbed into the attention at serve time.
                "wq": dense(lk[0], (h, cfg.num_heads * (hd + dr))),
                "w_dkv": dense(lk[1], (h, r)),
                "w_kr": dense(lk[2], (h, dr)),
                "w_uk": dense(lk[8], (cfg.num_heads, r, hd)),
                "w_uv": dense(lk[9], (cfg.num_heads, r, hd)),
            })
        else:
            layer.update({
                "wq": dense(lk[0], (h, cfg.num_heads * hd)),
                "wk": dense(lk[1], (h, cfg.num_kv_heads * hd)),
                "wv": dense(lk[2], (h, cfg.num_kv_heads * hd)),
            })
        if cfg.qk_norm:
            layer["q_norm"] = jnp.ones((hd,), jnp.float32)
            layer["k_norm"] = jnp.ones((hd,), jnp.float32)
        is_moe_layer = cfg.num_experts > 0 and (
            not cfg.moe_layers or i in cfg.moe_layers)
        if is_moe_layer:
            e = cfg.num_experts
            inter = cfg.moe_intermediate_size or cfg.intermediate_size
            layer.update({
                "router": dense(lk[7], (h, e)),
                "w_gate": dense(lk[4], (e, h, inter)),
                "w_up": dense(lk[5], (e, h, inter)),
                "w_down": dense(lk[6], (e, inter, h)),
            })
            if cfg.moe_router and cfg.moe_router[0] == "deepseek_v3":
                # deepseek_v3: bias + shared expert
                sh = inter * max(cfg.n_shared_experts, 1)
                skeys = jax.random.split(lk[7], 4)
                layer.update({
                    "router_bias": jnp.zeros((e,), jnp.float32),
                    "w_gate_sh": dense(skeys[1], (h, sh)),
                    "w_up_sh": dense(skeys[2], (h, sh)),
                    "w_down_sh": dense(skeys[3], (sh, h)),
                })
        else:
            layer.update({
                "w_gate": dense(lk[4], (h, cfg.intermediate_size)),
                "w_up": dense(lk[5], (h, cfg.intermediate_size)),
                "w_down": dense(lk[6], (cfg.intermediate_size, h)),
            })
        layers.append(layer)

    return {
        "embed": dense(keys[0], (cfg.vocab_size, h), scale=0.02),
        "layers": layers,
        "final_norm": jnp.ones((h,), jnp.float32),
        "lm_head": dense(keys[1], (h, cfg.vocab_size)),
    }


def _interleave_concat(parts: list, t: int, axis: int = 1) -> jax.Array:
    """Concatenate projection blocks in per-rank interleaved column order.

    t == 1 reproduces the canonical order. For t > 1 every part's fused
    axis must divide by t (the engine only requests an interleave the tp
    validation already guarantees); rank i's slice of each part lands
    contiguously, so a uniform t-way split of the result gives rank i
    exactly ``[part0_i | part1_i | ...]`` — its local fused block."""
    if t == 1:
        return jnp.concatenate(parts, axis=axis)
    for p in parts:
        if p.shape[axis] % t:
            raise ValueError(
                f"fused_interleave={t} does not divide projection width "
                f"{p.shape[axis]}")
    chunks = []
    for i in range(t):
        for p in parts:
            n = p.shape[axis] // t
            chunks.append(
                jax.lax.slice_in_dim(p, i * n, (i + 1) * n, axis=axis))
    return jnp.concatenate(chunks, axis=axis)


def _deinterleave_split(w: jax.Array, widths: tuple, t: int,
                        axis: int = 1) -> list:
    """Inverse of :func:`_interleave_concat`: recover the canonical
    per-projection blocks from a (possibly interleaved) fused array."""
    if t == 1:
        outs, off = [], 0
        for n in widths:
            outs.append(jax.lax.slice_in_dim(w, off, off + n, axis=axis))
            off += n
        return outs
    blk = sum(widths) // t
    ranks = [jax.lax.slice_in_dim(w, i * blk, (i + 1) * blk, axis=axis)
             for i in range(t)]
    outs = []
    off = 0
    for n in widths:
        outs.append(jnp.concatenate(
            [jax.lax.slice_in_dim(r, off, off + n // t, axis=axis)
             for r in ranks], axis=axis))
        off += n // t
    return outs


def split_fused_out(y: jax.Array, widths: tuple, t: int) -> list:
    """Split a fused projection's OUTPUT activations back into the
    per-projection tensors, honoring the interleaved layout.

    For t == 1 these are the canonical static slices. For t > 1 the
    last dim is reshaped ``[t, blk]`` (a shard-boundary split under the
    Megatron column sharding, so GSPMD keeps it local), each part's
    per-rank columns sliced, and the rank axis merged back — rank-major
    order IS canonical order, since rank i's slice was the i-th
    contiguous chunk of the canonical projection."""
    if t == 1:
        outs, off = [], 0
        for n in widths:
            outs.append(y[..., off:off + n])
            off += n
        return outs
    blk = sum(widths) // t
    yb = y.reshape(*y.shape[:-1], t, blk)
    outs, off = [], 0
    for n in widths:
        part = yb[..., off:off + n // t]
        outs.append(part.reshape(*y.shape[:-1], n))
        off += n // t
    return outs


def fuse_params(params: Params, cfg: LlamaConfig) -> Params:
    """Fuse per-layer projections that share an input into wider matmuls.

    Serving-time transform (applied once at engine startup): one
    [h, Nq+Nk+Nv] product reads the activations once and replaces three
    back-to-back [h, N] products. Measured on a real v5e
    (benchmarking/r5-tpu/tpu_validation.log), the trade is
    shape-dependent: at hidden 4096 (3.1B model) the fused 4k prefill is
    ~7% faster (210 ms / 64.0% MFU vs 227 ms / 59.4%), while at hidden
    2048 (the 0.9B bench model) it is ~8% SLOWER (112 ms vs 103 ms) —
    XLA already overlaps the narrow products there and the fused wide-N
    output only adds slice boundaries. ``fuse_profitable`` encodes the
    measured crossover; the engine's auto default consults it.

    - ``wq/wk/wv`` (+ ``bq/bk/bv``) → ``w_qkv`` (+ ``b_qkv``)
    - MLA: ``wq|w_dq`` + ``w_dkv`` + ``w_kr`` → ``w_mla_in``
      (all consume post-norm attn input; q-LoRA keeps its separate
      ``wq`` over the normed q latent)
    - dense SwiGLU: ``w_gate/w_up`` → ``w_gate_up``
    - DeepSeek shared experts: ``w_gate_sh/w_up_sh`` → ``w_gate_up_sh``

    Originals are dropped (no weight memory doubling). The forward
    accepts both layouts. TP-sharded serving fuses in the per-rank
    INTERLEAVED column order (``cfg.fused_interleave`` = tp, set by the
    engine): the canonical column order cannot shard uniformly across
    tp (chunks would straddle the q/k/v and gate/up boundaries), but
    interleaving each rank's slices makes the uniform Megatron column
    split hand every shard exactly its local fused block. MLA keeps the
    canonical order only (``fused_interleave > 1`` is refused by the
    config: its fused block mixes head-sharded and replicated columns).
    """
    t = cfg.fused_interleave
    out = dict(params)
    fused_layers = []
    fused_any = False
    for layer in params["layers"]:
        lyr = dict(layer)
        if "wk" in lyr:  # standard / GQA attention
            lyr["w_qkv"] = _interleave_concat(
                [lyr.pop("wq"), lyr.pop("wk"), lyr.pop("wv")], t)
            if "bq" in lyr:
                lyr["b_qkv"] = _interleave_concat(
                    [lyr.pop("bq"), lyr.pop("bk"), lyr.pop("bv")], t,
                    axis=0)
            fused_any = True
        elif "w_dkv" in lyr:  # absorbed MLA (canonical order; t == 1)
            head_in = (lyr.pop("w_dq") if "w_dq" in lyr
                       else lyr.pop("wq"))
            lyr["w_mla_in"] = jnp.concatenate(
                [head_in, lyr.pop("w_dkv"), lyr.pop("w_kr")], axis=1)
            fused_any = True
        if "w_gate" in lyr and lyr["w_gate"].ndim == 2:  # dense SwiGLU
            lyr["w_gate_up"] = _interleave_concat(
                [lyr.pop("w_gate"), lyr.pop("w_up")], t)
            fused_any = True
        if "w_gate_sh" in lyr:
            lyr["w_gate_up_sh"] = _interleave_concat(
                [lyr.pop("w_gate_sh"), lyr.pop("w_up_sh")], t)
            fused_any = True
        fused_layers.append(lyr)
    out["layers"] = fused_layers
    if fused_any:
        # Record the interleave the tree was ACTUALLY fused with, so
        # unfuse_params can refuse a mismatched config instead of silently
        # de-interleaving into scrambled wq/wk/wv. A no-op call on an
        # already-fused tree keeps the original marker.
        out["fused_interleave"] = t
    return out


def maybe_fuse_params(params: Params, cfg: LlamaConfig) -> Params:
    """``fuse_params`` iff ``fuse_profitable(cfg)`` — the one place the
    profit gate composes with the transform, shared by the engine's
    auto default and the bench's shared-tree path."""
    return fuse_params(params, cfg) if fuse_profitable(cfg) else params


def fuse_profitable(cfg: LlamaConfig, tp: int = 1) -> bool:
    """Whether ``fuse_params`` is expected to help this model on TPU.

    The measured crossover (real v5e, 4k flash prefill,
    benchmarking/r5-tpu/tpu_validation.log): hidden 4096 gains ~7%
    (59.4% → 64.0% MFU), hidden 2048 loses ~8% (38.4% → 35.5%). The
    boundary sits somewhere in (2048, 4096]; models below it keep the
    unfused layout so narrow-hidden serving never regresses. Engines
    with ``fuse_projections=None`` and the bench's shared-tree path both
    consult this.

    ``tp`` scales the gate to PER-SHARD widths: under Megatron column
    sharding each rank multiplies into 1/tp of the fused output columns,
    so a hidden-4096 model at tp=2 runs the same narrow per-core products
    the hidden-2048 measurement showed REGRESSING. The profit boundary
    therefore applies to ``hidden_size / tp``, not the full-model width.
    """
    return cfg.hidden_size // max(1, tp) >= 4096


def unfuse_params(params: Params, cfg: LlamaConfig) -> Params:
    """Inverse of :func:`fuse_params`: split fused projections back into
    the canonical per-projection layout. Checkpoints always store the
    canonical layout (portable across fused/unfused engines, TP sharding,
    and the trainer); a fused serving tree is unfused on save. No-op on
    an already-canonical tree.

    The interleave is read from the ``fused_interleave`` marker that
    :func:`fuse_params` stamped on the tree. A fused tree without the
    marker, or one whose marker disagrees with ``cfg.fused_interleave``,
    raises: de-interleaving with the wrong ``t`` would silently scramble
    ``wq/wk/wv`` column order (a checkpoint saved from such a tree is
    corrupt with no error anywhere downstream)."""
    out = dict(params)
    marker = out.pop("fused_interleave", None)
    fused_keys = ("w_qkv", "b_qkv", "w_mla_in", "w_gate_up", "w_gate_up_sh")
    if not any(k in lyr for lyr in params["layers"] for k in fused_keys):
        return out  # already canonical
    if marker is None:
        raise ValueError(
            "cannot unfuse: tree has fused projections but no "
            "fused_interleave marker (was it fused by fuse_params?)")
    t = int(marker)
    if t != cfg.fused_interleave:
        raise ValueError(
            f"fused_interleave mismatch: tree was fused with t={t} but "
            f"cfg.fused_interleave={cfg.fused_interleave}; unfusing with "
            "the wrong interleave would scramble the q/k/v column order")
    layers = []
    for layer in params["layers"]:
        lyr = dict(layer)
        if "w_qkv" in lyr:
            nq = cfg.num_heads * cfg.head_dim
            nk = cfg.num_kv_heads * cfg.head_dim
            w = lyr.pop("w_qkv")
            nv = w.shape[1] - nq - nk
            lyr["wq"], lyr["wk"], lyr["wv"] = _deinterleave_split(
                w, (nq, nk, nv), t)
            if "b_qkv" in lyr:
                b = lyr.pop("b_qkv")
                lyr["bq"], lyr["bk"], lyr["bv"] = _deinterleave_split(
                    b, (nq, nk, nv), t, axis=0)
        if "w_mla_in" in lyr:  # canonical order only (t == 1)
            r, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
            w = lyr.pop("w_mla_in")
            qc = w.shape[1] - r - dr
            head_key = "w_dq" if "q_latent_norm" in lyr else "wq"
            lyr[head_key] = w[:, :qc]
            lyr["w_dkv"] = w[:, qc:qc + r]
            lyr["w_kr"] = w[:, qc + r:]
        if "w_gate_up" in lyr:
            w = lyr.pop("w_gate_up")
            inter = w.shape[1] // 2
            lyr["w_gate"], lyr["w_up"] = _deinterleave_split(
                w, (inter, inter), t)
        if "w_gate_up_sh" in lyr:
            w = lyr.pop("w_gate_up_sh")
            sh = w.shape[1] // 2
            lyr["w_gate_sh"], lyr["w_up_sh"] = _deinterleave_split(
                w, (sh, sh), t)
        layers.append(lyr)
    out["layers"] = layers
    return out


def init_kv_cache(cfg: LlamaConfig, num_pages: int,
                  dtype=None) -> tuple[jax.Array, jax.Array]:
    """Allocate the paged K and V pools: ``[layers, pages, kvh, page, hd]``.

    MLA: the K pool holds the per-token latent (+rope key) as one shared
    head; the V pool is width-0 — attention reads values from the same
    latent, so a separate V cache would double the memory MLA exists to
    save. The zero-width array keeps every donation/offload seam shaped.

    ``dtype`` overrides the pool element type (serving-time choice —
    ``float8_e4m3fn`` halves KV HBM traffic and capacity; e4m3's
    per-element exponent needs no scale arrays, so the cache layout and
    every scatter/gather/offload seam are unchanged). The compute path
    stays bf16: ``scatter_kv_pages`` casts on write, the attention
    backends upcast on read.
    """
    dtype = cfg.dtype if dtype is None else dtype
    shape = (cfg.num_layers, num_pages, cfg.kv_cache_heads, cfg.page_size,
             cfg.kv_cache_head_dim)
    v_width = 0 if cfg.is_mla else cfg.kv_cache_head_dim
    return jnp.zeros(shape, dtype), jnp.zeros(shape[:-1] + (v_width,), dtype)


def init_kv_cache_hybrid(
    cfg: LlamaConfig, num_pages: int, num_swa_pages: int, dtype=None
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Allocate separate page pools for a hybrid model's two cache groups:
    ``(k0, v0, k1, v1)`` with group 0 = full-attention layers (num_pages)
    and group 1 = SWA layers (num_swa_pages — window-bounded, so typically
    much smaller; this is the memory win of hybrid attention).
    ``dtype`` as in ``init_kv_cache``."""
    if not cfg.is_hybrid:
        raise ValueError("init_kv_cache_hybrid needs a hybrid config")
    dtype = cfg.dtype if dtype is None else dtype

    def shape(group, pages):
        return (len(cfg.group_layers(group)), pages, cfg.num_kv_heads,
                cfg.page_size, cfg.head_dim)

    return (
        jnp.zeros(shape(0, num_pages), dtype),
        jnp.zeros(shape(0, num_pages), dtype),
        jnp.zeros(shape(1, num_swa_pages), dtype),
        jnp.zeros(shape(1, num_swa_pages), dtype),
    )


def _rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * weight).astype(x.dtype)


def _moe_router(mlp_in: jax.Array, layer: dict, cfg: "LlamaConfig",
                aux_out: Any):
    """Shared routing: top-k expert choices + softmaxed weights, and the
    Switch-style load-balancing term ``E·Σ_e f_e·P_e`` appended to
    ``aux_out`` (training; None skips it)."""
    e = cfg.num_experts
    k = cfg.num_experts_per_token
    router_logits = (
        mlp_in @ layer["router"].astype(mlp_in.dtype)
    ).astype(jnp.float32)  # [b,s,E]
    top_w, top_idx = jax.lax.top_k(router_logits, k)  # [b,s,k]
    if (cfg.moe_router and cfg.moe_router[0] == "softmax_topk"
            and not cfg.moe_router[1]):
        # Qwen3-MoE with norm_topk_prob=False: weights are the top-k
        # entries of the FULL softmax, NOT renormalized (HF
        # Qwen3MoeSparseMoeBlock — "only diff with mixtral").
        weights = jnp.take_along_axis(
            jax.nn.softmax(router_logits, axis=-1), top_idx, axis=-1)
    else:
        weights = jax.nn.softmax(top_w, axis=-1)
    onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # [b,s,k,E]
    if aux_out is not None:
        probs = jax.nn.softmax(router_logits, axis=-1)  # [b,s,E]
        f = jnp.mean(jnp.sum(onehot, axis=2) / k, axis=(0, 1))  # [E]
        p = jnp.mean(probs, axis=(0, 1))  # [E]
        aux_out.append(e * jnp.sum(f * p))
    return top_idx, weights, onehot


def _moe_dense(mlp_in, layer, cfg, aux_out):
    """Reference formulation: every expert over every token, one-hot mix.
    Exact but O(num_experts) compute."""
    _top_idx, weights, onehot = _moe_router(mlp_in, layer, cfg, aux_out)
    # bf16 matmuls, f32 activation math (mirrors the dense branch).
    gate = jax.nn.silu(jnp.einsum(
        "bsh,ehi->bsei", mlp_in, layer["w_gate"]
    ).astype(jnp.float32))
    up = jnp.einsum("bsh,ehi->bsei", mlp_in, layer["w_up"]).astype(jnp.float32)
    expert_out = jnp.einsum(
        "bsei,eih->bseh", (gate * up).astype(mlp_in.dtype), layer["w_down"]
    ).astype(jnp.float32)
    mix = jnp.einsum("bsk,bske,bseh->bsh", weights, onehot, expert_out)
    return mix.astype(mlp_in.dtype)


def _moe_capacity(mlp_in, layer, cfg, aux_out, valid=None):
    """GShard/Switch-style capacity dispatch: tokens scatter into fixed
    per-expert buffers of C = ceil(T·k/E · capacity_factor) slots via
    one-hot einsums (static shapes, XLA/MXU-friendly), experts run on
    [E, C, H], results combine back weighted. Compute scales with
    T·k·capacity_factor — independent of num_experts — at the cost of
    dropping assignments past an expert's capacity (earlier tokens win;
    dropped assignments contribute nothing, the residual passes through).
    Experts (and their buffers) shard over the ``ep`` mesh axis.

    ``valid`` ([b, s] bool, optional): padded positions are excluded from
    routing so they can never consume capacity slots that real tokens
    need (attention masks them, the router would not).
    """
    batch, seq, hidden = mlp_in.shape
    e = cfg.num_experts
    k = cfg.num_experts_per_token
    t = batch * seq
    top_idx, weights, _onehot = _moe_router(mlp_in, layer, cfg, aux_out)

    capacity = max(1, math.ceil(t * k * cfg.moe_capacity_factor / e))
    x = mlp_in.reshape(t, hidden)
    # Assignment axis a = (token, choice), token-major: earlier tokens win
    # capacity slots.
    oh = jax.nn.one_hot(top_idx.reshape(t * k), e, dtype=jnp.int32)  # [A,E]
    if valid is not None:
        mask = valid.reshape(t).astype(jnp.int32)
        oh = oh * jnp.repeat(mask, k)[:, None]
    pos_a = jnp.sum((jnp.cumsum(oh, axis=0) - oh) * oh, axis=1)      # [A]
    # one_hot zeroes out-of-range rows, so over-capacity assignments (and
    # masked tokens, whose oh row is zero) drop out of the dispatch.
    pos_oh = jax.nn.one_hot(pos_a, capacity, dtype=mlp_in.dtype)     # [A,C]
    oh_tk = oh.astype(mlp_in.dtype).reshape(t, k, e)
    pos_tk = pos_oh.reshape(t, k, capacity)
    # A token's k assignments land in distinct (expert, slot) cells, so
    # summing the choice axis gives a lossless [T,E,C] dispatch — no
    # k-times-repeated activations.
    disp = jnp.einsum("tke,tkc->tec", oh_tk, pos_tk)                 # [T,E,C]

    buf = jnp.einsum("tec,th->ech", disp, x)                         # [E,C,H]
    gate = jax.nn.silu(
        jnp.einsum("ech,ehi->eci", buf, layer["w_gate"]).astype(jnp.float32))
    up = jnp.einsum("ech,ehi->eci", buf, layer["w_up"]).astype(jnp.float32)
    expert_out = jnp.einsum(
        "eci,eih->ech", (gate * up).astype(mlp_in.dtype), layer["w_down"]
    ).astype(jnp.float32)
    combine = jnp.einsum(
        "tke,tkc,tk->tec", oh_tk.astype(jnp.float32),
        pos_tk.astype(jnp.float32), weights.reshape(t, k))
    y = jnp.einsum("tec,ech->th", combine, expert_out)               # [T,H]
    return y.reshape(batch, seq, hidden).astype(mlp_in.dtype)


def _moe_deepseek(mlp_in, layer, cfg):
    """DeepSeek-V3 MoE, exact dense form (DeepseekV3TopkRouter +
    DeepseekV3MoE semantics): sigmoid scores; top-k SELECTION uses
    bias-corrected scores restricted to the best ``topk_group`` of
    ``n_group`` expert groups (group score = sum of its top-2 corrected
    scores); mix WEIGHTS are the unbiased sigmoid scores of the chosen
    experts, optionally renormalized, times the routed scaling factor;
    a shared expert always adds in."""
    _kind, n_group, topk_group, norm_flag, factor = cfg.moe_router
    b, s, h = mlp_in.shape
    e = layer["w_gate"].shape[0]
    k = cfg.num_experts_per_token
    x = mlp_in.reshape(b * s, h)

    logits = x.astype(jnp.float32) @ layer["router"].astype(jnp.float32)
    scores = jax.nn.sigmoid(logits)  # [T, E]
    choice = scores + layer["router_bias"][None, :].astype(jnp.float32)
    group_scores = jax.lax.top_k(
        choice.reshape(-1, n_group, e // n_group), 2)[0].sum(-1)
    _, gidx = jax.lax.top_k(group_scores, topk_group)  # [T, topk_group]
    gmask = jnp.sum(jax.nn.one_hot(gidx, n_group), axis=1)  # [T, n_group]
    smask = jnp.repeat(gmask, e // n_group, axis=-1)  # [T, E]
    masked = jnp.where(smask > 0, choice, 0.0)
    _, idx = jax.lax.top_k(masked, k)  # [T, k]
    w = jnp.take_along_axis(scores, idx, axis=-1)
    if norm_flag:
        w = w / (jnp.sum(w, axis=-1, keepdims=True) + 1e-20)
    w = w * factor
    mix_w = jnp.einsum(
        "tk,tke->te", w, jax.nn.one_hot(idx, e, dtype=jnp.float32))

    gate = jax.nn.silu(jnp.einsum(
        "th,ehi->tei", x, layer["w_gate"]).astype(jnp.float32))
    up = jnp.einsum("th,ehi->tei", x, layer["w_up"]).astype(jnp.float32)
    expert_out = jnp.einsum(
        "tei,eih->teh", (gate * up).astype(x.dtype), layer["w_down"]
    ).astype(jnp.float32)
    out = jnp.einsum("te,teh->th", mix_w, expert_out).astype(mlp_in.dtype)

    if "w_gate_up_sh" in layer:  # fused serving layout (fuse_params)
        sh_gu = (x @ layer["w_gate_up_sh"]).astype(jnp.float32)
        sh_i = sh_gu.shape[-1] // 2
        sh_gate_out, sh_up = split_fused_out(sh_gu, (sh_i, sh_i),
                                             cfg.fused_interleave)
        sh_gate = jax.nn.silu(sh_gate_out)
    else:
        sh_gate = jax.nn.silu((x @ layer["w_gate_sh"]).astype(jnp.float32))
        sh_up = (x @ layer["w_up_sh"]).astype(jnp.float32)
    shared = (sh_gate * sh_up).astype(x.dtype) @ layer["w_down_sh"]
    return (out + shared).reshape(b, s, h)


def _mlp(mlp_in: jax.Array, layer: dict, cfg: "LlamaConfig",
         aux_out: Any = None, valid: Any = None) -> jax.Array:
    """MLP block: dense SwiGLU or top-k MoE (capacity dispatch by default,
    dense reference formulation via ``cfg.moe_dispatch="dense"``; the
    deepseek_v3 router when ``cfg.moe_router`` selects it).

    Dispatch is keyed on the LAYER's parameters (``router`` present →
    MoE), so dense-first_k DeepSeek layouts mix layer kinds in one model.
    Expert matmuls stay in the model dtype (bf16 MXU path, like the dense
    branch); only router/softmax/mix math runs in f32. ``valid`` ([b, s]
    bool) excludes padded positions from capacity routing.
    """
    if "router" in layer:
        if cfg.moe_router and cfg.moe_router[0] == "deepseek_v3":
            return _moe_deepseek(mlp_in, layer, cfg)
        if cfg.moe_dispatch == "capacity":
            return _moe_capacity(mlp_in, layer, cfg, aux_out, valid=valid)
        if cfg.moe_dispatch == "dense":
            return _moe_dense(mlp_in, layer, cfg, aux_out)
        raise ValueError(f"unknown moe_dispatch: {cfg.moe_dispatch!r}")

    if "w_gate_up" in layer:  # fused serving layout (fuse_params)
        gu = (mlp_in @ layer["w_gate_up"]).astype(jnp.float32)
        inter = gu.shape[-1] // 2
        gate_out, up = split_fused_out(gu, (inter, inter),
                                       cfg.fused_interleave)
        gate = jax.nn.silu(gate_out)
    else:
        gate = jax.nn.silu((mlp_in @ layer["w_gate"]).astype(jnp.float32))
        up = (mlp_in @ layer["w_up"]).astype(jnp.float32)
    return (gate * up).astype(mlp_in.dtype) @ layer["w_down"]


def _rope(x: jax.Array, positions: jax.Array, theta: float,
          scaling: tuple = ()) -> jax.Array:
    """Rotary position embedding. x: [b, s, heads, hd], positions: [b, s].

    ``scaling`` is ``LlamaConfig.rope_scaling``: ``()`` for plain RoPE,
    ``("llama3", factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings)`` — the Llama-3.1 frequency-band
    NTK scheme (long wavelengths divided by ``factor``, short kept,
    smooth ramp between) — or ``("yarn", factor, beta_fast, beta_slow,
    original_max, attention_factor)``; both match transformers'
    ``modeling_rope_utils`` formulas.
    """
    hd = x.shape[-1]
    half = hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    att = 1.0
    if scaling and scaling[0] == "llama3":
        _, factor, low_f, high_f, orig = scaling
        wavelen = 2.0 * math.pi / freqs
        low_wl = orig / low_f       # wavelengths above this: fully scaled
        high_wl = orig / high_f     # wavelengths below this: unscaled
        smooth = (orig / wavelen - low_f) / (high_f - low_f)
        mid = (1.0 - smooth) * freqs / factor + smooth * freqs
        freqs = jnp.where(wavelen > low_wl, freqs / factor,
                          jnp.where(wavelen < high_wl, freqs, mid))
    elif scaling:
        # yarn (NTK-by-parts, paper 2309.00071; matches transformers'
        # _compute_yarn_parameters with truncate=True): dims below the
        # beta_fast correction bound extrapolate (unscaled), above the
        # beta_slow bound interpolate (freq/factor), linear ramp between;
        # cos/sin are scaled by the pre-resolved attention factor.
        _, factor, beta_fast, beta_slow, orig, att = scaling

        def corr_dim(n_rot):  # full-dim index for a rotation count
            return (hd * math.log(orig / (n_rot * 2.0 * math.pi))
                    ) / (2.0 * math.log(theta))

        low = max(math.floor(corr_dim(beta_fast)), 0)
        high = min(math.ceil(corr_dim(beta_slow)), hd - 1)
        ramp = jnp.clip(
            (jnp.arange(half, dtype=jnp.float32) - low)
            / max(high - low, 0.001), 0.0, 1.0)
        extrap = 1.0 - ramp
        freqs = (freqs / factor) * (1.0 - extrap) + freqs * extrap
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, half]
    cos = jnp.cos(angles)[:, :, None, :] * att
    sin = jnp.sin(angles)[:, :, None, :] * att
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def _forward_impl_grouped(params, cfg, tokens, k_caches, v_caches, tables,
                          ctx_lens, new_lens, attention_fn, last_only=False,
                          tails=None, ragged=None):
    """Shared transformer body over grouped KV pools.

    ``k_caches[g]`` holds group g's layers stacked in ``cfg.group_layers(g)``
    order with its own page pool; ``tables[g]`` is that pool's page table.
    The non-hybrid case is the 1-tuple degenerate form. ``attention_fn(q,
    k_l, v_l, page_table, positions, total_lens, window) -> [b, seq, heads,
    hd]`` picks the backend.

    ``last_only=True`` computes logits only for each sequence's final valid
    token (``new_lens - 1``) — the prefill-chunk case, where the full
    [seq, vocab] lm_head matmul and its fp32 materialization are pure waste
    (a 2048-token chunk of the bench model otherwise burns 0.27 TFLOP and a
    262 MB HBM write per chunk on logits nobody reads).

    ``tails=(tail_ks, tail_vs, ctx_base)`` is the fused-decode-burst mode
    (seq == 1): the paged caches are READ-ONLY (XLA copies large scan
    carries every iteration, so the burst scan must not carry them) and
    the current token's K/V is written into the burst-local tail buffers
    ``tail_ks[g]`` [layers_g, batch, steps, kvh, width] at slot
    ``ctx_lens - ctx_base`` instead; attention folds the tail after the
    paged keys (ops-level ``tail_k/tail_v/tail_lens``). Returns
    ``(logits, tail_ks, tail_vs)`` in place of the caches; the caller
    scatters the accumulated tail into the caches once, outside the scan.

    ``ragged=row_starts`` ([rows+1] flat-token prefix sums) is the ragged
    mixed-batch mode: ``tokens`` is one flat axis [1, total_q] where row r
    owns slots ``[row_starts[r], row_starts[r+1])`` at logical positions
    ``ctx_lens[r] + i`` — ``ctx_lens``/``new_lens`` are per-ROW [rows],
    ``tables[g]`` is [rows, pages_per_seq], and the attention backend must
    understand the ragged layout (``pallas_paged_ragged_attention``).
    ``last_only=True`` then returns one logit row per ragged row (each
    row's final token) — logits [1, rows, vocab].
    """
    batch, seq = tokens.shape
    if ragged is not None:
        if tails is not None:
            raise ValueError("ragged mode is scatter-then-attend; "
                             "burst tails are not supported")
        if batch != 1:
            raise ValueError(
                f"ragged mode takes one flat token axis [1, total_q], "
                f"got batch={batch}")
        rows = ctx_lens.shape[0]
        flat = jnp.arange(seq)
        row_of = jnp.clip(
            jnp.searchsorted(ragged, flat, side="right") - 1, 0, rows - 1)
        positions = (ctx_lens[row_of] + flat - ragged[row_of])[None, :]
        valid = (flat < ragged[-1])[None, :]

        def _scatter(cache, new_kv, table):
            return scatter_kv_pages_ragged(
                cache, new_kv[0], table, row_of, positions[0], valid[0])
    else:
        positions = ctx_lens[:, None] + jnp.arange(seq)[None, :]  # [b, s]
        valid = jnp.arange(seq)[None, :] < new_lens[:, None]

        def _scatter(cache, new_kv, table):
            return scatter_kv_pages(cache, new_kv, table, positions, valid)
    total_lens = ctx_lens + new_lens
    if tails is not None:
        # The burst path is single-token-per-tick: tmask broadcasts
        # valid [b, 1] over [b, T] and tail_lens counts exactly one new
        # token per live row. A seq>1 caller would mis-mask silently.
        if seq != 1:
            raise ValueError(
                f"tails mode requires seq == 1 (decode bursts), got {seq}")
        tail_ks, tail_vs, ctx_base = tails
        tail_ks, tail_vs = list(tail_ks), list(tail_vs)
        t_steps = tail_ks[0].shape[2]
        slot = ctx_lens - ctx_base  # [b] tail tokens already written
        # One-hot write mask over tail slots (t_steps ≤ burst size, so a
        # where over [b, T, ...] beats any scatter): live rows write the
        # current token at slot; frozen rows write nothing.
        tmask = ((jnp.arange(t_steps)[None, :] == slot[:, None])
                 & valid)  # [b, T]
        tail_lens = slot + new_lens  # attendable tail keys incl. current

        def write_tail(buf, new_kv):
            # buf [b, T, kvh, w]; new_kv [b, 1, kvh, w] broadcasts over T.
            # Explicit cast: a quantized (fp8) cache makes the tail buffer
            # fp8 too, and 8-bit floats refuse implicit promotion — the
            # cast is also the semantics (tail tokens quantize exactly
            # like their eventual scatter into the cache).
            return jnp.where(tmask[:, :, None, None],
                             new_kv.astype(buf.dtype), buf)

        def tail_kwargs(tk_l, tv_l):
            return dict(tail_k=tk_l, tail_v=tv_l, tail_lens=tail_lens,
                        ctx_base=ctx_base)

    # Static layer→(group, local index) map, resolved at trace time.
    local_idx = {}
    for g in range(len(k_caches)):
        for j, li in enumerate(cfg.group_layers(g)):
            local_idx[li] = (g, j)

    x = params["embed"][tokens]  # [b, s, h]

    k_caches = list(k_caches)
    v_caches = list(v_caches)
    for li, layer in enumerate(params["layers"]):
        g, lj = local_idx[li] if len(k_caches) > 1 else (0, li)
        table = tables[g]
        attn_in = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        if cfg.is_mla:
            # Absorbed MLA (DeepSeek-V2 §2.1.2, TPU-first formulation):
            # cache ONLY the latent [c_kv ; rope-key] per token and fold
            # the per-head up-projections into the query and output — the
            # attention core is then plain multi-query paged attention
            # with head_dim = rank+rope over the cache this file already
            # pages, and HBM traffic per token drops by ~num_heads·2.
            r, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
            if "w_mla_in" in layer:  # fused serving layout (fuse_params)
                fused = attn_in @ layer["w_mla_in"]
                qc = fused.shape[-1] - r - dr  # static split point
                head_in = fused[..., :qc]
                c_kv = fused[..., qc:qc + r]
                k_rope_in = fused[..., qc + r:]
                if "q_latent_norm" in layer:
                    # q-LoRA: the fused block holds w_dq's output; the
                    # norm between down- and up-projection stays.
                    q = _rms_norm(head_in, layer["q_latent_norm"],
                                  cfg.norm_eps) @ layer["wq"]
                else:
                    q = head_in
            else:
                if "w_dq" in layer:
                    # DeepSeek q-LoRA: q is down-projected to a compressed
                    # latent, RMS-normed, then up-projected per head — the
                    # norm between the two matmuls prevents precomposition.
                    q_in = _rms_norm(attn_in @ layer["w_dq"],
                                     layer["q_latent_norm"], cfg.norm_eps)
                else:
                    q_in = attn_in
                q = q_in @ layer["wq"]
                c_kv = attn_in @ layer["w_dkv"]  # [b, s, r]
                k_rope_in = attn_in @ layer["w_kr"]
            q = q.reshape(batch, seq, cfg.num_heads, cfg.head_dim + dr)
            q_nope, q_rope = q[..., :cfg.head_dim], q[..., cfg.head_dim:]
            q_rope = _rope(q_rope, positions, cfg.rope_theta,
                           cfg.rope_scaling)
            if "latent_norm" in layer:
                # DeepSeek kv_a_layernorm: the latent is RMS-normed before
                # the up-projections — cached post-norm, so absorption is
                # unchanged (w_uk applies to the normed latent).
                c_kv = _rms_norm(c_kv, layer["latent_norm"], cfg.norm_eps)
            k_rope = _rope(k_rope_in[:, :, None, :],
                           positions, cfg.rope_theta,
                           cfg.rope_scaling)  # [b, s, 1, dr]
            latent = jnp.concatenate(
                [c_kv[:, :, None, :], k_rope], axis=-1)  # [b, s, 1, r+dr]
            # Absorb W_UK: q·(latent@W_UK) == (q@W_UK^T)·latent.
            q_lat = jnp.einsum("bshd,hrd->bshr", q_nope, layer["w_uk"])
            q_eff = jnp.concatenate([q_lat, q_rope], axis=-1)
            if cfg.latent_pad:
                # 128-lane alignment pad (see LlamaConfig.latent_pad):
                # zero key dims score zero against any query, so the
                # attention output only sees the pad through fp rounding
                # of the two-step scale factor (~1 ulp).
                pad = [(0, 0)] * 3 + [(0, cfg.latent_pad)]
                latent = jnp.pad(latent, pad)
                q_eff = jnp.pad(q_eff, pad)
            # The attention backends scale by q.shape[-1]^-0.5 (the padded
            # cache width); MLA's logical scale is the per-head q/k width
            # (nope+rope), times the DeepSeek-yarn mscale^2 when set.
            q_eff = q_eff * (
                q_eff.shape[-1] ** 0.5 / (cfg.head_dim + dr) ** 0.5
                * cfg.softmax_scale_mult)

            if tails is not None:
                tail_ks[g] = tail_ks[g].at[lj].set(
                    write_tail(tail_ks[g][lj], latent))
                ctx = attention_fn(
                    q_eff, k_caches[g][lj], k_caches[g][lj], table,
                    positions, total_lens, None,
                    k_stack=k_caches[g], v_stack=k_caches[g], layer_idx=lj,
                    **tail_kwargs(tail_ks[g][lj], tail_ks[g][lj]),
                )
            else:
                k_caches[g] = k_caches[g].at[lj].set(
                    _scatter(k_caches[g][lj], latent, table)
                )
                # Values ARE the latent: pass the K pool as both K and V
                # (the width-0 V pool is never read), then un-absorb W_UV.
                ctx = attention_fn(
                    q_eff, k_caches[g][lj], k_caches[g][lj], table,
                    positions, total_lens, None,
                    k_stack=k_caches[g], v_stack=k_caches[g], layer_idx=lj,
                )
            attn = jnp.einsum("bshr,hrv->bshv", ctx[..., :r], layer["w_uv"])
        else:
            if "w_qkv" in layer:  # fused serving layout (fuse_params)
                qkv = attn_in @ layer["w_qkv"]
                if "b_qkv" in layer:
                    qkv = qkv + layer["b_qkv"]
                nq = cfg.num_heads * cfg.head_dim
                nk = cfg.num_kv_heads * cfg.head_dim
                nv = qkv.shape[-1] - nq - nk
                q, k, v = split_fused_out(qkv, (nq, nk, nv),
                                          cfg.fused_interleave)
            else:
                q = attn_in @ layer["wq"]
                k = attn_in @ layer["wk"]
                v = attn_in @ layer["wv"]
                if "bq" in layer:  # Qwen2-lineage QKV projection biases
                    q = q + layer["bq"]
                    k = k + layer["bk"]
                    v = v + layer["bv"]
            q = q.reshape(batch, seq, cfg.num_heads, cfg.head_dim)
            k = k.reshape(batch, seq, cfg.num_kv_heads, cfg.head_dim)
            v = v.reshape(batch, seq, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm:  # Qwen3: per-head RMS over head_dim, pre-RoPE
                q = _rms_norm(q, layer["q_norm"], cfg.norm_eps)
                k = _rms_norm(k, layer["k_norm"], cfg.norm_eps)
            q = _rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
            k = _rope(k, positions, cfg.rope_theta, cfg.rope_scaling)

            if tails is not None:
                tail_ks[g] = tail_ks[g].at[lj].set(
                    write_tail(tail_ks[g][lj], k))
                tail_vs[g] = tail_vs[g].at[lj].set(
                    write_tail(tail_vs[g][lj], v))
                attn = attention_fn(
                    q, k_caches[g][lj], v_caches[g][lj], table, positions,
                    total_lens, cfg.layer_window(li),
                    k_stack=k_caches[g], v_stack=v_caches[g], layer_idx=lj,
                    **tail_kwargs(tail_ks[g][lj], tail_vs[g][lj]),
                )
            else:
                k_caches[g] = k_caches[g].at[lj].set(
                    _scatter(k_caches[g][lj], k, table)
                )
                v_caches[g] = v_caches[g].at[lj].set(
                    _scatter(v_caches[g][lj], v, table)
                )

                attn = attention_fn(
                    q, k_caches[g][lj], v_caches[g][lj], table, positions,
                    total_lens, cfg.layer_window(li),
                    k_stack=k_caches[g], v_stack=v_caches[g], layer_idx=lj,
                )
        x = x + attn.reshape(batch, seq, -1) @ layer["wo"]

        mlp_in = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(mlp_in, layer, cfg, valid=valid)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    if last_only:
        if ragged is not None:
            # One logit row per ragged row: its final flat token
            # (row_starts[r+1] - 1; empty rows clamp to slot 0 and the
            # caller ignores them).
            idx = jnp.maximum(ragged[1:] - 1, 0)[None, :]  # [1, rows]
            x = jnp.take_along_axis(x, idx[:, :, None], axis=1)
        else:
            idx = jnp.maximum(new_lens - 1, 0)  # [b]
            x = jnp.take_along_axis(x, idx[:, None, None], axis=1)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    if tails is not None:
        return logits, tuple(tail_ks), tuple(tail_vs)
    return logits, tuple(k_caches), tuple(v_caches)


def _forward_impl(params, cfg, tokens, k_cache, v_cache, page_table,
                  ctx_lens, new_lens, attention_fn, last_only=False):
    logits, ks, vs = _forward_impl_grouped(
        params, cfg, tokens, (k_cache,), (v_cache,), (page_table,),
        ctx_lens, new_lens, attention_fn, last_only=last_only,
    )
    return logits, ks[0], vs[0]


@partial(jax.jit, static_argnames=("cfg", "last_only"),
         donate_argnames=("k_cache", "v_cache"))
def forward(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [batch, seq] int32 (padded)
    k_cache: jax.Array,  # [layers, pages, kvh, page_size, hd] (donated)
    v_cache: jax.Array,  # same (donated)
    page_table: jax.Array,  # [batch, pages_per_seq] int32
    ctx_lens: jax.Array,  # [batch] tokens already cached before this call
    new_lens: jax.Array,  # [batch] valid new tokens in `tokens`
    last_only: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One model step (prefill or decode), XLA attention backend.

    Returns ``(logits [b, seq, vocab], k_cache, v_cache)``. Query i of
    sequence b sits at logical position ``ctx_lens[b] + i``; padded
    positions (``i >= new_lens[b]``) are masked and scatter to the garbage
    page. ``last_only=True`` → logits is [b, 1, vocab], the final valid
    position of each row (prefill chunks; see ``_forward_impl_grouped``).
    """
    def xla_attention(q, k_l, v_l, table, positions, total_lens, window,
                      **_stack_kw):  # slices fuse into XLA's gather
        return paged_attention(
            q, k_l, v_l, table, positions, total_lens, sliding_window=window,
            attention_sinks=cfg.attention_sinks or None,
        )

    return _forward_impl(
        params, cfg, tokens, k_cache, v_cache, page_table, ctx_lens, new_lens,
        xla_attention, last_only=last_only,
    )


@partial(jax.jit, static_argnames=("cfg", "last_only"),
         donate_argnames=("k0", "v0", "k1", "v1"))
def forward_hybrid(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,   # [batch, seq] int32 (padded)
    k0: jax.Array,       # group 0 (full attention): [g0_layers, pages, kvh, p, hd]
    v0: jax.Array,
    k1: jax.Array,       # group 1 (SWA): [g1_layers, swa_pages, kvh, p, hd]
    v1: jax.Array,
    table0: jax.Array,   # [batch, pages_per_seq] into group 0's pool
    table1: jax.Array,   # [batch, pages_per_seq] into group 1's pool
    ctx_lens: jax.Array,
    new_lens: jax.Array,
    last_only: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """One model step for a hybrid (mixed full/SWA) model over two
    separately-paged cache groups. XLA attention backend."""
    def xla_attention(q, k_l, v_l, table, positions, total_lens, window,
                      **_stack_kw):  # slices fuse into XLA's gather
        return paged_attention(
            q, k_l, v_l, table, positions, total_lens, sliding_window=window,
            attention_sinks=cfg.attention_sinks or None,
        )

    logits, ks, vs = _forward_impl_grouped(
        params, cfg, tokens, (k0, k1), (v0, v1), (table0, table1),
        ctx_lens, new_lens, xla_attention, last_only=last_only,
    )
    return logits, ks[0], vs[0], ks[1], vs[1]


@partial(
    jax.jit,
    static_argnames=("cfg", "interpret", "mesh", "batch_rows"),
    donate_argnames=("k_cache", "v_cache"),
)
def forward_decode_pallas(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [batch, 1] int32
    k_cache: jax.Array,
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, pages_per_seq]
    ctx_lens: jax.Array,  # [batch]
    new_lens: jax.Array,  # [batch] 1 for live rows, 0 for padding
    interpret: bool = False,
    mesh=None,
    batch_rows: int = 1,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Decode step (seq == 1) using the Pallas flash-decode kernel.

    Same semantics as ``forward``; streaming pages HBM→VMEM in-kernel
    avoids materializing the gathered KV — the long-context win over the
    XLA reference path. ``mesh`` (tp axis) runs the kernel per-shard over
    the kv-heads sharding via ``shard_map``.
    """
    from ..ops.pallas_paged_attention import (
        pallas_paged_decode_attention, sharded_paged_decode_attention)

    sinks = cfg.attention_sinks or None

    def pallas_attention(q, k_l, v_l, table, _positions, total_lens, window,
                         k_stack=None, v_stack=None, layer_idx=None):
        # Prefer the stacked operand + in-kernel layer index: a sliced
        # cache materializes a per-layer copy at the pallas custom-call
        # boundary (see ops.pallas_paged_attention._superblock_streamer).
        if k_stack is not None:
            k_l, v_l = k_stack, v_stack
        if mesh is not None:
            out = sharded_paged_decode_attention(
                mesh, q[:, 0], k_l, v_l, table, total_lens,
                sliding_window=window, sinks=sinks, shared_kv=cfg.is_mla,
                shared_stream=cfg.mla_decode_stream,
                layer_idx=layer_idx, interpret=interpret,
            )
        else:
            out = pallas_paged_decode_attention(
                q[:, 0], k_l, v_l, table, total_lens,
                sliding_window=window, sinks=sinks, shared_kv=cfg.is_mla,
                shared_stream=cfg.mla_decode_stream,
                layer_idx=layer_idx, batch_rows=batch_rows,
                interpret=interpret,
            )
        return out[:, None]  # restore the seq axis

    return _forward_impl(
        params, cfg, tokens, k_cache, v_cache, page_table, ctx_lens, new_lens,
        pallas_attention,
    )


def _decode_step_attention(use_pallas: bool, interpret: bool, mesh,
                           sinks: int | None = None,
                           shared_kv: bool = False,
                           shared_stream: str = "copy",
                           batch_rows: int = 1):
    """Attention closure for fused decode bodies — one implementation for
    the single-pool and hybrid two-pool scans (the grouped forward hands
    each layer its own group's table and window, so the closure is
    pool-agnostic). ``sinks`` (StreamingLLM) applies in-kernel on the
    Pallas path and in-mask on the XLA path — same semantics, parity
    tested in tests/test_pallas_attention.py."""
    from ..ops.pallas_paged_attention import (
        pallas_paged_decode_attention, sharded_paged_decode_attention)

    def attention(q, k_l, v_l, table, positions, total_lens, window,
                  tail_k=None, tail_v=None, tail_lens=None, ctx_base=None,
                  k_stack=None, v_stack=None, layer_idx=None):
        # Burst-tail mode: the paged cache covers only ctx_base keys; the
        # tail holds the burst's tokens (see _forward_impl_grouped).
        base_lens = total_lens if ctx_base is None else ctx_base
        if use_pallas and k_stack is not None:
            # Stacked operand + in-kernel layer index: a sliced cache
            # materializes a per-layer copy at the pallas custom-call
            # boundary.
            k_l, v_l = k_stack, v_stack
        else:
            layer_idx = None
        if use_pallas and mesh is not None:
            out = sharded_paged_decode_attention(
                mesh, q[:, 0], k_l, v_l, table, base_lens,
                sliding_window=window, sinks=sinks, shared_kv=shared_kv,
                shared_stream=shared_stream,
                tail_k=tail_k, tail_v=tail_v, tail_lens=tail_lens,
                layer_idx=layer_idx, interpret=interpret,
            )
            return out[:, None]
        if use_pallas:
            out = pallas_paged_decode_attention(
                q[:, 0], k_l, v_l, table, base_lens,
                sliding_window=window, sinks=sinks, shared_kv=shared_kv,
                shared_stream=shared_stream,
                tail_k=tail_k, tail_v=tail_v, tail_lens=tail_lens,
                layer_idx=layer_idx, batch_rows=batch_rows,
                interpret=interpret,
            )
            return out[:, None]
        return paged_attention(
            q, k_l, v_l, table, positions, base_lens, sliding_window=window,
            attention_sinks=sinks, tail_k=tail_k, tail_v=tail_v,
            tail_lens=tail_lens,
        )

    return attention


@partial(
    jax.jit,
    static_argnames=("cfg", "steps", "use_pallas", "interpret", "mesh",
                     "batch_rows"),
    donate_argnames=("k_cache", "v_cache"),
)
def forward_decode_steps(
    params: Params,
    cfg: LlamaConfig,
    last_tokens: jax.Array,  # [batch] int32 — the most recent token per row
    k_cache: jax.Array,
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, pages_per_seq] int32
    ctx_lens: jax.Array,  # [batch] computed context before this call
    active: jax.Array,  # [batch] 1 for live rows, 0 for padding
    steps: int,
    use_pallas: bool = False,
    interpret: bool = False,
    mesh=None,
    batch_rows: int = 1,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Greedy decode of ``steps`` tokens fused into ONE XLA program.

    A ``lax.scan`` over the single-token decode body: each tick scatters
    the previous token's KV, attends, and argmaxes the next token —
    device-resident the whole way, so a burst costs one dispatch and one
    logits-free [batch, steps] token download instead of ``steps``
    round-trips. On a remote-tunneled TPU this is the difference between
    dispatch-bound and compute-bound decode; on-host it still removes
    per-token launch overhead and logits transfers.

    ``active`` is each row's remaining token budget, not a binary mask: a
    row decodes while the tick index is below its budget and freezes after
    (writes land in the garbage page, context stops advancing, the token
    output repeats its final value) — so one burst serves a mixed batch
    where requests finish at different ticks, and rows with ``active == 0``
    are inert padding throughout. Page tables must already cover
    ``ctx + min(active, steps)`` tokens (the engine preallocates through
    ``max_new_tokens`` at admission).
    Returns ``(tokens [batch, steps], k_cache, v_cache)``; row i's valid
    entries are the first ``min(active[i], steps)``.

    The scan does NOT carry the caches (XLA copies large while-loop
    carries every iteration — see ``_decode_steps_scan``); burst tokens
    accumulate in a small KV tail folded into attention per step and are
    scattered into the caches once, after the scan. The XLA backend's
    burst is bit-identical to single-stepping (same softmax structure);
    the Pallas backend's fp32 tail round sums in a different order than
    the in-page rounds, so greedy argmax can legitimately flip on
    logit ties within ~1 bf16 ulp (random-weight test models tie often;
    trained models rarely).
    """
    toks, ks, vs = _decode_steps_scan(
        params, cfg, last_tokens, (k_cache,), (v_cache,), (page_table,),
        ctx_lens, active, steps,
        _decode_step_attention(use_pallas, interpret, mesh,
                               sinks=cfg.attention_sinks or None,
                               shared_kv=cfg.is_mla,
                               shared_stream=cfg.mla_decode_stream,
                               batch_rows=batch_rows),
    )
    return toks, ks[0], vs[0]


def _decode_steps_scan(params, cfg, last_tokens, k_caches, v_caches, tables,
                       ctx_lens, active, steps, attention):
    """The fused-decode scan body over grouped KV pools — one
    implementation for the single-pool (1-tuple degenerate form, mirroring
    ``_forward_impl``) and hybrid two-pool variants, so the live/freeze and
    ctx-advance semantics cannot diverge between them.

    The paged caches are scan CONSTANTS, not carries: XLA copies large
    while-loop carries every iteration (measured ~300 GB/s r+w on a v5e —
    a 4.6 GB cache pair cost ~30 ms/step of pure copy at production pool
    sizes), so each tick attends over the frozen base cache plus a
    burst-local KV tail (≤steps tokens, the only carried KV state) and
    the accumulated tail is scattered into the caches ONCE after the
    scan, where jit-boundary donation keeps it in place.
    """
    batch = last_tokens.shape[0]
    tail_ks = tuple(
        jnp.zeros((kc.shape[0], batch, steps) + kc.shape[2:3] + kc.shape[4:],
                  kc.dtype)
        for kc in k_caches)
    tail_vs = tuple(
        jnp.zeros((vc.shape[0], batch, steps) + vc.shape[2:3] + vc.shape[4:],
                  vc.dtype)
        for vc in v_caches)

    def body(carry, tick):
        toks, tks, tvs, ctx = carry
        live = (tick < active).astype(jnp.int32)  # [batch]
        logits, tks, tvs = _forward_impl_grouped(
            params, cfg, toks[:, None], k_caches, v_caches, tables, ctx,
            live, attention, tails=(tks, tvs, ctx_lens),
        )
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        nxt = jnp.where(live > 0, nxt, toks)
        return (nxt, tks, tvs, ctx + live), nxt

    (_t, tail_ks, tail_vs, _c), toks = jax.lax.scan(
        body, (last_tokens, tail_ks, tail_vs, ctx_lens),
        jnp.arange(steps, dtype=jnp.int32),
    )

    # Fold the burst's tokens into the paged caches — one batched scatter
    # per (group, layer, K/V) at the program tail, in place on the
    # donated buffers.
    tpos = ctx_lens[:, None] + jnp.arange(steps)[None, :]  # [b, T]
    tvalid = jnp.arange(steps)[None, :] < jnp.minimum(active, steps)[:, None]
    k_caches = list(k_caches)
    v_caches = list(v_caches)
    for g in range(len(k_caches)):
        for lj in range(k_caches[g].shape[0]):
            k_caches[g] = k_caches[g].at[lj].set(scatter_kv_pages(
                k_caches[g][lj], tail_ks[g][lj], tables[g], tpos, tvalid))
            if v_caches[g].shape[-1]:  # MLA's width-0 V pool has no data
                v_caches[g] = v_caches[g].at[lj].set(scatter_kv_pages(
                    v_caches[g][lj], tail_vs[g][lj], tables[g], tpos,
                    tvalid))
    return toks.T, tuple(k_caches), tuple(v_caches)  # toks [batch, steps]


@partial(
    jax.jit,
    static_argnames=("cfg", "steps", "use_pallas", "interpret", "mesh",
                     "batch_rows"),
    donate_argnames=("k0", "v0", "k1", "v1"),
)
def forward_decode_steps_hybrid(
    params: Params,
    cfg: LlamaConfig,
    last_tokens: jax.Array,  # [batch] int32
    k0: jax.Array, v0: jax.Array,   # full-attention group pool
    k1: jax.Array, v1: jax.Array,   # sliding-window group pool
    table0: jax.Array,  # [batch, pages_per_seq] into group 0's pool
    table1: jax.Array,  # [batch, pages_per_seq] into group 1's pool
    ctx_lens: jax.Array,
    active: jax.Array,  # [batch] per-row remaining token budget
    steps: int,
    use_pallas: bool = False,
    interpret: bool = False,
    mesh=None,
    batch_rows: int = 1,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused multi-token decode over the hybrid two-pool layout.

    The freeze-and-reclaim half of the SWA burst design (VERDICT r2 #4):
    the engine pre-extends each request's SWA table through the pages the
    whole burst will touch, the scan runs ``steps`` device-resident ticks
    against the frozen tables (same per-row budget semantics as
    ``forward_decode_steps``), and the host reclaims slots that slid out
    of the window once per burst instead of once per token. SWA layers get
    their sliding-window mask and group-1 table from the grouped forward;
    the flash-decode kernel applies per layer, so ``use_pallas`` covers
    both pools (the kernel is single-pool per *layer*, which is all it
    ever sees). Returns ``(tokens [batch, steps], k0, v0, k1, v1)``.
    """
    toks, ks, vs = _decode_steps_scan(
        params, cfg, last_tokens, (k0, k1), (v0, v1), (table0, table1),
        ctx_lens, active, steps,
        _decode_step_attention(use_pallas, interpret, mesh,
                               sinks=cfg.attention_sinks or None,
                               shared_kv=cfg.is_mla,
                               shared_stream=cfg.mla_decode_stream,
                               batch_rows=batch_rows),
    )
    return toks, ks[0], vs[0], ks[1], vs[1]


@partial(
    jax.jit,
    static_argnames=("cfg", "interpret", "mesh", "last_only"),
    donate_argnames=("k_cache", "v_cache"),
)
def forward_prefill_pallas(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [batch, seq] int32 (padded)
    k_cache: jax.Array,
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, pages_per_seq]
    ctx_lens: jax.Array,
    new_lens: jax.Array,
    interpret: bool = False,
    mesh=None,
    last_only: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill using the Pallas flash-prefill kernel.

    Same semantics as ``forward``: queries attend causally over the cached
    prefix plus themselves (clipped to the layer's sliding window when
    set, with out-of-window pages skipped), streaming pages HBM→VMEM
    in-kernel instead of materializing the gathered KV. ``mesh`` (tp axis)
    runs the kernel per-shard over the kv-heads sharding.
    """
    from ..ops.pallas_paged_attention import (
        pallas_paged_prefill_attention, sharded_paged_prefill_attention)

    seq = tokens.shape[1]
    # Query rows per program: target group·q_tile ≈ 1024 so each
    # online-softmax round is a [~1024, head_dim]×[head_dim, keys]
    # matmul. Measured on a real v5e at the bench's 2048-token chunks
    # (hack/mfu_probe.py in-jit sweep): q_tile 512 at group 2 runs
    # 1.9 ms/layer vs 3.0 ms at q_tile 128 — bigger tiles re-stream the
    # KV fewer times. Tiny test seqs fall back to their gcd.
    group = cfg.num_heads // max(1, cfg.kv_cache_heads)
    q_tile = math.gcd(seq, max(128, 1024 // max(1, group)))

    sinks = cfg.attention_sinks or None

    def attention_fn(q, k_l, v_l, table, positions, total_lens, window,
                     k_stack=None, v_stack=None, layer_idx=None):
        # Stacked operand + in-kernel layer index: a sliced cache
        # materializes a per-layer copy at the pallas custom-call
        # boundary (see ops.pallas_paged_attention._superblock_streamer).
        if k_stack is not None:
            k_l, v_l = k_stack, v_stack
        if mesh is not None:
            return sharded_paged_prefill_attention(
                mesh, q, k_l, v_l, table, ctx_lens, total_lens,
                q_tile=q_tile, sliding_window=window,
                sinks=sinks, shared_kv=cfg.is_mla, layer_idx=layer_idx,
                interpret=interpret,
            )
        return pallas_paged_prefill_attention(
            q, k_l, v_l, table, ctx_lens, total_lens,
            q_tile=q_tile, sliding_window=window,
            sinks=sinks, shared_kv=cfg.is_mla, layer_idx=layer_idx,
            interpret=interpret,
        )

    return _forward_impl(
        params, cfg, tokens, k_cache, v_cache, page_table, ctx_lens, new_lens,
        attention_fn, last_only=last_only,
    )


@partial(
    jax.jit,
    static_argnames=("cfg", "interpret"),
    donate_argnames=("k_cache", "v_cache"),
)
def forward_ragged(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [1, total_q] int32 flat mixed batch (padded)
    k_cache: jax.Array,  # [layers, pages, kvh, page_size, hd] (donated)
    v_cache: jax.Array,  # same (donated)
    page_table: jax.Array,  # [rows, pages_per_seq] int32
    row_starts: jax.Array,  # [rows+1] int32 flat-token prefix sums
    ctx_lens: jax.Array,  # [rows] tokens already cached per row
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One ragged mixed prefill+decode step via the single ragged kernel.

    Row r's new tokens occupy flat slots ``[row_starts[r],
    row_starts[r+1])`` of ``tokens`` at logical positions
    ``ctx_lens[r] + i`` — a decode row is a 1-token row, a prefill chunk a
    longer one; one dispatch serves the whole mixed batch with no
    per-sequence padding (the flat axis pads only to the q-tile multiple;
    slots at and past ``row_starts[-1]`` are inert). Returns
    ``(logits [rows, vocab], k_cache, v_cache)`` — one logit row per
    ragged row, its final token (the next-token logits for both decode
    rows and a prefill chunk's last token). Single-shard only: the engine
    gates the ragged path off under tp/sp meshes and pp pipelines.
    """
    from ..ops.pallas_paged_attention import pallas_paged_ragged_attention

    total_q = tokens.shape[1]
    new_lens = row_starts[1:] - row_starts[:-1]  # [rows]
    # Ragged batches mix 1-token decode rows with long prefill chunks, so
    # the tile stays small — a decode row straddles at most one tile and
    # pays at most q_tile-1 dead query rows, while a chunk spans many
    # tiles at full occupancy.
    q_tile = math.gcd(total_q, 8)

    sinks = cfg.attention_sinks or None

    def attention_fn(q, k_l, v_l, table, positions, total_lens, window,
                     k_stack=None, v_stack=None, layer_idx=None):
        if k_stack is not None:
            k_l, v_l = k_stack, v_stack
        out = pallas_paged_ragged_attention(
            q[0], k_l, v_l, table, row_starts, ctx_lens,
            q_tile=q_tile, sliding_window=window, sinks=sinks,
            shared_kv=cfg.is_mla, layer_idx=layer_idx, interpret=interpret,
        )
        return out[None]

    logits, ks, vs = _forward_impl_grouped(
        params, cfg, tokens, (k_cache,), (v_cache,), (page_table,),
        ctx_lens, new_lens, attention_fn, last_only=True,
        ragged=row_starts,
    )
    return logits[0], ks[0], vs[0]
