"""Engine checkpoint/resume via Orbax.

The reference's checkpoint story is the persistent offload store (cache
state survives restarts — SURVEY.md §5); this module adds the engine-side
half for the in-tree serving engine: save/restore model parameters and the
engine identity so a restarted pod resumes with identical weights and
cache fingerprints (identical fingerprints → the restarted pod re-attaches
to its offload store and the indexer's entries stay valid).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import asdict, fields

import jax
import orbax.checkpoint as ocp

from ..utils.atomic_io import atomic_write_bytes
from ..utils.logging import get_logger
from .llama import LlamaConfig, Params, init_params, unfuse_params

logger = get_logger("models.checkpoint")

_META_FILE = "engine_meta.json"


def save_engine_checkpoint(path: str, params: Params, model_cfg: LlamaConfig,
                           model_name: str, hash_seed: str = "") -> None:
    """Save params + engine identity to ``path`` (a directory).

    Checkpoints always store the canonical (unfused, per-layer-list)
    layout — portable across fused serving engines, pp-stacked engines,
    TP sharding, and the trainer; fused trees (models.llama.fuse_params)
    and pp-stacked trees (parallel.pipeline.stack_layer_params) convert
    back on save."""
    path = os.path.abspath(path)
    if "layers_stacked" in params:
        from ..parallel.pipeline import unstack_layer_params

        params = unstack_layer_params(params)
    # The tree records the interleave it was ACTUALLY fused with
    # (fuse_params stamps it); trust that over the caller's config. A
    # pre-init config predates the engine's tp fusing decision, and
    # unfuse_params would otherwise refuse the mismatch — correctly, but
    # needlessly: the marker, not the config, is authoritative here.
    marker = params.get("fused_interleave")
    unfuse_cfg = model_cfg
    if marker is not None and int(marker) != model_cfg.fused_interleave:
        unfuse_cfg = dataclasses.replace(model_cfg, fused_interleave=int(marker))
    params = unfuse_params(params, unfuse_cfg)
    # The saved tree is canonical; the persisted config says so
    # (fused_interleave is a runtime serving-layout knob set by tp
    # engines, consumed by the unfuse above).
    model_cfg = dataclasses.replace(model_cfg, fused_interleave=1)
    with ocp.StandardCheckpointer() as ckptr:
        # force=True: periodic re-checkpointing to a fixed path overwrites.
        ckptr.save(os.path.join(path, "params"), params, force=True)
    meta = {
        "model_name": model_name,
        "hash_seed": hash_seed,
        "model_config": {
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in asdict(model_cfg).items()
            if k != "dtype"
        },
        "dtype": str(model_cfg.dtype.__name__ if hasattr(model_cfg.dtype, "__name__")
                     else model_cfg.dtype),
    }
    # Durable publish (atomic_io): the meta file is the checkpoint's
    # validity marker — a crash must not leave it renamed-but-empty.
    atomic_write_bytes(
        os.path.join(path, _META_FILE),
        json.dumps(meta, indent=2).encode("utf-8"),
    )
    logger.info("engine checkpoint saved to %s", path)


def load_engine_checkpoint(
    path: str,
) -> tuple[Params, LlamaConfig, str, str]:
    """Load ``(params, model_cfg, model_name, hash_seed)`` from ``path``."""
    import jax.numpy as jnp

    path = os.path.abspath(path)
    with open(os.path.join(path, _META_FILE)) as f:
        meta = json.load(f)

    cfg_dict = dict(meta["model_config"])
    # Restore tuple-typed fields generically (JSON stores them as lists).
    for f in fields(LlamaConfig):
        if f.name in cfg_dict and isinstance(cfg_dict[f.name], list):
            cfg_dict[f.name] = tuple(cfg_dict[f.name])
    dtype = getattr(jnp, meta.get("dtype", "bfloat16"))
    model_cfg = LlamaConfig(dtype=dtype, **cfg_dict)

    # Restore into the abstract structure of a freshly-initialized tree so
    # shapes/dtypes are validated against the config.
    abstract = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), model_cfg)
    )
    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(os.path.join(path, "params"), abstract)
    return params, model_cfg, meta["model_name"], meta.get("hash_seed", "")
