"""HuggingFace checkpoint loading: serve real Llama-family weights.

Maps a ``transformers`` Llama / Mistral / Mixtral / Qwen2 / Qwen3 /
Qwen3-MoE / DeepSeek-architecture state dict (or a checkpoint
directory) onto this repo's parameter pytree, so the paged
serving engine runs real checkpoints instead of random init. The mapping
is validated end-to-end by logits parity against the authoritative HF
implementation (``tests/test_hf_loader.py`` builds a random-init HF model
and requires our forward to reproduce its logits) — the model family is
pinned to the upstream reference implementation, not just internal
oracles.

Conventions handled:
- ``nn.Linear`` stores ``[out_features, in_features]``; this repo's
  matmuls are activation-major (``x @ W`` with ``W [in, out]``) → every
  projection transposes.
- HF rotary is the half-split ``rotate_half`` form — identical to
  ``llama._rope`` (verified by the parity test), so Q/K need no
  permutation.
- ``tie_word_embeddings`` reuses the embedding matrix as ``lm_head``.

Reference analog: the reference serves through external engines and ships
no loader; this is part of the in-tree serving engine
(PARITY.md "Additions beyond the reference").
"""

from __future__ import annotations

import math

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig, Params


def _yarn_get_mscale(scale: float, m: float = 1.0) -> float:
    """transformers' yarn_get_mscale: 0.1·m·ln(scale)+1 (1.0 for ≤1)."""
    return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0


def _convert_rope_scaling(hf_cfg: Any) -> tuple:
    """Map HF ``rope_scaling`` to ``LlamaConfig.rope_scaling``.

    ``llama3`` (the Llama-3.1+ frequency-band NTK scheme) is implemented
    by ``llama._rope``; every other kind (yarn, linear, dynamic — both
    the modern ``rope_type`` and legacy ``type`` key spellings) refuses:
    converting would silently change every position's frequencies vs the
    checkpoint's training."""
    rope_scaling = getattr(hf_cfg, "rope_scaling", None)
    if not rope_scaling:
        return ()
    kind = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
    if kind == "default":
        return ()
    if kind == "llama3":
        return ("llama3", float(rope_scaling["factor"]),
                float(rope_scaling["low_freq_factor"]),
                float(rope_scaling["high_freq_factor"]),
                float(rope_scaling["original_max_position_embeddings"]))
    if kind == "yarn":
        if not rope_scaling.get("truncate", True):
            raise NotImplementedError(
                "yarn with truncate=false (untruncated correction bounds) "
                "is not implemented")
        factor = float(rope_scaling["factor"])
        att = rope_scaling.get("attention_factor")
        mscale = rope_scaling.get("mscale")
        mscale_all = rope_scaling.get("mscale_all_dim")
        if att is None:
            if mscale and mscale_all:
                att = _yarn_get_mscale(factor, mscale) / _yarn_get_mscale(
                    factor, mscale_all)
            else:
                att = _yarn_get_mscale(factor)
        orig = (rope_scaling.get("original_max_position_embeddings")
                or hf_cfg.max_position_embeddings)
        return ("yarn", factor,
                float(rope_scaling.get("beta_fast") or 32),
                float(rope_scaling.get("beta_slow") or 1),
                float(orig), float(att))
    raise NotImplementedError(
        f"rope_scaling={rope_scaling!r} is not implemented")


def config_from_hf(hf_cfg: Any, page_size: int = 16,
                   dtype: Any = jnp.bfloat16) -> LlamaConfig:
    """Translate a ``transformers`` Llama/Mistral/Qwen config.

    The per-layer attention layout follows ``hf_cfg.layer_types`` when
    present (the authoritative map modern transformers derives from
    ``max_window_layers``: first-N full, rest SWA); otherwise a set
    ``sliding_window`` (Mistral) means uniform SWA. Unsupported features
    raise instead of silently converting to wrong logits.
    """
    n_layers = hf_cfg.num_hidden_layers

    # Architecture allowlist: families whose forward this repo implements
    # exactly. Anything else (Gemma's GELU + softcapping + scaled embeds,
    # Phi's partial rotary, …) must refuse rather than convert to
    # silently-wrong logits.
    supported = ("llama", "mistral", "mixtral", "qwen2", "qwen3",
                 "qwen3_moe", "deepseek_v2", "deepseek_v3")
    if hf_cfg.model_type not in supported:
        raise NotImplementedError(
            f"model_type {hf_cfg.model_type!r} is not supported "
            f"(supported: {supported})")
    act = getattr(hf_cfg, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise NotImplementedError(
            f"hidden_act {act!r} != silu: the SwiGLU MLP here would be "
            f"silently wrong")
    rope_scaling = _convert_rope_scaling(hf_cfg)
    if hf_cfg.model_type.startswith("deepseek"):
        return _config_from_deepseek(hf_cfg, page_size, dtype,
                                     rope_scaling)
    if getattr(hf_cfg, "mlp_bias", False):
        raise NotImplementedError(
            "MLP biases are not implemented; a bias-free conversion "
            "would be silently wrong")
    moe_kw = {}
    if hf_cfg.model_type == "mixtral":
        moe_kw = dict(
            num_experts=hf_cfg.num_local_experts,
            num_experts_per_token=hf_cfg.num_experts_per_tok,
            # "dense" computes every expert with an exact one-hot top-k
            # mix — the semantics HF Mixtral implements
            # (softmax→top-k→renorm == top-k→softmax). The GShard
            # capacity dispatch stays the opt-in performance mode
            # (dataclasses.replace(moe_dispatch="capacity")).
            moe_dispatch="dense",
        )
    elif hf_cfg.model_type == "qwen3_moe":
        # HF layer rule: MoE unless listed in mlp_only_layers, gated by
        # decoder_sparse_step (modeling_qwen3_moe decoder layer init).
        step = getattr(hf_cfg, "decoder_sparse_step", 1) or 1
        only = set(getattr(hf_cfg, "mlp_only_layers", ()) or ())
        moe_layers = tuple(
            i for i in range(n_layers)
            if i not in only and (i + 1) % step == 0)
        if moe_layers:
            moe_kw = dict(
                num_experts=hf_cfg.num_experts,
                num_experts_per_token=hf_cfg.num_experts_per_tok,
                moe_layers=moe_layers,
                moe_intermediate_size=hf_cfg.moe_intermediate_size,
                moe_router=("softmax_topk",
                            int(bool(hf_cfg.norm_topk_prob))),
                moe_dispatch="dense",
            )
    elif getattr(hf_cfg, "num_experts", 0) or getattr(
            hf_cfg, "num_local_experts", 0):
        raise NotImplementedError(
            "MoE checkpoint mapping is only implemented for mixtral and "
            "qwen3_moe")

    layer_types = getattr(hf_cfg, "layer_types", None)
    if layer_types:
        unknown = set(layer_types) - {"full_attention", "sliding_attention"}
        if unknown:
            raise NotImplementedError(f"layer types {unknown} unsupported")
        swa = tuple(i for i, t in enumerate(layer_types)
                    if t == "sliding_attention")
        window = getattr(hf_cfg, "sliding_window", None) if swa else None
    else:
        window = getattr(hf_cfg, "sliding_window", None)
        # Qwen-family configs carry a sliding_window value gated by a
        # separate use_sliding_window flag — honor the gate.
        if not getattr(hf_cfg, "use_sliding_window", True):
            window = None
        swa = tuple(range(n_layers)) if window else ()

    head_dim = getattr(hf_cfg, "head_dim", None) or (
        hf_cfg.hidden_size // hf_cfg.num_attention_heads)
    return LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        num_layers=n_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=hf_cfg.num_key_value_heads,
        head_dim=head_dim,
        intermediate_size=hf_cfg.intermediate_size,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        norm_eps=float(hf_cfg.rms_norm_eps),
        page_size=page_size,
        dtype=dtype,
        sliding_window=window,
        swa_layers=swa,
        qk_norm=hf_cfg.model_type in ("qwen3", "qwen3_moe"),
        rope_scaling=rope_scaling,
        **moe_kw,
    )


def _config_from_deepseek(hf_cfg: Any, page_size: int, dtype: Any,
                          rope_scaling: tuple = ()) -> LlamaConfig:
    """DeepSeek-V2/V3 → absorbed-MLA config.

    Supported subset: dense MLP layers only (``num_hidden_layers <=
    first_k_dense_replace``) and ``v_head_dim == qk_nope_head_dim`` (the
    shared head_dim here); q-LoRA (the full V2/V3 form) and the direct q
    projection (V2-lite) both convert. The parity test pins our
    *absorbed* attention against HF's materialized MLA — a
    cross-implementation check of the absorption algebra.
    """
    if hf_cfg.v_head_dim != hf_cfg.qk_nope_head_dim:
        raise NotImplementedError(
            f"v_head_dim {hf_cfg.v_head_dim} != qk_nope_head_dim "
            f"{hf_cfg.qk_nope_head_dim}: this model shares one head_dim")
    n_layers = hf_cfg.num_hidden_layers
    moe_kw = {}
    first_dense = getattr(hf_cfg, "first_k_dense_replace", 0)
    if getattr(hf_cfg, "n_routed_experts", None) and n_layers > first_dense:
        if hf_cfg.model_type != "deepseek_v3":
            raise NotImplementedError(
                "MoE conversion is implemented for deepseek_v3 only "
                "(V2's softmax/greedy router differs)")
        if getattr(hf_cfg, "topk_method", "noaux_tc") not in (
                "noaux_tc", None):
            raise NotImplementedError(
                f"topk_method {hf_cfg.topk_method!r} unsupported")
        moe_kw = dict(
            num_experts=hf_cfg.n_routed_experts,
            num_experts_per_token=hf_cfg.num_experts_per_tok,
            moe_layers=tuple(range(first_dense, n_layers)),
            n_shared_experts=hf_cfg.n_shared_experts,
            moe_intermediate_size=hf_cfg.moe_intermediate_size,
            moe_router=("deepseek_v3", hf_cfg.n_group,
                        hf_cfg.topk_group,
                        int(bool(hf_cfg.norm_topk_prob)),
                        float(hf_cfg.routed_scaling_factor)),
            moe_dispatch="dense",
        )
    # DeepSeek yarn: the generic cos/sin attention factor applies via
    # rope_scaling; for deepseek_v3 ONLY, mscale_all_dim ADDITIONALLY
    # multiplies the softmax scale by mscale^2 (in-tree
    # DeepseekV3Attention.__init__ — DeepseekV2Attention has no such
    # term, verified against transformers 4.57; the V2 parity test pins
    # it).
    scale_mult = 1.0
    hf_rs = getattr(hf_cfg, "rope_scaling", None)
    if (rope_scaling and hf_cfg.model_type == "deepseek_v3"
            and hf_rs and hf_rs.get("mscale_all_dim")):
        m = _yarn_get_mscale(float(hf_rs["factor"]),
                             float(hf_rs["mscale_all_dim"]))
        scale_mult = m * m
    return LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        num_layers=n_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=hf_cfg.num_attention_heads,
        head_dim=hf_cfg.qk_nope_head_dim,
        intermediate_size=hf_cfg.intermediate_size,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        norm_eps=float(hf_cfg.rms_norm_eps),
        page_size=page_size,
        dtype=dtype,
        kv_lora_rank=hf_cfg.kv_lora_rank,
        qk_rope_head_dim=hf_cfg.qk_rope_head_dim,
        rope_scaling=rope_scaling,
        softmax_scale_mult=scale_mult,
        **moe_kw,
    )


def _deinterleave(w: np.ndarray, dr: int) -> np.ndarray:
    """Permute the trailing ``dr`` output columns from HF DeepSeek's
    interleaved-rotary layout (pairs (2i, 2i+1)) to this repo's
    half-split layout (pairs (i, i+dr/2)).

    Rotations act on activations, so permuting the columns that PRODUCE
    the rope dims makes half-split rope equal interleaved rope up to the
    same permutation on both q_pe and k_pe — and their dot product (the
    only consumer) is permutation-invariant.
    """
    order = np.concatenate([np.arange(0, dr, 2), np.arange(1, dr, 2)])
    out = w.copy()
    out[..., -dr:] = w[..., -dr:][..., order]
    return out


def params_from_hf(state_dict: Mapping[str, Any], cfg: LlamaConfig,
                   mla_rope_interleaved: bool = True) -> Params:
    """Build the parameter pytree from an HF Llama-architecture state dict.

    Accepts torch tensors or numpy arrays. Norm scales stay fp32 (this
    repo's convention — norms compute in fp32); projections cast to
    ``cfg.dtype``. ``mla_rope_interleaved`` mirrors DeepSeek's
    ``rope_interleave`` (True in both HF implementations; V3 exposes the
    flag) — when set, the rope-producing weight columns are permuted so
    this repo's half-split rotary reproduces HF's interleaved one (see
    ``_deinterleave``).
    """
    consumed: set = set()

    def get(name):
        consumed.add(name)
        t = state_dict[name]
        if hasattr(t, "detach"):  # torch tensor
            t = t.detach().to("cpu").float().numpy()
        return np.asarray(t)

    def proj(name):  # [out, in] -> [in, out], model dtype
        return jnp.asarray(get(name).T, cfg.dtype)

    def norm(name):  # fp32 scale vector
        return jnp.asarray(get(name), jnp.float32)

    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        layer = {
            "attn_norm": norm(p + "input_layernorm.weight"),
            "mlp_norm": norm(p + "post_attention_layernorm.weight"),
            "wo": proj(p + "self_attn.o_proj.weight"),
        }
        if p + "mlp.gate.weight" in state_dict:
            # DeepSeek / Qwen3-MoE layer: router + routed experts. The
            # router KIND decides the extra tensors: deepseek_v3 REQUIRES
            # the e_score_correction bias and shared expert (a truncated
            # checkpoint fails here, at load, naming the tensor);
            # softmax_topk (Qwen3-MoE) has neither.
            E = cfg.num_experts
            deepseek = cfg.moe_router and cfg.moe_router[0] == "deepseek_v3"
            layer["router"] = proj(p + "mlp.gate.weight")
            if deepseek:
                layer["router_bias"] = norm(
                    p + "mlp.gate.e_score_correction_bias")
            for ours, theirs in (("w_gate", "gate_proj"),
                                 ("w_up", "up_proj"),
                                 ("w_down", "down_proj")):
                layer[ours] = jnp.stack([
                    proj(p + f"mlp.experts.{e}.{theirs}.weight")
                    for e in range(E)])
            if deepseek:
                for ours, theirs in (("w_gate_sh", "gate_proj"),
                                     ("w_up_sh", "up_proj"),
                                     ("w_down_sh", "down_proj")):
                    layer[ours] = proj(
                        p + f"mlp.shared_experts.{theirs}.weight")
        elif p + "block_sparse_moe.gate.weight" in state_dict:  # Mixtral
            E = cfg.num_experts
            layer["router"] = proj(p + "block_sparse_moe.gate.weight")
            for ours, theirs in (("w_gate", "w1"), ("w_up", "w3"),
                                 ("w_down", "w2")):
                # Stack via per-expert proj(): only ONE expert's fp32
                # copy is live at a time (a real 8x7B stack would
                # otherwise hold ~2 GB of transient fp32 per tensor).
                layer[ours] = jnp.stack([
                    proj(p + f"block_sparse_moe.experts.{e}"
                             f".{theirs}.weight")
                    for e in range(E)])
        else:
            layer["w_gate"] = proj(p + "mlp.gate_proj.weight")
            layer["w_up"] = proj(p + "mlp.up_proj.weight")
            layer["w_down"] = proj(p + "mlp.down_proj.weight")
        if cfg.is_mla:
            # DeepSeek: q either direct (V2-lite) or via the q-LoRA
            # compressed latent; fused latent down-projection, RMS-normed
            # latent, fused k_nope/v up-projections split into the
            # absorbed form.
            r, dr, hd = (cfg.kv_lora_rank, cfg.qk_rope_head_dim,
                         cfg.head_dim)
            H = cfg.num_heads
            if p + "self_attn.q_a_proj.weight" in state_dict:  # q-LoRA
                layer["w_dq"] = proj(p + "self_attn.q_a_proj.weight")
                layer["q_latent_norm"] = norm(
                    p + "self_attn.q_a_layernorm.weight")
                wq = get(p + "self_attn.q_b_proj.weight").T
            else:
                wq = get(p + "self_attn.q_proj.weight").T  # [h|q_lora, H*(hd+dr)]
            wq = wq.reshape(wq.shape[0], H, hd + dr)
            if mla_rope_interleaved:
                wq = _deinterleave(wq, dr)
            layer["wq"] = jnp.asarray(
                wq.reshape(wq.shape[0], H * (hd + dr)), cfg.dtype)
            kva = get(p + "self_attn.kv_a_proj_with_mqa.weight").T
            layer["w_dkv"] = jnp.asarray(kva[:, :r], cfg.dtype)
            k_rope = kva[:, r:]
            if mla_rope_interleaved:
                k_rope = _deinterleave(k_rope, dr)
            layer["w_kr"] = jnp.asarray(k_rope, cfg.dtype)
            layer["latent_norm"] = norm(
                p + "self_attn.kv_a_layernorm.weight")
            kvb = get(p + "self_attn.kv_b_proj.weight").reshape(
                H, 2 * hd, r)  # [H, nope+v, r]
            layer["w_uk"] = jnp.asarray(
                kvb[:, :hd, :].transpose(0, 2, 1), cfg.dtype)
            layer["w_uv"] = jnp.asarray(
                kvb[:, hd:, :].transpose(0, 2, 1), cfg.dtype)
        else:
            layer["wq"] = proj(p + "self_attn.q_proj.weight")
            layer["wk"] = proj(p + "self_attn.k_proj.weight")
            layer["wv"] = proj(p + "self_attn.v_proj.weight")
            if cfg.qk_norm:  # Qwen3: per-head RMS on Q/K pre-RoPE
                layer["q_norm"] = norm(p + "self_attn.q_norm.weight")
                layer["k_norm"] = norm(p + "self_attn.k_norm.weight")
            if p + "self_attn.q_proj.bias" in state_dict:  # Qwen2 lineage
                for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"),
                                     ("bv", "v_proj")):
                    layer[ours] = jnp.asarray(
                        get(p + f"self_attn.{theirs}.bias"), cfg.dtype)
        layers.append(layer)

    embed = jnp.asarray(get("model.embed_tokens.weight"), cfg.dtype)
    if "lm_head.weight" in state_dict:
        lm_head = proj("lm_head.weight")
    else:  # tie_word_embeddings
        lm_head = embed.T
    params = {
        "embed": embed,
        "layers": layers,
        "final_norm": norm("model.norm.weight"),
        "lm_head": lm_head,
    }
    # Every tensor the checkpoint carries must have landed in the pytree
    # (modulo non-persistent rotary buffers older exports include) — a
    # leftover weight means an architectural feature this model lacks,
    # and ignoring it would serve silently-wrong logits.
    leftover = [k for k in state_dict
                if k not in consumed and "rotary_emb" not in k]
    if leftover:
        raise NotImplementedError(
            f"checkpoint carries unmapped tensors ({leftover[:4]}…) — "
            f"this architecture has features the conversion would drop")
    return params


def load_hf_checkpoint(path: str, page_size: int = 16,
                       dtype: Any = jnp.bfloat16):
    """Load a local HF checkpoint directory → ``(LlamaConfig, Params)``.

    Uses ``transformers`` to materialize the state dict (handles both
    safetensors and torch shards); zero-egress environments must have the
    checkpoint on disk already.
    """
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(path)
    cfg = config_from_hf(hf_cfg, page_size=page_size, dtype=dtype)
    # Validate the config BEFORE materializing weights; load at the
    # checkpoint's own dtype without full nn.Module init — fp32
    # materialization of an 8B checkpoint would double peak host RAM
    # (get() upcasts per-tensor during conversion anyway).
    import inspect as _inspect

    # transformers >= 4.56 renamed torch_dtype -> dtype; pick by
    # signature (an unknown kwarg can be silently absorbed into config
    # kwargs on some releases, so try/except is not a reliable probe).
    sig = _inspect.signature(AutoModelForCausalLM.from_pretrained)
    accepts_dtype = "dtype" in sig.parameters or any(
        p.kind is _inspect.Parameter.VAR_KEYWORD
        for p in sig.parameters.values())
    dtype_kw = {"dtype": "auto"} if accepts_dtype else {
        "torch_dtype": "auto"}
    model = AutoModelForCausalLM.from_pretrained(
        path, low_cpu_mem_usage=True, **dtype_kw)
    params = params_from_hf(
        model.state_dict(), cfg,
        mla_rope_interleaved=getattr(hf_cfg, "rope_interleave", True))
    return cfg, params
