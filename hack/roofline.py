#!/usr/bin/env python
"""CPU-side roofline + XLA cost-model analysis of the bench prefill.

VERDICT r3 #1 fallback deliverable: with the TPU tunnel down, produce the
maximally-detailed *a priori* account of where the 0.9B/4k cold prefill's
time must go on a v5e, so the first on-chip hour is pure measurement
(`hack/mfu_probe.py`), not prep.

Two independent estimates, cross-checked:

1. **Analytic**: per-component FLOPs and minimum HBM traffic derived from
   the model config (weights read once per chunk, activations read/written
   per op, KV pages scattered/gathered) — the numbers a reviewer can check
   by hand.
2. **XLA cost model**: ``jit(forward).lower(...).compile().cost_analysis()``
   flops/bytes for the REAL compiled program (CPU backend — XLA's flop
   count is arithmetic, not platform, so it cross-checks the analytic
   count; bytes differ with fusion decisions and are reported as a range
   check, not truth).

v5e roofline constants: 197 TFLOP/s bf16 peak (MXU), 819 GB/s HBM.
Each component's floor is max(flops/peak, bytes/bw); the sum over the
chunked prefill is the no-overhead floor the measured number is judged
against (round-2 measured: 1.77 s cold 4k prefill ≈ 2-3%% MFU).

Usage: env PYTHONPATH=. JAX_PLATFORMS=cpu python hack/roofline.py
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np

from llmd_kv_cache_tpu.models.llama import (
    LlamaConfig, forward, init_kv_cache, init_params,
)

# The bench's TPU sizing (bench.py main()) and v5e hardware constants.
CFG = LlamaConfig(
    vocab_size=32000, hidden_size=2048, num_layers=16,
    num_heads=16, num_kv_heads=8, head_dim=128,
    intermediate_size=5632, page_size=16,
)
CHUNK = 2048
PREFIX = 4096          # bench prefix length; prefill = 2 chunks of 2048
PAGES_PER_SEQ = 272
NUM_PAGES = 1024
PEAK_TFLOPS = 197e12   # v5e bf16
HBM_GBPS = 819e9       # v5e HBM bandwidth
BF16 = 2               # bytes


def analytic_chunk(ctx: int) -> dict[str, dict[str, float]]:
    """Per-component FLOPs + minimum HBM bytes for one CHUNK-token step
    with ``ctx`` tokens already cached (weights in bf16, activations
    bf16, fp32 softmax/norm stats ignored — they fuse)."""
    h, inter, v = CFG.hidden_size, CFG.intermediate_size, CFG.vocab_size
    L, t = CFG.num_layers, CHUNK
    kvh_dim = CFG.num_kv_heads * CFG.head_dim  # 1024

    comp: dict[str, dict[str, float]] = {}

    def add(name, flops, w_bytes, act_bytes):
        comp[name] = {"flops": flops, "bytes": w_bytes + act_bytes}

    # Projections (per layer × L): weight read + activation in/out.
    add("qkv_proj", L * 2 * t * h * (h + 2 * kvh_dim),
        L * h * (h + 2 * kvh_dim) * BF16,
        L * (t * h + t * (h + 2 * kvh_dim)) * BF16)
    add("wo_proj", L * 2 * t * h * h, L * h * h * BF16,
        L * 2 * t * h * BF16)
    add("mlp", L * 2 * t * h * 3 * inter, L * 3 * h * inter * BF16,
        L * (2 * t * h + 3 * t * inter) * BF16)
    # Attention: QK^T + PV over ctx + causal self (avg t/2 keys), GQA
    # grouped. Bytes: gathered K+V pages (ctx+t tokens, kvh heads) read
    # once per layer + Q/attn-out activations; the gather MATERIALIZES
    # the gathered KV in HBM on the XLA path (write + read) — counted,
    # because that is the design's real cost (the Pallas path streams it).
    keys = ctx + t / 2
    add("attention", L * 4 * t * keys * CFG.num_heads * CFG.head_dim,
        0.0,
        L * ((ctx + t) * kvh_dim * 2 * BF16 * 2   # gather write+read, K+V
             + 2 * t * CFG.num_heads * CFG.head_dim * BF16))
    # KV scatter: write t tokens × kvh into pages (read-modify-write of
    # touched pages ~= 2× write).
    add("kv_scatter", 0.0, 0.0, L * 2 * t * kvh_dim * 2 * BF16)
    # Embed gather + final norm (activations only).
    add("embed", 0.0, 0.0, t * h * BF16 * 2)
    # lm_head: last_only=True in the serving path → one row.
    add("lm_head_last", 2 * 1 * h * v, h * v * BF16, (h + v) * 4)
    return comp


def roofline(comp: dict[str, dict[str, float]]):
    rows = []
    for name, c in comp.items():
        t_c = c["flops"] / PEAK_TFLOPS
        t_m = c["bytes"] / HBM_GBPS
        rows.append({
            "component": name,
            "tflop": round(c["flops"] / 1e12, 4),
            "mbytes": round(c["bytes"] / 1e6, 2),
            "t_compute_us": round(t_c * 1e6, 1),
            "t_memory_us": round(t_m * 1e6, 1),
            "bound": "compute" if t_c >= t_m else "memory",
            "floor_us": round(max(t_c, t_m) * 1e6, 1),
        })
    return rows


def xla_cost_check():
    """Compile the real forward (CPU) and pull XLA's flop/byte estimate."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    k_cache, v_cache = init_kv_cache(CFG, NUM_PAGES)
    tokens = jnp.zeros((1, CHUNK), jnp.int32)
    table = jnp.asarray(
        np.arange(1, 1 + PAGES_PER_SEQ, dtype=np.int32))[None, :]
    ctx = jnp.asarray([2048], jnp.int32)
    new = jnp.asarray([CHUNK], jnp.int32)
    lowered = jax.jit(
        forward.__wrapped__, static_argnames=("cfg", "last_only")
    ).lower(params, CFG, tokens, k_cache, v_cache, table, ctx, new,
            last_only=True)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):  # older jax returns one dict per device
        cost = cost[0]
    return {
        "flops": cost.get("flops"),
        "bytes_accessed": cost.get("bytes accessed"),
    }


def main():
    chunks = []
    total_floor = 0.0
    total_tflop = 0.0
    for ci in range(PREFIX // CHUNK):
        ctx = ci * CHUNK
        comp = analytic_chunk(ctx)
        rows = roofline(comp)
        floor = sum(r["floor_us"] for r in rows)
        tflop = sum(r["tflop"] for r in rows)
        total_floor += floor
        total_tflop += tflop
        chunks.append({"chunk": ci, "ctx": ctx, "rows": rows,
                       "floor_us": round(floor, 1),
                       "tflop": round(tflop, 3)})

    measured_r2_s = 1.77  # round-2 on-chip cold 4k prefill (bench log)
    floor_s = total_floor / 1e6
    out = {
        "model": "bench 0.9B (h2048 L16 kv8x128 inter5632 v32000)",
        "prefill_tokens": PREFIX,
        "chunks": chunks,
        "total_tflop": round(total_tflop, 2),
        "roofline_floor_ms": round(floor_s * 1e3, 1),
        "mfu_at_floor_pct": round(
            100 * total_tflop * 1e12 / (floor_s * PEAK_TFLOPS), 1),
        "measured_r2_s": measured_r2_s,
        "gap_vs_floor": round(measured_r2_s / floor_s, 1),
        "implied_measured_mfu_pct": round(
            100 * total_tflop * 1e12 / (measured_r2_s * PEAK_TFLOPS), 2),
    }
    try:
        out["xla_cost_model_one_chunk"] = xla_cost_check()
    except Exception as e:  # cost_analysis availability varies by backend
        out["xla_cost_model_one_chunk"] = {"error": str(e)}
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
