#!/usr/bin/env python
"""kvdiag: one-shot diagnostic snapshot of a running indexer's admin endpoint.

Scrapes the stdlib admin server (``services/admin.py``) and folds everything
an on-call engineer needs into a single JSON report on stdout:

- ``/healthz``                 — liveness
- ``/debug/vars``              — flight-recorder ring + every registered
                                 debug provider (per-pod event lag, the
                                 cache-efficiency ledger, engine telemetry, …)
- ``/metrics`` (parsed)        — the ``kvcache_*`` / ``kv_offload_*`` /
                                 ``kvtpu_engine_*`` / ``kvtpu_shard_*`` /
                                 ``kvtpu_handoff_*``
                                 Prometheus families as name → samples
- ``engine`` (summary)         — when the target is an engine pod: KV-pool
                                 occupancy, request phase percentiles
                                 (TTFT/ITL/TPOT/step), and the last
                                 profiler-capture path
- ``shard`` (summary)          — when the target is a shard replica of the
                                 sharded control plane: shard identity,
                                 owned/filtered write counters, and the
                                 consistent-hash ring view
- ``handoff`` (summary)        — when the pod participates in prefill/
                                 decode disaggregation: transfer queue
                                 depth, in-flight store jobs, and the last
                                 handoff latency
- ``ledger`` (summary)         — indexer pods: the cache-efficiency
                                 ledger condensed per pod (appearances,
                                 wins, stored/evicted blocks)
- ``workingset`` (summary)     — pods running the working-set tracker:
                                 sampler health (rate, windows, tracked
                                 blocks, self-measured overhead)
- ``fleet`` (``--fleet``)      — when the target is the fleet telemetry
                                 collector: assembled-trace summaries
                                 (critical path + processes), per-role
                                 rollup percentiles, SLO burn-rate /
                                 alert state, and the working-set what-if
                                 capacity table (hit ratio at
                                 0.5x/1x/2x/4x HBM, never-read offload
                                 fraction, cross-pod duplicate share)
- ``fleet.audit`` (summary)    — collector targets running the audit
                                 plane: score-vs-reality calibration per
                                 pod, routing-regret rate, and current
                                 index divergence (phantom/ghost blocks)
                                 with the degraded pods named
- ``fleet.anomaly`` (summary)  — collector targets: robust-z anomaly
                                 sentinels (firing state, last score)
                                 over the fleet SLI series
- ``fleet.incidents`` (summary)— collector targets: the incident
                                 black-box state (recent bundles,
                                 suppression counters, per-pod clock
                                 offsets)
- ``controller`` (summary)     — when the target is the fleet controller:
                                 the last N actions with each action's
                                 causing signal, per-action-kind cooldown
                                 + hysteresis state, the global action
                                 budget, in-flight (unsettled) actions,
                                 and dry-run would-have-acted records

Usage:
  python hack/kvdiag.py --port 9400 [--host 127.0.0.1] [--out report.json]
  python hack/kvdiag.py --port 9500 --fleet          # collector target
  python hack/kvdiag.py --targets 127.0.0.1:9400,127.0.0.1:9401
  python hack/kvdiag.py --port 9400 --watch 5        # delta lines
  python hack/kvdiag.py --incident /var/kvtpu/incident-00000001-slo.inc

``--incident <bundle>`` needs no running pod at all: it opens an
incident black-box bundle offline and prints the skew-corrected
cross-pod timeline, the alerts/anomalies firing at capture time, the
dominant critical-path segment, and the first-anomalous-pod heuristic
(this mode imports ``llmd_kv_cache_tpu`` for the bundle codec).

Multi-target scrapes (``--targets``) degrade gracefully: an unreachable
pod contributes an ``{"error": ...}`` stanza instead of aborting the
whole report.

Stdlib-only on purpose: this must run inside the most degraded pod
imaginable (``kubectl exec`` + whatever python is present).

Exit codes: 0 healthy, 2 target unreachable, 3 (with ``--fleet``) at
least one SLO alert is firing — so cron/CI can gate on
``kvdiag --fleet --quiet``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

METRIC_PREFIXES = ("kvcache_", "kv_offload_", "kvtpu_engine_", "kvtpu_shard_",
                   "kvtpu_handoff_", "kvtpu_slo_", "kvtpu_trace_",
                   "kvtpu_fleet_", "kvtpu_pyprof_", "kvtpu_offload_",
                   "kvtpu_workingset_", "kvtpu_cache_ledger_", "kvtpu_ctrl_",
                   "kvtpu_ingest_", "kvtpu_native_", "kvtpu_audit_",
                   "kvtpu_index_divergence_", "kvtpu_topology_",
                   "kvtpu_anomaly_", "kvtpu_incident_")


def _fetch(url: str, timeout: float) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def parse_metrics(text: str) -> dict:
    """Prometheus text exposition → {family: {"type": t, "samples": [...]}},
    keeping only this project's metric families.

    ``# TYPE`` lines are retained (previously every ``#`` line was
    skipped, which threw the family type away): any consumer merging
    across pods must know summable counters from gauges. Sample names are
    mapped back to their TYPE'd family (``foo_total``/``foo_bucket`` →
    family ``foo``) so histogram pieces stay grouped.
    """
    types: dict[str, str] = {}
    families: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        name_and_labels, _, value = line.rpartition(" ")
        if not name_and_labels:
            continue
        if "{" in name_and_labels:
            name, _, raw_labels = name_and_labels.partition("{")
            raw_labels = raw_labels.rstrip("}")
            labels = {}
            for pair in raw_labels.split(","):
                if "=" in pair:
                    k, _, v = pair.partition("=")
                    labels[k] = v.strip('"')
        else:
            name, labels = name_and_labels, {}
        if not name.startswith(METRIC_PREFIXES):
            continue
        try:
            num = float(value)
        except ValueError:
            continue
        family = name
        for suffix in ("_total", "_bucket", "_sum", "_count", "_created"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                family = name[: -len(suffix)]
                break
        fam = families.setdefault(
            family, {"type": types.get(family, "untyped"), "samples": []})
        if fam["type"] == "untyped" and family in types:
            fam["type"] = types[family]
        fam["samples"].append({"name": name, "labels": labels, "value": num})
    return families


def snapshot(host: str, port: int, timeout: float = 5.0,
             fleet: bool = False) -> dict:
    base = f"http://{host}:{port}"
    report: dict = {"endpoint": base}

    status, body = _fetch(f"{base}/healthz", timeout)
    report["healthz"] = {
        "status_code": status,
        "body": json.loads(body) if status == 200 else body.decode("utf-8", "replace"),
    }

    status, body = _fetch(f"{base}/debug/vars", timeout)
    if status == 200:
        report["debug"] = json.loads(body)
    else:
        # metrics-only endpoint (metricsPort without adminPort): still a
        # valid target, the report just lacks the debug surfaces.
        report["debug"] = {"error": f"/debug/vars -> HTTP {status}"}

    status, body = _fetch(f"{base}/metrics", timeout)
    if status == 200:
        report["metrics"] = parse_metrics(body.decode("utf-8", "replace"))
    else:
        report["metrics"] = {"error": f"/metrics -> HTTP {status}"}

    engine = report["debug"].get("engine") if isinstance(report["debug"], dict) else None
    if isinstance(engine, dict) and "pool" in engine:
        # Engine pods (telemetry.engine_telemetry): lift the bits an
        # on-call engineer scans first into a top-level summary.
        report["engine"] = {
            "pool": engine.get("pool", {}),
            "phases": engine.get("phases", {}),
            "requests": engine.get("requests", {}),
            "ragged": engine.get("ragged", {}),
            "last_profile": (engine.get("last_profile") or {}).get("dir"),
        }

    shard = report["debug"].get("shard") if isinstance(report["debug"], dict) else None
    if isinstance(shard, dict):
        # Shard replicas (cluster/ ShardFilterIndex debug provider): the
        # identity + ring balance an on-call engineer checks before
        # blaming the router for skewed or degraded scores.
        ring = shard.get("ring") or {}
        report["shard"] = {
            "shard_id": shard.get("shard_id"),
            "replication_factor": shard.get("replication_factor"),
            "owned_writes": shard.get("owned_writes"),
            "filtered_writes": shard.get("filtered_writes"),
            "ring_members": ring.get("shards"),
            "ring_version": ring.get("version"),
            "ring_load": ring.get("load"),
        }

    handoff = report["debug"].get("handoff") if isinstance(report["debug"], dict) else None
    metrics = report.get("metrics") or {}

    def _gauge(name):
        fam = metrics.get(name) if isinstance(metrics, dict) else None
        samples = fam.get("samples") if isinstance(fam, dict) else None
        return samples[0]["value"] if samples else None

    if isinstance(handoff, dict):
        # Disaggregated pods (offload.handoff debug provider): the live
        # transfer ledger — is the decode side waiting because stores are
        # queued, in flight, or failing?
        report["handoff"] = {
            "transfer_queue_depth": handoff.get("transfer_queue_depth"),
            "in_flight_jobs": handoff.get("in_flight_jobs"),
            "completed": handoff.get("completed"),
            "failed": handoff.get("failed"),
            "last_handoff_latency_s": handoff.get("last_handoff_latency_s"),
        }
    elif _gauge("kvtpu_handoff_transfer_queue_depth") is not None:
        # No debug provider (metrics-only endpoint): fall back to the
        # exported gauges so the section still answers the triage basics.
        report["handoff"] = {
            "transfer_queue_depth": _gauge("kvtpu_handoff_transfer_queue_depth"),
            "in_flight_jobs": _gauge("kvtpu_handoff_in_flight_jobs"),
        }

    debug = report["debug"] if isinstance(report["debug"], dict) else {}

    ledger = debug.get("ledger")
    if isinstance(ledger, dict) and "pods" in ledger:
        # Indexer pods: the cache-efficiency ledger (also exported as the
        # kvtpu_cache_ledger_* families) — which pods earn their cache
        # footprint, condensed to the counters scanned first.
        hit = ledger.get("lookup_hit_blocks") or 0
        total = ledger.get("lookup_blocks") or 0
        report["ledger"] = {
            "score_calls": ledger.get("score_calls"),
            "lookup_hit_ratio": round(hit / total, 4) if total else None,
            "pods": {
                pod: {
                    "appearances": st.get("appearances"),
                    "wins": st.get("wins"),
                    "stored_blocks": st.get("stored_blocks"),
                    "evicted_blocks": st.get("evicted_blocks"),
                }
                for pod, st in (ledger.get("pods") or {}).items()
            },
        }

    dp = debug.get("data_plane")
    if isinstance(dp, dict):
        # Native data plane (/debug/data_plane): zero-copy ingest and
        # chunked native-scoring counters. A shard serving fleet traffic
        # with zerocopy_batches == 0 is decoding msgpack per event; a
        # native_score_calls == 0 indexer is scoring in Python — both
        # mean the fast path silently disengaged.
        report["data_plane"] = dp

    ws_state = debug.get("workingset_state")
    if isinstance(ws_state, dict):
        # Pods running the working-set tracker: sampler health (the
        # reuse windows themselves live at /debug/workingset).
        report["workingset"] = ws_state

    controller = debug.get("controller")
    if isinstance(controller, dict):
        report["controller"] = controller_summary(controller)

    if fleet or "rollup" in debug:
        report["fleet"] = fleet_summary(debug)

    return report


def controller_summary(view: dict, last_n: int = 10) -> dict:
    """Condense the fleet controller's ``/debug/controller`` view into the
    triage questions: what did it do and *why* (last N actions, each with
    the causing signal), is it allowed to act again (cooldowns, budget,
    hysteresis arming), is anything in flight after a restart, and what
    would a ``--dry-run`` controller have done."""

    def _action(rec: dict) -> dict:
        signal = rec.get("signal")
        if isinstance(signal, str):
            # Span attributes carry the signal JSON-encoded; decode for
            # the report so grepping the snapshot finds slo names.
            try:
                signal = json.loads(signal)
            except ValueError:
                pass
        return {
            "action_id": rec.get("action_id"),
            "ts": rec.get("ts"),
            "phase": rec.get("phase"),
            "kind": rec.get("kind"),
            "target": rec.get("target"),
            "reason": rec.get("reason"),
            "signal": signal,
            "result": rec.get("result"),
        }

    policy = view.get("policy") or {}
    hysteresis = policy.get("hysteresis") or {}
    return {
        "dry_run": view.get("dry_run"),
        "rounds": view.get("rounds"),
        "resumed_records": view.get("resumed_records"),
        "budget": view.get("budget"),
        "cooldowns": policy.get("cooldowns"),
        "hysteresis_armed": {
            name: (st or {}).get("armed")
            for name, st in hysteresis.items()
            if isinstance(st, dict)
        },
        "pending": [_action(r) for r in view.get("pending") or []],
        "last_actions": [
            _action(r) for r in (view.get("actions") or [])[-last_n:]
        ],
        "would_act": [
            _action(r) for r in (view.get("would_act") or [])[-last_n:]
        ],
    }


def fleet_summary(debug: dict) -> dict:
    """Condense the telemetry collector's debug providers (``traces``,
    ``slo``, ``rollup``) into what an on-call engineer scans first:
    which traces were kept and why, where the request time went
    (critical-path head), fleet percentiles per role, and any burning
    SLOs."""
    traces = debug.get("traces") or {}
    slo = debug.get("slo") or {}
    rollup = debug.get("rollup") or {}
    pyprof = debug.get("pyprof") or {}
    prof_spans = pyprof.get("spans") or {}
    out: dict = {
        "open_traces": traces.get("open_traces"),
        "assembled_total": traces.get("assembled_total"),
        "sampled_out_total": traces.get("sampled_out_total"),
    }

    kept = []
    for t in traces.get("retained") or []:
        path = t.get("critical_path") or []
        head = max(path, key=lambda seg: seg.get("self_time_s", 0.0)) \
            if path else None
        dominant = None
        if head is not None:
            dominant = {
                "name": head.get("name"),
                "process": head.get("process"),
                "self_time_s": head.get("self_time_s"),
            }
            # Join against the fleet-merged continuous profile: which
            # function dominates the CPU samples taken *inside* this
            # critical-path segment ("score fan-out: 41% in msgpack
            # decode").
            prof = prof_spans.get(head.get("name"))
            functions = (prof or {}).get("functions") or {}
            if functions:
                fn = next(iter(functions))
                dominant["dominant_function"] = fn
                dominant["function_share"] = functions[fn]
        kept.append({
            "trace_id": t.get("trace_id"),
            "reason": t.get("retained_reason"),
            "duration_s": t.get("duration_s"),
            "span_count": t.get("span_count"),
            "processes": t.get("processes"),
            "dominant_segment": dominant,
        })
    kept.sort(key=lambda t: -(t["duration_s"] or 0.0))
    out["retained_traces"] = kept

    if pyprof:
        # Continuous-profiling rollup: where the fleet's CPU time went,
        # per span, without anyone having run a profiler by hand.
        out["profile"] = {
            "windows": pyprof.get("windows"),
            "samples": pyprof.get("samples"),
            "targets": pyprof.get("targets"),
            "spans": {
                name: {
                    "samples": entry.get("samples"),
                    "top_functions": dict(
                        list((entry.get("functions") or {}).items())[:3]),
                }
                for name, entry in prof_spans.items()
            },
            "attribution": pyprof.get("attribution"),
        }

    out["rollup"] = {
        role: fams for role, fams in rollup.items() if role != "targets"
    }
    out["targets"] = rollup.get("targets", {})

    alerts = []
    for name, view in (slo or {}).items():
        if not isinstance(view, dict):
            continue
        severity = (view.get("alert") or {}).get("severity")
        if severity:
            alerts.append({
                "slo": name,
                "severity": severity,
                "burn_rates": view.get("burn_rates"),
                "error_budget_remaining": view.get("error_budget_remaining"),
            })
    workingset = debug.get("workingset") or {}
    if workingset.get("windows"):
        # What-if capacity planning: the fleet-merged miss-ratio curve
        # evaluated at multiples of current HBM, next to the never-read
        # offload fraction and the cross-pod duplicate share (the numbers
        # the SSD-admission and dedup ROADMAP items consume).
        out["workingset"] = {
            "windows": workingset.get("windows"),
            "targets": workingset.get("targets"),
            "hbm_capacity_blocks": workingset.get("hbm_capacity_blocks"),
            "whatif": workingset.get("whatif"),
            "whatif_table": [
                f"{row.get('factor'):g}x HBM "
                f"({row.get('capacity_blocks')} blocks): "
                f"est hit ratio {row.get('est_hit_ratio'):.1%}"
                for row in workingset.get("whatif") or []
            ],
            "never_read_offload_fraction":
                (workingset.get("never_read") or {}).get("fraction"),
            "cross_pod_duplicate_share":
                (workingset.get("duplication") or {}).get("share"),
            "scopes": {
                name: {
                    "accesses": st.get("accesses"),
                    "measured_hit_ratio": st.get("measured_hit_ratio"),
                }
                for name, st in (workingset.get("scopes") or {}).items()
            },
        }

    audit = debug.get("audit") or {}
    if audit.get("joined") or audit.get("divergence") \
            or audit.get("unjoined_outcomes"):
        # Ground-truth audit plane: how honest the routing scores were
        # (calibration), what routing the fleet regrets, and which pods'
        # advertised index currently disagrees with engine truth.
        pods = audit.get("pods") or {}
        divergence = audit.get("divergence") or {}
        degraded = sorted(
            set(divergence)
            | {pod for pod, st in pods.items()
               if (st.get("stale_mispredicted_blocks") or 0)
               > (st.get("fresh_mispredicted_blocks") or 0)
               and (st.get("mean_abs_error_blocks") or 0) > 0.5})
        out["audit"] = {
            "joined": audit.get("joined"),
            "unjoined_outcomes": audit.get("unjoined_outcomes"),
            "mean_abs_error_blocks": audit.get("mean_abs_error_blocks"),
            "regrets": audit.get("regrets"),
            "regret_rate": audit.get("regret_rate"),
            "calibration": {
                pod: {
                    "joins": st.get("joins"),
                    "mean_abs_error_blocks": st.get("mean_abs_error_blocks"),
                    "calibration_ratio": st.get("calibration_ratio"),
                    "regrets": st.get("regrets"),
                    "stale_mispredicted_blocks":
                        st.get("stale_mispredicted_blocks"),
                    "fresh_mispredicted_blocks":
                        st.get("fresh_mispredicted_blocks"),
                }
                for pod, st in pods.items()
            },
            "divergence": divergence,
            "degraded_pods": degraded,
        }

    anomaly = debug.get("anomaly") or {}
    if isinstance(anomaly, dict) and anomaly:
        # Robust-z anomaly sentinels over the fleet SLI series: the
        # earliest gray-failure signal (fires before a burn-rate window
        # fills) and the trigger feed for the incident black-box.
        out["anomaly"] = {
            "firing": sorted(
                name for name, st in anomaly.items()
                if isinstance(st, dict) and st.get("firing")),
            "sentinels": {
                name: {
                    "firing": st.get("firing"),
                    "fires": st.get("fires"),
                    "last_z": st.get("last_z"),
                    "last_value": st.get("last_value"),
                    "samples": st.get("samples"),
                }
                for name, st in anomaly.items() if isinstance(st, dict)
            },
        }

    incident = debug.get("incident") or {}
    if incident:
        # Incident black-box: what got captured, what got suppressed
        # (cooldown/inflight), and the per-pod clock offsets every
        # bundle's merged timeline is corrected with.
        out["incidents"] = {
            "enabled": incident.get("enabled"),
            "directory": incident.get("directory"),
            "opened_total": incident.get("opened_total"),
            "capturing": incident.get("capturing"),
            "suppressed": incident.get("suppressed"),
            "recent": [
                {
                    "seq": r.get("seq"),
                    "trigger": r.get("trigger"),
                    "pods_captured": r.get("pods_captured"),
                    "pods_total": r.get("pods_total"),
                    "path": r.get("path"),
                }
                for r in incident.get("recent") or []
            ],
            "clock_offsets": incident.get("offsets"),
        }

    membership = debug.get("membership") or {}
    if membership:
        # Epoch-fenced membership plane: where the pod thinks topology
        # is, every lease's age/runway, and which traffic it fenced —
        # the first place to look when writes silently stop landing.
        leases = membership.get("leases") or {}
        out["membership"] = {
            "epoch": membership.get("epoch"),
            "fence_mode": membership.get("fence_mode"),
            "leases": {
                pod: {
                    "epoch": st.get("epoch"),
                    "age_s": st.get("age_s"),
                    "remaining_s": st.get("remaining_s"),
                    "lapsed": st.get("lapsed"),
                }
                for pod, st in leases.items()
            },
            "lapsed_pods": sorted(
                pod for pod, st in leases.items() if st.get("lapsed")),
            "fence_rejections": membership.get("rejections"),
            "fence_flagged": membership.get("flagged"),
            "recent_rejections": membership.get("recent_rejections"),
        }

    out["alerts"] = alerts
    out["slo"] = slo
    return out


def multi_snapshot(targets: list[str], timeout: float = 5.0,
                   fleet: bool = False) -> dict:
    """Snapshot several pods into one report; unreachable pods degrade to
    an ``{"error": ...}`` stanza instead of aborting the whole report."""
    report: dict = {"targets": {}}
    reachable = 0
    for spec in targets:
        host, _, port_s = spec.rpartition(":")
        host = host or "127.0.0.1"
        try:
            port = int(port_s)
        except ValueError:
            report["targets"][spec] = {"error": f"bad target spec {spec!r}"}
            continue
        try:
            report["targets"][spec] = snapshot(host, port, timeout, fleet=fleet)
            reachable += 1
        except OSError as e:
            report["targets"][spec] = {
                "error": f"cannot reach {host}:{port}: {e}"}
    report["reachable"] = reachable
    report["unreachable"] = len(targets) - reachable
    return report


def _watch_stats(report: dict) -> dict:
    """Counters the watch loop turns into delta lines, from one snapshot
    (single-target) or a multi_snapshot report."""
    stats = {"score_calls": 0.0, "staleness_s": None, "alerts": 0,
             "reachable": 1, "targets": 1}
    if "targets" in report and isinstance(report["targets"], dict):
        stats["reachable"] = report.get("reachable", 0)
        stats["targets"] = len(report["targets"])
        per = [t for t in report["targets"].values()
               if isinstance(t, dict) and "error" not in t]
    else:
        per = [report]
    staleness = []
    for rep in per:
        debug = rep.get("debug") if isinstance(rep.get("debug"), dict) else {}
        ledger = debug.get("ledger") or {}
        stats["score_calls"] += ledger.get("score_calls") or 0
        lag = debug.get("lag") or {}
        if lag.get("staleness_s") is not None:
            staleness.append(lag["staleness_s"])
        fleet = rep.get("fleet") or {}
        stats["alerts"] += len(fleet.get("alerts") or [])
    if staleness:
        stats["staleness_s"] = max(staleness)
    return stats


def watch_loop(args, specs) -> int:
    """``--watch N``: re-poll every N seconds, print one delta line per
    round (score rate, ingest lag, firing alerts) instead of the full
    JSON snapshot — 'is it moving?' without a dashboard."""
    prev = None
    try:
        while True:
            try:
                if specs is not None:
                    report = multi_snapshot(specs, args.timeout,
                                            fleet=args.fleet)
                else:
                    report = snapshot(args.host, args.port, args.timeout,
                                      fleet=args.fleet)
            except OSError as e:
                print(f"[{time.strftime('%H:%M:%S')}] unreachable: {e}",
                      flush=True)
                time.sleep(args.watch)
                continue
            cur = _watch_stats(report)
            line = [time.strftime("[%H:%M:%S]")]
            if prev is not None:
                rate = (cur["score_calls"] - prev["score_calls"]) / args.watch
                line.append(f"score_rate={max(rate, 0.0):.1f}/s")
            else:
                line.append(f"score_calls={cur['score_calls']:.0f}")
            if cur["staleness_s"] is not None:
                line.append(f"ingest_lag={cur['staleness_s']:.3f}s")
            line.append(f"alerts={cur['alerts']}")
            if cur["targets"] > 1:
                line.append(f"reachable={cur['reachable']}/{cur['targets']}")
            print(" ".join(line), flush=True)
            prev = cur
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0


def firing_alerts(report: dict) -> list[dict]:
    """Every firing SLO alert across a single- or multi-target report
    (the ``fleet.alerts`` stanzas), each tagged with its target."""
    found: list[dict] = []
    if "targets" in report and isinstance(report.get("targets"), dict):
        per = [(spec, t) for spec, t in report["targets"].items()
               if isinstance(t, dict) and "error" not in t]
    else:
        per = [(report.get("endpoint", ""), report)]
    for spec, rep in per:
        fleet = rep.get("fleet") or {}
        for alert in fleet.get("alerts") or []:
            entry = dict(alert)
            entry["target"] = spec
            found.append(entry)
    return found


def _emit(report: dict, args, alerts: list[dict]) -> None:
    """Print the report — full JSON, or (``--quiet``) one status line
    built for scripts and CI gates."""
    if args.quiet:
        if alerts:
            names = ", ".join(
                f"{a.get('slo')}:{a.get('severity')}" for a in alerts)
            line = f"kvdiag: {len(alerts)} alert(s) firing [{names}]"
            degraded = sorted({
                pod
                for rep in ([report] if "targets" not in report
                            else report.get("targets", {}).values())
                if isinstance(rep, dict)
                for pod in ((rep.get("fleet") or {}).get("audit") or {})
                .get("degraded_pods") or []})
            if degraded:
                line += f" degraded_pods={','.join(degraded)}"
        else:
            line = "kvdiag: ok"
        print(line)
        return
    payload = json.dumps(report, indent=2, default=repr)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")
    else:
        print(payload)


def incident_report(path: str, timeline_limit: int = 40,
                    out=sys.stdout) -> int:
    """``--incident <bundle>``: offline black-box viewer.

    Loads one incident bundle (no running pod needed), verifies its CRC
    footer, and prints the triage story: capture header, per-pod clock
    offsets, alerts/anomalies firing at capture, the dominant
    critical-path segment, the first-anomalous-pod heuristic, and the
    skew-corrected merged timeline tail.
    """
    try:
        from llmd_kv_cache_tpu.telemetry import incident as inc
    except ImportError:
        import os
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        try:
            from llmd_kv_cache_tpu.telemetry import incident as inc
        except ImportError as e:
            print(f"kvdiag --incident needs the llmd_kv_cache_tpu package "
                  f"for the bundle codec: {e}", file=sys.stderr)
            return 2
    try:
        doc = inc.load_bundle(path)
    except (OSError, inc.IncidentBundleError) as e:
        print(f"kvdiag: cannot read incident bundle {path}: {e}",
              file=sys.stderr)
        return 2

    def emit(line: str = "") -> None:
        print(line, file=out)

    opened = doc.get("opened_wall")
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S",
                          time.localtime(opened)) if opened else "?"
    emit(f"incident #{doc.get('seq', '?')}  trigger={doc.get('trigger', '?')}"
         f"  opened={stamp}  capture={doc.get('capture_seconds', '?')}s")
    reason = doc.get("reason") or {}
    if reason:
        emit(f"  reason: {json.dumps(reason, default=repr)}")

    pods = doc.get("pods") or {}
    reachable = sorted(p for p, ev in pods.items() if ev.get("reachable"))
    unreachable = sorted(set(pods) - set(reachable))
    emit(f"pods: {len(reachable)}/{len(pods)} captured"
         + (f"  unreachable={','.join(unreachable)}" if unreachable else ""))

    offsets = doc.get("offsets") or {}
    if offsets:
        emit("clock offsets (pod wall - collector wall; error <= rtt/2):")
        for pod in sorted(offsets):
            st = offsets[pod]
            emit(f"  {pod}: offset={st.get('offset_s'):+.6f}s "
                 f"rtt={st.get('rtt_s'):.6f}s age={st.get('age_s')}s")

    alerts = inc.firing_alerts(doc)
    if alerts:
        emit("firing at capture:")
        for a in alerts:
            if a.get("kind") == "slo":
                emit(f"  slo {a.get('name')}: {a.get('severity')}")
            else:
                emit(f"  anomaly {a.get('name')}: z={a.get('z')} "
                     f"value={a.get('value')}")
    else:
        emit("firing at capture: none")

    seg = inc.dominant_segment(doc)
    if seg:
        emit(f"dominant segment: {seg.get('name')} "
             f"({seg.get('process')}) self_time={seg.get('self_time_s')}s "
             f"trace={seg.get('trace_id')}")

    suspect = inc.first_anomalous_pod(doc)
    if suspect:
        emit(f"first anomalous pod: {suspect['pod']} "
             f"(sentinel={suspect['sentinel']} round={suspect['round']} "
             f"z={suspect['z']} value={suspect['value']})")
    else:
        emit("first anomalous pod: none identified")

    timeline = inc.merged_timeline(doc, limit=timeline_limit)
    emit(f"timeline (skew-corrected, last {len(timeline)} events):")
    for ev in timeline:
        detail = ev.get("detail")
        tail = f"  {json.dumps(detail, default=repr)}" if detail else ""
        emit(f"  {ev['ts']:.6f}  {ev['pod']:<16} {ev['source']:<10} "
             f"{ev['label']}{tail}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=None,
                        help="the indexer's --admin-port (or --metrics-port)")
    parser.add_argument("--targets", default=None,
                        help="comma-separated host:port list; unreachable "
                             "pods degrade to an error stanza per pod")
    parser.add_argument("--fleet", action="store_true",
                        help="summarise the telemetry collector's surfaces "
                             "(retained traces, rollup percentiles, SLO "
                             "burn state) into a top-level fleet section")
    parser.add_argument("--quiet", action="store_true",
                        help="print one status line instead of the JSON "
                             "report (pairs with the exit code: 0 ok, 2 "
                             "unreachable, 3 SLO alert firing)")
    parser.add_argument("--timeout", type=float, default=5.0)
    parser.add_argument("--watch", type=float, default=None, metavar="N",
                        help="re-poll every N seconds and print delta "
                             "lines (score rate, ingest lag, firing "
                             "alerts) instead of a one-shot snapshot")
    parser.add_argument("--out", default=None,
                        help="write the JSON report here instead of stdout")
    parser.add_argument("--incident", default=None, metavar="BUNDLE",
                        help="offline mode: print the triage story of one "
                             "incident black-box bundle (skew-corrected "
                             "timeline, firing alerts, dominant segment, "
                             "first anomalous pod) — no pod needed")
    parser.add_argument("--timeline-limit", type=int, default=40,
                        help="with --incident: events of merged timeline "
                             "tail to print (0 = all)")
    args = parser.parse_args(argv)
    if args.incident is not None:
        return incident_report(args.incident, args.timeline_limit)
    if (args.port is None) == (args.targets is None):
        parser.error("exactly one of --port / --targets is required")
    if args.watch is not None and args.watch <= 0:
        parser.error("--watch needs a positive interval")

    if args.watch is not None:
        specs = None
        if args.targets is not None:
            specs = [t.strip() for t in args.targets.split(",") if t.strip()]
        return watch_loop(args, specs)

    if args.targets is not None:
        specs = [t.strip() for t in args.targets.split(",") if t.strip()]
        report = multi_snapshot(specs, args.timeout, fleet=args.fleet)
        alerts = firing_alerts(report) if args.fleet else []
        _emit(report, args, alerts)
        if not report["reachable"]:
            return 2
        # CI/cron gate: --fleet exits nonzero while any SLO alert is
        # firing, so "kvdiag --fleet --quiet || page" just works.
        return 3 if alerts else 0

    try:
        report = snapshot(args.host, args.port, args.timeout, fleet=args.fleet)
    except OSError as e:
        print(json.dumps({"error": f"cannot reach {args.host}:{args.port}: {e}"}),
              file=sys.stderr)
        return 2

    alerts = firing_alerts(report) if args.fleet else []
    _emit(report, args, alerts)
    return 3 if alerts else 0


if __name__ == "__main__":
    sys.exit(main())
